#!/usr/bin/env python
"""Render the markdown doc tree to HTML (reference parity: docs/Makefile +
Sphinx tree, /root/reference/docs/. Sphinx is not in this image, so this
uses the stdlib-adjacent `markdown` package — same role: a rendered,
navigable doc build from the committed sources).

Usage: python docs/build_docs.py [outdir]   (default docs/_build/html)
Or: make -C docs html
"""

from __future__ import annotations

import os
import re
import sys

try:
    import markdown
except ImportError:  # minimal fallback: readable <pre> pages, no deps
    markdown = None

DOCS = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(DOCS)

PAGES = [("index", os.path.join(ROOT, "README.md"), "Overview"),
         ("architecture", os.path.join(DOCS, "architecture.md"),
          "Architecture"),
         ("migration", os.path.join(DOCS, "migration.md"),
          "Migration from FlexFlow"),
         ("resilience", os.path.join(DOCS, "resilience.md"),
          "Fault tolerance & elastic recovery"),
         ("serving", os.path.join(DOCS, "serving.md"),
          "Serving (continuous batching, prefix cache, fleet router, "
          "quantized tier, disaggregated fleet + tiered cache, "
          "sampling + multi-tenant LoRA, rolling deployment, "
          "elastic fleet + preemption)"),
         ("performance", os.path.join(DOCS, "performance.md"),
          "Performance (host + in-graph overlap, Pallas kernel tier, "
          "search v2: persistent cost DB + multi-objective search)"),
         ("observability", os.path.join(DOCS, "observability.md"),
          "Observability (metrics registry, per-request tracing, "
          "Prometheus/JSON export)"),
         ("analysis", os.path.join(DOCS, "analysis.md"),
          "fflint static analysis (strategy passes + ffsan "
          "concurrency/trace-stability passes & runtime sanitizer)"),
         ("install", os.path.join(ROOT, "INSTALL.md"), "Install")]
# every round-notes file, newest first (numeric: round10 > round9)
_rounds = []
for fn in os.listdir(DOCS):
    m = re.match(r"round(\d+)_notes\.md$", fn)
    if m:
        _rounds.append((int(m.group(1)), fn))
for n_round, fn in sorted(_rounds, reverse=True):
    PAGES.append((f"round{n_round}", os.path.join(DOCS, fn),
                  f"Round {n_round} notes"))

TEMPLATE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title} — flexflow_tpu</title>
<style>
body {{ font: 15px/1.5 system-ui, sans-serif; max-width: 60rem;
       margin: 2rem auto; padding: 0 1rem; color: #1a1a1a; }}
nav {{ border-bottom: 1px solid #ddd; padding-bottom: .5rem;
      margin-bottom: 1.5rem; }}
nav a {{ margin-right: 1rem; }}
pre {{ background: #f6f8fa; padding: .8rem; overflow-x: auto; }}
code {{ background: #f6f8fa; padding: .1rem .25rem; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #ccc; padding: .3rem .6rem; }}
</style></head><body>
<nav>{nav}</nav>
{body}
</body></html>
"""


def build(outdir: str) -> int:
    os.makedirs(outdir, exist_ok=True)
    nav = " ".join(f'<a href="{slug}.html">{title}</a>'
                   for slug, _, title in PAGES)
    n = 0
    for slug, path, title in PAGES:
        if not os.path.exists(path):
            print(f"skip {path} (missing)", file=sys.stderr)
            continue
        with open(path, encoding="utf-8") as f:
            text = f.read()
        if markdown is not None:
            body = markdown.markdown(
                text, extensions=["tables", "fenced_code"])
        else:
            import html

            body = f"<pre>{html.escape(text)}</pre>"
        with open(os.path.join(outdir, f"{slug}.html"), "w",
                  encoding="utf-8") as f:
            f.write(TEMPLATE.format(title=title, nav=nav, body=body))
        n += 1
    print(f"built {n} pages -> {outdir}")
    return 0


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(DOCS, "_build", "html")
    sys.exit(build(out))
