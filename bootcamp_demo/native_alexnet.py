"""Bootcamp demo 3/3: AlexNet-CIFAR10 through the native builder API with an
explicit train loop (reference: bootcamp_demo/native_cnn_cifar10.py +
examples/cpp/AlexNet/alexnet.cc:102-118 loop structure)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.models.cnn import alexnet_cifar10


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x, out = alexnet_cifar10(ff, cfg.batch_size)
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    loader_x = SingleDataLoader(ff, x, x_train)
    loader_y = SingleDataLoader(ff, ff.label_tensor, y_train)
    ff.init_layers()

    # explicit loop: next_batch / forward / zero / backward / update
    num_batches = min(loader_x.num_batches, loader_y.num_batches)
    for epoch in range(cfg.epochs):
        loader_x.reset()
        loader_y.reset()
        for it in range(num_batches):
            batch = ff._stage_batch()
            loss, mets = ff._run_train_step(batch)
            if it % 50 == 0:
                print(f"epoch {epoch} iter {it}: loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
