"""Bootcamp demo 2/3: AlexNet-CIFAR10 defined in PyTorch, imported via
torch.fx (reference: bootcamp_demo/torch_cnn_cifar10.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.torch import PyTorchModel, torch_to_flexflow


class AlexNetCifar(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 5, padding=2)
        self.relu1 = nn.ReLU()
        self.pool1 = nn.MaxPool2d(2)
        self.conv2 = nn.Conv2d(64, 192, 5, padding=2)
        self.relu2 = nn.ReLU()
        self.pool2 = nn.MaxPool2d(2)
        self.conv3 = nn.Conv2d(192, 256, 3, padding=1)
        self.relu3 = nn.ReLU()
        self.pool3 = nn.MaxPool2d(2)
        self.flat = nn.Flatten()
        self.fc1 = nn.Linear(256 * 4 * 4, 512)
        self.relu4 = nn.ReLU()
        self.fc2 = nn.Linear(512, 10)

    def forward(self, x):
        x = self.pool1(self.relu1(self.conv1(x)))
        x = self.pool2(self.relu2(self.conv2(x)))
        x = self.pool3(self.relu3(self.conv3(x)))
        return self.fc2(self.relu4(self.fc1(self.flat(x))))


def main():
    torch_to_flexflow(AlexNetCifar(), "/tmp/alexnet_cifar.ff")
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="x")
    outs = PyTorchModel("/tmp/alexnet_cifar.ff").apply(ff, [x])
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    SingleDataLoader(ff, x, x_train)
    SingleDataLoader(ff, ff.label_tensor, y_train)
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
