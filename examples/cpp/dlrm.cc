/* DLRM through the C API (reference: examples/cpp/DLRM/dlrm.cc:77-210 —
 * sparse features -> per-table embeddings, dense features -> bottom MLP,
 * concat -> top MLP -> scalar CTR prediction, MSE loss).
 *
 * Usage: ./dlrm [batch_size] [num_tables] [embedding_entries] [num_samples]
 * Synthetic data (the reference synthesizes too when no dataset given). */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 64;
  int num_tables = argc > 2 ? atoi(argv[2]) : 4;
  int entries = argc > 3 ? atoi(argv[3]) : 1000;
  int num_samples = argc > 4 ? atoi(argv[4]) : 256;
  const int embed_dim = 64, dense_dim = 16;

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);
  fft_config_t cfg = fft_config_create(batch_size, 1, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  fft_model_t ff = fft_model_create(cfg);
  CHECK(ff.impl);

  /* bottom MLP over dense features (dlrm.cc create_mlp) */
  int dense_dims[2] = {batch_size, dense_dim};
  fft_tensor_t dense_in =
      fft_model_create_tensor(ff, dense_dims, 2, FFT_DT_FLOAT, "dense_input");
  CHECK(dense_in.impl);
  fft_tensor_t bot = fft_model_add_dense(ff, dense_in, embed_dim,
                                         FFT_AC_MODE_RELU, 1, "bot1");
  bot = fft_model_add_dense(ff, bot, embed_dim, FFT_AC_MODE_RELU, 1, "bot2");

  /* per-table embeddings over sparse features (dlrm.cc create_emb) */
  std::vector<fft_tensor_t> features;
  std::vector<fft_tensor_t> sparse_ins;
  for (int i = 0; i < num_tables; ++i) {
    int sdims[2] = {batch_size, 1};
    std::string in_name = "sparse_" + std::to_string(i);
    fft_tensor_t s =
        fft_model_create_tensor(ff, sdims, 2, FFT_DT_INT32, in_name.c_str());
    CHECK(s.impl);
    sparse_ins.push_back(s);
    std::string emb_name = "emb_" + std::to_string(i);
    fft_tensor_t e = fft_model_add_embedding(ff, s, entries, embed_dim,
                                             FFT_AGGR_MODE_SUM,
                                             emb_name.c_str());
    CHECK(e.impl);
    features.push_back(e);
  }
  features.push_back(bot);

  /* interaction = concat (reference interact_features "cat" mode) */
  fft_tensor_t inter = fft_model_add_concat(ff, features.data(),
                                            (int)features.size(), 1, "concat");
  CHECK(inter.impl);

  fft_tensor_t top = fft_model_add_dense(ff, inter, 128, FFT_AC_MODE_RELU, 1,
                                         "top1");
  top = fft_model_add_dense(ff, top, 64, FFT_AC_MODE_RELU, 1, "top2");
  top = fft_model_add_dense(ff, top, 1, FFT_AC_MODE_NONE, 1, "out");
  CHECK(top.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.0, 0, 0.0);
  fft_metrics_type metrics[1] = {FFT_METRICS_MEAN_SQUARED_ERROR};
  CHECK(fft_model_compile(ff, opt, FFT_LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                          metrics, 1, top) == 0);

  /* synthetic click data */
  srand(42);
  std::vector<float> xdense((size_t)num_samples * dense_dim);
  for (auto &v : xdense) v = (float)rand() / RAND_MAX - 0.5f;
  std::vector<float> y((size_t)num_samples);
  for (auto &v : y) v = (float)(rand() % 2);

  fft_dataloader_t dl_dense =
      fft_single_dataloader_create(ff, dense_in, xdense.data(), num_samples);
  CHECK(dl_dense.impl);
  std::vector<std::vector<int>> xsparse(num_tables);
  std::vector<fft_dataloader_t> dl_sparse;
  for (int i = 0; i < num_tables; ++i) {
    xsparse[i].resize(num_samples);
    for (auto &v : xsparse[i]) v = rand() % entries;
    fft_dataloader_t d = fft_single_dataloader_create(
        ff, sparse_ins[i], xsparse[i].data(), num_samples);
    CHECK(d.impl);
    dl_sparse.push_back(d);
  }
  fft_tensor_t label = fft_model_get_label_tensor(ff);
  fft_dataloader_t dl_y =
      fft_single_dataloader_create(ff, label, y.data(), num_samples);
  CHECK(dl_y.impl);

  CHECK(fft_model_init_layers(ff) == 0);

  int num_batches = fft_dataloader_num_batches(dl_dense);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(ff) == 0);
    CHECK(fft_model_forward(ff) == 0);
    CHECK(fft_model_zero_gradients(ff) == 0);
    CHECK(fft_model_backward(ff) == 0);
    CHECK(fft_model_update(ff) == 0);
  }
  /* loss fetch blocks on the device; keep it inside the timed region */
  float loss = fft_model_get_last_loss(ff);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("dlrm: %d batches, loss=%.4f, THROUGHPUT = %.2f samples/s\n",
         num_batches, loss, dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));

  fft_dataloader_destroy(dl_dense);
  for (auto &d : dl_sparse) fft_dataloader_destroy(d);
  fft_dataloader_destroy(dl_y);
  fft_optimizer_destroy(opt);
  fft_model_destroy(ff);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("dlrm_c: SUCCESS\n");
  return 0;
}
