/* Transformer encoder through the C API (reference:
 * examples/cpp/Transformer/transformer.cc:30-140 — N blocks of
 * multi-head attention + residual + two dense layers + residual on 3D
 * (batch, seq, hidden) tensors, MSE loss against random targets).
 *
 * Usage: ./transformer [batch_size] [layers] [seq] [hidden] [heads] */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 16;
  int layers = argc > 2 ? atoi(argv[2]) : 2;
  int seq = argc > 3 ? atoi(argv[3]) : 32;
  int hidden = argc > 4 ? atoi(argv[4]) : 64;
  int heads = argc > 5 ? atoi(argv[5]) : 4;
  int num_samples = batch_size * 4;

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);
  fft_config_t cfg = fft_config_create(batch_size, 1, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  fft_model_t ff = fft_model_create(cfg);
  CHECK(ff.impl);

  int dims[3] = {batch_size, seq, hidden};
  fft_tensor_t input =
      fft_model_create_tensor(ff, dims, 3, FFT_DT_FLOAT, "input");
  CHECK(input.impl);

  /* attention + residual + FFN + residual per block
   * (reference create_attention_encoder, transformer.cc:30-46) */
  fft_tensor_t t = input;
  for (int i = 0; i < layers; ++i) {
    std::string a = "attn_" + std::to_string(i);
    fft_tensor_t att = fft_model_add_multihead_attention(
        ff, t, t, t, hidden, heads, 0, a.c_str());
    CHECK(att.impl);
    std::string r1 = "res1_" + std::to_string(i);
    t = fft_model_add_add(ff, att, t, r1.c_str());
    std::string f1 = "ffn1_" + std::to_string(i);
    fft_tensor_t h = fft_model_add_dense(ff, t, hidden * 4, FFT_AC_MODE_RELU,
                                         1, f1.c_str());
    std::string f2 = "ffn2_" + std::to_string(i);
    h = fft_model_add_dense(ff, h, hidden, FFT_AC_MODE_NONE, 1, f2.c_str());
    std::string r2 = "res2_" + std::to_string(i);
    t = fft_model_add_add(ff, h, t, r2.c_str());
  }
  CHECK(t.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.0, 0, 0.0);
  fft_metrics_type metrics[1] = {FFT_METRICS_MEAN_SQUARED_ERROR};
  CHECK(fft_model_compile(ff, opt, FFT_LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                          metrics, 1, t) == 0);

  srand(42);
  std::vector<float> x((size_t)num_samples * seq * hidden);
  std::vector<float> y((size_t)num_samples * seq * hidden);
  for (auto &v : x) v = (float)rand() / RAND_MAX - 0.5f;
  for (auto &v : y) v = (float)rand() / RAND_MAX - 0.5f;

  fft_dataloader_t dl_x =
      fft_single_dataloader_create(ff, input, x.data(), num_samples);
  CHECK(dl_x.impl);
  fft_tensor_t label = fft_model_get_label_tensor(ff);
  fft_dataloader_t dl_y =
      fft_single_dataloader_create(ff, label, y.data(), num_samples);
  CHECK(dl_y.impl);

  CHECK(fft_model_init_layers(ff) == 0);

  int num_batches = fft_dataloader_num_batches(dl_x);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(ff) == 0);
    CHECK(fft_model_forward(ff) == 0);
    CHECK(fft_model_zero_gradients(ff) == 0);
    CHECK(fft_model_backward(ff) == 0);
    CHECK(fft_model_update(ff) == 0);
  }
  /* loss fetch blocks on the device; keep it inside the timed region */
  float loss = fft_model_get_last_loss(ff);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("transformer: %d batches, loss=%.4f, THROUGHPUT = %.2f samples/s\n",
         num_batches, loss, dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));

  fft_dataloader_destroy(dl_x);
  fft_dataloader_destroy(dl_y);
  fft_optimizer_destroy(opt);
  fft_model_destroy(ff);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("transformer_c: SUCCESS\n");
  return 0;
}
