/* InceptionV3 through the C API (reference: examples/cpp/InceptionV3/ —
 * the branchy graph where operator placement pays off: each inception
 * module concatenates 3-4 convolution branches that the strategy search
 * can place on disjoint device blocks).
 *
 * Usage: ./inception [batch_size] [epochs] [num_samples] [budget]
 * budget > 0 runs the MCMC search and exports inception_strategy.txt
 * (reference --budget/--export flow). Synthetic data at 3x299x299 by
 * default (the real InceptionV3 input); pass a smaller size via argv[5].
 */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

static fft_model_t FF;
static int conv_id = 0;

/* conv + BN(relu) — the InceptionV3 building block */
static fft_tensor_t conv_bn(fft_tensor_t in, int out_ch, int kh, int kw,
                            int sh, int sw, int ph, int pw) {
  char name[64];
  snprintf(name, sizeof(name), "conv_%d", conv_id);
  fft_tensor_t t = fft_model_add_conv2d(FF, in, out_ch, kh, kw, sh, sw, ph,
                                        pw, FFT_AC_MODE_NONE, 1, 0, name);
  snprintf(name, sizeof(name), "bn_%d", conv_id);
  ++conv_id;
  return fft_model_add_batch_norm(FF, t, 1, name);
}

/* reference InceptionA (inception.cc InceptionA): 1x1 / 5x5 / 3x3dbl /
 * pool branches */
static fft_tensor_t inception_a(fft_tensor_t in, int pool_ch, int mod) {
  fft_tensor_t b1 = conv_bn(in, 64, 1, 1, 1, 1, 0, 0);
  fft_tensor_t b2 = conv_bn(in, 48, 1, 1, 1, 1, 0, 0);
  b2 = conv_bn(b2, 64, 5, 5, 1, 1, 2, 2);
  fft_tensor_t b3 = conv_bn(in, 64, 1, 1, 1, 1, 0, 0);
  b3 = conv_bn(b3, 96, 3, 3, 1, 1, 1, 1);
  b3 = conv_bn(b3, 96, 3, 3, 1, 1, 1, 1);
  char name[64];
  snprintf(name, sizeof(name), "incA%d_pool", mod);
  fft_tensor_t b4 = fft_model_add_pool2d(FF, in, 3, 3, 1, 1, 1, 1,
                                         FFT_POOL_AVG, name);
  b4 = conv_bn(b4, pool_ch, 1, 1, 1, 1, 0, 0);
  fft_tensor_t branches[4] = {b1, b2, b3, b4};
  snprintf(name, sizeof(name), "incA%d_cat", mod);
  return fft_model_add_concat(FF, branches, 4, 1, name);
}

/* reference InceptionB: grid reduction 35->17 */
static fft_tensor_t inception_b(fft_tensor_t in, int mod) {
  fft_tensor_t b1 = conv_bn(in, 384, 3, 3, 2, 2, 0, 0);
  fft_tensor_t b2 = conv_bn(in, 64, 1, 1, 1, 1, 0, 0);
  b2 = conv_bn(b2, 96, 3, 3, 1, 1, 1, 1);
  b2 = conv_bn(b2, 96, 3, 3, 2, 2, 0, 0);
  char name[64];
  snprintf(name, sizeof(name), "incB%d_pool", mod);
  fft_tensor_t b3 = fft_model_add_pool2d(FF, in, 3, 3, 2, 2, 0, 0,
                                         FFT_POOL_MAX, name);
  fft_tensor_t branches[3] = {b1, b2, b3};
  snprintf(name, sizeof(name), "incB%d_cat", mod);
  return fft_model_add_concat(FF, branches, 3, 1, name);
}

/* reference InceptionC: factorized 7x7 branches */
static fft_tensor_t inception_c(fft_tensor_t in, int ch7, int mod) {
  fft_tensor_t b1 = conv_bn(in, 192, 1, 1, 1, 1, 0, 0);
  fft_tensor_t b2 = conv_bn(in, ch7, 1, 1, 1, 1, 0, 0);
  b2 = conv_bn(b2, ch7, 1, 7, 1, 1, 0, 3);
  b2 = conv_bn(b2, 192, 7, 1, 1, 1, 3, 0);
  fft_tensor_t b3 = conv_bn(in, ch7, 1, 1, 1, 1, 0, 0);
  b3 = conv_bn(b3, ch7, 7, 1, 1, 1, 3, 0);
  b3 = conv_bn(b3, ch7, 1, 7, 1, 1, 0, 3);
  b3 = conv_bn(b3, ch7, 7, 1, 1, 1, 3, 0);
  b3 = conv_bn(b3, 192, 1, 7, 1, 1, 0, 3);
  char name[64];
  snprintf(name, sizeof(name), "incC%d_pool", mod);
  fft_tensor_t b4 = fft_model_add_pool2d(FF, in, 3, 3, 1, 1, 1, 1,
                                         FFT_POOL_AVG, name);
  b4 = conv_bn(b4, 192, 1, 1, 1, 1, 0, 0);
  fft_tensor_t branches[4] = {b1, b2, b3, b4};
  snprintf(name, sizeof(name), "incC%d_cat", mod);
  return fft_model_add_concat(FF, branches, 4, 1, name);
}

/* reference InceptionD: grid reduction 17->8 */
static fft_tensor_t inception_d(fft_tensor_t in, int mod) {
  fft_tensor_t b1 = conv_bn(in, 192, 1, 1, 1, 1, 0, 0);
  b1 = conv_bn(b1, 320, 3, 3, 2, 2, 0, 0);
  fft_tensor_t b2 = conv_bn(in, 192, 1, 1, 1, 1, 0, 0);
  b2 = conv_bn(b2, 192, 1, 7, 1, 1, 0, 3);
  b2 = conv_bn(b2, 192, 7, 1, 1, 1, 3, 0);
  b2 = conv_bn(b2, 192, 3, 3, 2, 2, 0, 0);
  char name[64];
  snprintf(name, sizeof(name), "incD%d_pool", mod);
  fft_tensor_t b3 = fft_model_add_pool2d(FF, in, 3, 3, 2, 2, 0, 0,
                                         FFT_POOL_MAX, name);
  fft_tensor_t branches[3] = {b1, b2, b3};
  snprintf(name, sizeof(name), "incD%d_cat", mod);
  return fft_model_add_concat(FF, branches, 3, 1, name);
}

/* reference InceptionE: the widest module (8x8 grid) */
static fft_tensor_t inception_e(fft_tensor_t in, int mod) {
  fft_tensor_t b1 = conv_bn(in, 320, 1, 1, 1, 1, 0, 0);
  fft_tensor_t b2 = conv_bn(in, 384, 1, 1, 1, 1, 0, 0);
  fft_tensor_t b2a = conv_bn(b2, 384, 1, 3, 1, 1, 0, 1);
  fft_tensor_t b2b = conv_bn(b2, 384, 3, 1, 1, 1, 1, 0);
  char name[64];
  fft_tensor_t pair1[2] = {b2a, b2b};
  snprintf(name, sizeof(name), "incE%d_cat2", mod);
  b2 = fft_model_add_concat(FF, pair1, 2, 1, name);
  fft_tensor_t b3 = conv_bn(in, 448, 1, 1, 1, 1, 0, 0);
  b3 = conv_bn(b3, 384, 3, 3, 1, 1, 1, 1);
  fft_tensor_t b3a = conv_bn(b3, 384, 1, 3, 1, 1, 0, 1);
  fft_tensor_t b3b = conv_bn(b3, 384, 3, 1, 1, 1, 1, 0);
  fft_tensor_t pair2[2] = {b3a, b3b};
  snprintf(name, sizeof(name), "incE%d_cat3", mod);
  b3 = fft_model_add_concat(FF, pair2, 2, 1, name);
  snprintf(name, sizeof(name), "incE%d_pool", mod);
  fft_tensor_t b4 = fft_model_add_pool2d(FF, in, 3, 3, 1, 1, 1, 1,
                                         FFT_POOL_AVG, name);
  b4 = conv_bn(b4, 192, 1, 1, 1, 1, 0, 0);
  fft_tensor_t branches[4] = {b1, b2, b3, b4};
  snprintf(name, sizeof(name), "incE%d_cat", mod);
  return fft_model_add_concat(FF, branches, 4, 1, name);
}

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 8;
  int epochs = argc > 2 ? atoi(argv[2]) : 1;
  int num_samples = argc > 3 ? atoi(argv[3]) : 16;
  int budget = argc > 4 ? atoi(argv[4]) : 0;
  int image_size = argc > 5 ? atoi(argv[5]) : 299;
  int classes = 10;

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);
  fft_config_t cfg = fft_config_create(batch_size, epochs, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  if (budget > 0) {
    fft_config_set_search_budget(cfg, budget);
    fft_config_set_export_strategy_file(cfg, "inception_strategy.txt");
  }
  printf("inception_v3: batch=%d epochs=%d image=%d devices=%d budget=%d\n",
         batch_size, epochs, image_size, fft_config_get_num_devices(cfg),
         budget);

  FF = fft_model_create(cfg);
  CHECK(FF.impl);

  int input_dims[4] = {batch_size, 3, image_size, image_size};
  fft_tensor_t input = fft_model_create_tensor(FF, input_dims, 4,
                                               FFT_DT_FLOAT, "input");
  CHECK(input.impl);

  /* stem (reference inception.cc top_level_task) */
  fft_tensor_t t = conv_bn(input, 32, 3, 3, 2, 2, 0, 0);
  t = conv_bn(t, 32, 3, 3, 1, 1, 0, 0);
  t = conv_bn(t, 64, 3, 3, 1, 1, 1, 1);
  t = fft_model_add_pool2d(FF, t, 3, 3, 2, 2, 0, 0, FFT_POOL_MAX, "stem_p1");
  t = conv_bn(t, 80, 1, 1, 1, 1, 0, 0);
  t = conv_bn(t, 192, 3, 3, 1, 1, 0, 0);
  t = fft_model_add_pool2d(FF, t, 3, 3, 2, 2, 0, 0, FFT_POOL_MAX, "stem_p2");

  t = inception_a(t, 32, 0);
  t = inception_a(t, 64, 1);
  t = inception_a(t, 64, 2);
  t = inception_b(t, 0);
  t = inception_c(t, 128, 0);
  t = inception_c(t, 160, 1);
  t = inception_c(t, 160, 2);
  t = inception_c(t, 192, 3);
  t = inception_d(t, 0);
  t = inception_e(t, 0);
  t = inception_e(t, 1);

  int nd = fft_tensor_get_ndims(t);
  int dims[8];
  fft_tensor_get_dims(t, dims);
  CHECK(nd == 4);
  t = fft_model_add_pool2d(FF, t, dims[2], dims[3], 1, 1, 0, 0, FFT_POOL_AVG,
                           "gap");
  t = fft_model_add_flat(FF, t, "flat");
  t = fft_model_add_dense(FF, t, classes, FFT_AC_MODE_NONE, 1, "fc");
  CHECK(t.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.9, 0, 1e-4);
  fft_metrics_type metrics[1] = {FFT_METRICS_ACCURACY};
  fft_tensor_t no_final = {nullptr};
  CHECK(fft_model_compile(FF, opt, FFT_LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                          metrics, 1, no_final) == 0);

  std::vector<float> x((size_t)num_samples * 3 * image_size * image_size);
  std::vector<int> y((size_t)num_samples);
  srand(42);
  for (auto &v : x) v = (float)rand() / RAND_MAX - 0.5f;
  for (auto &v : y) v = rand() % classes;

  fft_dataloader_t dl_x =
      fft_single_dataloader_create(FF, input, x.data(), num_samples);
  CHECK(dl_x.impl);
  fft_tensor_t label = fft_model_get_label_tensor(FF);
  fft_dataloader_t dl_y =
      fft_single_dataloader_create(FF, label, y.data(), num_samples);
  CHECK(dl_y.impl);

  CHECK(fft_model_init_layers(FF) == 0);

  int num_batches = fft_dataloader_num_batches(dl_x);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(FF) == 0);
    CHECK(fft_model_forward(FF) == 0);
    CHECK(fft_model_zero_gradients(FF) == 0);
    CHECK(fft_model_backward(FF) == 0);
    CHECK(fft_model_update(FF) == 0);
  }
  float loss = fft_model_get_last_loss(FF);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("epoch: %d batches, loss=%.4f, THROUGHPUT = %.2f samples/s\n",
         num_batches, loss,
         dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));
  if (epochs > 1) CHECK(fft_model_fit(FF, epochs - 1) == 0);

  fft_dataloader_destroy(dl_x);
  fft_dataloader_destroy(dl_y);
  fft_tensor_destroy(label);
  fft_tensor_destroy(input);
  fft_optimizer_destroy(opt);
  fft_model_destroy(FF);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("inception_c: SUCCESS\n");
  return 0;
}
