/* AlexNet-on-CIFAR10 through the C API — the canonical C++ train loop
 * (reference: examples/cpp/AlexNet/alexnet.cc:34-130: build layers,
 * compile, attach dataloaders, init_layers, epochs x iterations of
 * next_batch/forward/zero/backward/update, throughput print).
 *
 * Usage: ./alexnet [batch_size] [epochs] [num_samples]
 * Runs on synthetic data; shapes are CIFAR10 (3x32x32, 10 classes). */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 64;
  int epochs = argc > 2 ? atoi(argv[2]) : 1;
  int num_samples = argc > 3 ? atoi(argv[3]) : 256;

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);

  fft_config_t cfg = fft_config_create(batch_size, epochs, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  printf("batch_size=%d epochs=%d devices=%d\n",
         fft_config_get_batch_size(cfg), fft_config_get_epochs(cfg),
         fft_config_get_num_devices(cfg));

  fft_model_t ff = fft_model_create(cfg);
  CHECK(ff.impl);

  int input_dims[4] = {batch_size, 3, 32, 32};
  fft_tensor_t input = fft_model_create_tensor(ff, input_dims, 4,
                                               FFT_DT_FLOAT, "input");
  CHECK(input.impl);

  fft_tensor_t t;
  t = fft_model_add_conv2d(ff, input, 64, 5, 5, 1, 1, 2, 2,
                           FFT_AC_MODE_RELU, 1, 1, "conv1");
  t = fft_model_add_pool2d(ff, t, 2, 2, 2, 2, 0, 0, FFT_POOL_MAX, "pool1");
  t = fft_model_add_conv2d(ff, t, 192, 5, 5, 1, 1, 2, 2, FFT_AC_MODE_RELU,
                           1, 1, "conv2");
  t = fft_model_add_pool2d(ff, t, 2, 2, 2, 2, 0, 0, FFT_POOL_MAX, "pool2");
  t = fft_model_add_conv2d(ff, t, 256, 3, 3, 1, 1, 1, 1, FFT_AC_MODE_RELU,
                           1, 1, "conv3");
  t = fft_model_add_pool2d(ff, t, 2, 2, 2, 2, 0, 0, FFT_POOL_MAX, "pool3");
  t = fft_model_add_flat(ff, t, "flat");
  t = fft_model_add_dense(ff, t, 512, FFT_AC_MODE_RELU, 1, "fc1");
  t = fft_model_add_dense(ff, t, 10, FFT_AC_MODE_NONE, 1, "fc2");
  CHECK(t.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.9, 0, 0.0);
  CHECK(opt.impl);
  fft_metrics_type metrics[1] = {FFT_METRICS_ACCURACY};
  fft_tensor_t no_final = {nullptr};
  CHECK(fft_model_compile(ff, opt, FFT_LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                          metrics, 1, no_final) == 0);

  /* synthetic dataset (reference app loads from file or synthesizes) */
  std::vector<float> x((size_t)num_samples * 3 * 32 * 32);
  std::vector<int> y((size_t)num_samples);
  srand(42);
  for (auto &v : x) v = (float)rand() / RAND_MAX - 0.5f;
  for (auto &v : y) v = rand() % 10;

  fft_dataloader_t dl_x =
      fft_single_dataloader_create(ff, input, x.data(), num_samples);
  CHECK(dl_x.impl);
  fft_tensor_t label = fft_model_get_label_tensor(ff);
  CHECK(label.impl);
  fft_dataloader_t dl_y =
      fft_single_dataloader_create(ff, label, y.data(), num_samples);
  CHECK(dl_y.impl);

  CHECK(fft_model_init_layers(ff) == 0);

  /* explicit verb loop for one epoch (parity with alexnet.cc:102-118),
   * then fit() for the remaining epochs */
  int num_batches = fft_dataloader_num_batches(dl_x);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(ff) == 0);
    CHECK(fft_model_forward(ff) == 0);
    CHECK(fft_model_zero_gradients(ff) == 0);
    CHECK(fft_model_backward(ff) == 0);
    CHECK(fft_model_update(ff) == 0);
  }
  /* fetching the loss blocks on the device (async dispatch) — must happen
   * inside the timed region or samples/s measures dispatch, not execution */
  float loss = fft_model_get_last_loss(ff);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("verb-loop epoch: %d batches, loss=%.4f, "
         "THROUGHPUT = %.2f samples/s\n",
         num_batches, loss,
         dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));

  if (epochs > 1) CHECK(fft_model_fit(ff, epochs - 1) == 0);

  /* weights IO round-trip (reference Parameter::get/set_weights) */
  int fc2_in = 512, fc2_out = 10;
  std::vector<float> w((size_t)fc2_in * fc2_out);
  CHECK(fft_model_get_weights(ff, "fc2", "kernel", w.data(),
                              (int64_t)w.size()) == 0);
  CHECK(fft_model_set_weights(ff, "fc2", "kernel", w.data(),
                              (int64_t)w.size()) == 0);
  printf("weights IO ok (fc2 kernel %dx%d)\n", fc2_in, fc2_out);

  fft_dataloader_destroy(dl_x);
  fft_dataloader_destroy(dl_y);
  fft_tensor_destroy(label);
  fft_tensor_destroy(input);
  fft_optimizer_destroy(opt);
  fft_model_destroy(ff);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("alexnet_c: SUCCESS\n");
  return 0;
}
