/* ResNet-50 through the C API (reference: examples/cpp/ResNet/resnet.cc —
 * the BASELINE north-star model: conv stem, 4 stages of bottleneck blocks
 * [3,4,6,3], global average pool, dense head).
 *
 * Usage: ./resnet [batch_size] [epochs] [num_samples] [image_size] [budget]
 * budget > 0 runs the MCMC strategy search at compile time and exports the
 * found strategy to resnet_strategy.txt (reference --budget/--export flow).
 * Runs on synthetic data; default shapes are ImageNet-at-64 (3x64x64, 10
 * classes) so the smoke run finishes quickly; pass 224 for the real config.
 */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

static int block_id = 0;

/* Bottleneck residual block (reference resnet.cc BottleneckBlock):
 * 1x1 reduce -> 3x3 -> 1x1 expand, each BN+ReLU (last BN no relu),
 * projection shortcut when stride != 1 or channels change, add + relu. */
static fft_tensor_t bottleneck(fft_model_t ff, fft_tensor_t in, int in_ch,
                               int mid_ch, int stride) {
  char name[64];
  int out_ch = mid_ch * 4;
  fft_tensor_t t = in;

  snprintf(name, sizeof(name), "b%d_conv1", block_id);
  t = fft_model_add_conv2d(ff, t, mid_ch, 1, 1, 1, 1, 0, 0, FFT_AC_MODE_NONE,
                           1, 0, name);
  snprintf(name, sizeof(name), "b%d_bn1", block_id);
  t = fft_model_add_batch_norm(ff, t, 1, name);

  snprintf(name, sizeof(name), "b%d_conv2", block_id);
  t = fft_model_add_conv2d(ff, t, mid_ch, 3, 3, stride, stride, 1, 1,
                           FFT_AC_MODE_NONE, 1, 0, name);
  snprintf(name, sizeof(name), "b%d_bn2", block_id);
  t = fft_model_add_batch_norm(ff, t, 1, name);

  snprintf(name, sizeof(name), "b%d_conv3", block_id);
  t = fft_model_add_conv2d(ff, t, out_ch, 1, 1, 1, 1, 0, 0, FFT_AC_MODE_NONE,
                           1, 0, name);
  snprintf(name, sizeof(name), "b%d_bn3", block_id);
  t = fft_model_add_batch_norm(ff, t, 0, name);

  fft_tensor_t shortcut = in;
  if (stride != 1 || in_ch != out_ch) {
    snprintf(name, sizeof(name), "b%d_proj", block_id);
    shortcut = fft_model_add_conv2d(ff, in, out_ch, 1, 1, stride, stride, 0,
                                    0, FFT_AC_MODE_NONE, 1, 0, name);
    snprintf(name, sizeof(name), "b%d_proj_bn", block_id);
    shortcut = fft_model_add_batch_norm(ff, shortcut, 0, name);
  }
  snprintf(name, sizeof(name), "b%d_add", block_id);
  t = fft_model_add_add(ff, t, shortcut, name);
  snprintf(name, sizeof(name), "b%d_out", block_id);
  t = fft_model_add_relu(ff, t, name);
  ++block_id;
  return t;
}

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 16;
  int epochs = argc > 2 ? atoi(argv[2]) : 1;
  int num_samples = argc > 3 ? atoi(argv[3]) : 32;
  int image_size = argc > 4 ? atoi(argv[4]) : 64;
  int budget = argc > 5 ? atoi(argv[5]) : 0;
  int classes = 10;

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);
  fft_config_t cfg = fft_config_create(batch_size, epochs, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  if (budget > 0) {
    /* reference --budget/--export flow through the C API */
    fft_config_set_search_budget(cfg, budget);
    fft_config_set_export_strategy_file(cfg, "resnet_strategy.txt");
  }
  printf("resnet50: batch=%d epochs=%d image=%d devices=%d budget=%d\n",
         batch_size, epochs, image_size, fft_config_get_num_devices(cfg),
         budget);

  fft_model_t ff = fft_model_create(cfg);
  CHECK(ff.impl);

  int input_dims[4] = {batch_size, 3, image_size, image_size};
  fft_tensor_t input = fft_model_create_tensor(ff, input_dims, 4,
                                               FFT_DT_FLOAT, "input");
  CHECK(input.impl);

  /* stem: 7x7/2 conv + BN/ReLU + 3x3/2 maxpool */
  fft_tensor_t t = fft_model_add_conv2d(ff, input, 64, 7, 7, 2, 2, 3, 3,
                                        FFT_AC_MODE_NONE, 1, 0, "stem_conv");
  t = fft_model_add_batch_norm(ff, t, 1, "stem_bn");
  t = fft_model_add_pool2d(ff, t, 3, 3, 2, 2, 1, 1, FFT_POOL_MAX, "stem_pool");

  /* stages [3,4,6,3] x bottleneck(64,128,256,512) */
  const int depths[4] = {3, 4, 6, 3};
  const int widths[4] = {64, 128, 256, 512};
  int ch = 64;
  for (int s = 0; s < 4; ++s) {
    for (int i = 0; i < depths[s]; ++i) {
      int stride = (i == 0 && s > 0) ? 2 : 1;
      t = bottleneck(ff, t, ch, widths[s], stride);
      ch = widths[s] * 4;
    }
  }

  /* global average pool = avg pool over the remaining spatial extent */
  int nd = fft_tensor_get_ndims(t);
  int dims[8];
  fft_tensor_get_dims(t, dims);
  CHECK(nd == 4);
  t = fft_model_add_pool2d(ff, t, dims[2], dims[3], 1, 1, 0, 0, FFT_POOL_AVG,
                           "gap");
  t = fft_model_add_flat(ff, t, "flat");
  t = fft_model_add_dense(ff, t, classes, FFT_AC_MODE_NONE, 1, "fc");
  CHECK(t.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.9, 0, 1e-4);
  fft_metrics_type metrics[1] = {FFT_METRICS_ACCURACY};
  fft_tensor_t no_final = {nullptr};
  CHECK(fft_model_compile(ff, opt, FFT_LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                          metrics, 1, no_final) == 0);

  std::vector<float> x((size_t)num_samples * 3 * image_size * image_size);
  std::vector<int> y((size_t)num_samples);
  srand(42);
  for (auto &v : x) v = (float)rand() / RAND_MAX - 0.5f;
  for (auto &v : y) v = rand() % classes;

  fft_dataloader_t dl_x =
      fft_single_dataloader_create(ff, input, x.data(), num_samples);
  CHECK(dl_x.impl);
  fft_tensor_t label = fft_model_get_label_tensor(ff);
  fft_dataloader_t dl_y =
      fft_single_dataloader_create(ff, label, y.data(), num_samples);
  CHECK(dl_y.impl);

  CHECK(fft_model_init_layers(ff) == 0);

  int num_batches = fft_dataloader_num_batches(dl_x);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(ff) == 0);
    CHECK(fft_model_forward(ff) == 0);
    CHECK(fft_model_zero_gradients(ff) == 0);
    CHECK(fft_model_backward(ff) == 0);
    CHECK(fft_model_update(ff) == 0);
  }
  float loss = fft_model_get_last_loss(ff);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("epoch: %d batches, loss=%.4f, THROUGHPUT = %.2f samples/s\n",
         num_batches, loss,
         dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));
  if (epochs > 1) CHECK(fft_model_fit(ff, epochs - 1) == 0);

  fft_dataloader_destroy(dl_x);
  fft_dataloader_destroy(dl_y);
  fft_tensor_destroy(label);
  fft_tensor_destroy(input);
  fft_optimizer_destroy(opt);
  fft_model_destroy(ff);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("resnet_c: SUCCESS\n");
  return 0;
}
