/* CANDLE Uno drug-response model through the C API (reference:
 * examples/cpp/candle_uno/candle_uno.cc — multi-input concat MLP with
 * per-feature dense towers, joined into a deep regression head; MSE loss).
 *
 * Usage: ./candle_uno [batch_size] [epochs] [num_samples]
 * Synthetic feature data (the reference reads CANDLE CSVs).
 */

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "flexflow_tpu_c.h"

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED: %s at %s:%d: %s\n", #cond, __FILE__,     \
              __LINE__, fft_last_error());                              \
    exit(1);                                                            \
    }                                                                   \
  } while (0)

int main(int argc, char **argv) {
  int batch_size = argc > 1 ? atoi(argv[1]) : 32;
  int epochs = argc > 2 ? atoi(argv[2]) : 1;
  int num_samples = argc > 3 ? atoi(argv[3]) : 128;

  /* reference feature widths: gene expression + drug descriptors etc. */
  const int n_inputs = 4;
  const int widths[n_inputs] = {942, 5270, 2048, 1};
  const int tower[3] = {1000, 1000, 1000};

  CHECK(fft_init(getenv("FFT_REPO_ROOT")) == 0);
  fft_config_t cfg = fft_config_create(batch_size, epochs, nullptr, nullptr, 0);
  CHECK(cfg.impl);
  printf("candle_uno: batch=%d epochs=%d devices=%d\n", batch_size, epochs,
         fft_config_get_num_devices(cfg));

  fft_model_t ff = fft_model_create(cfg);
  CHECK(ff.impl);

  fft_tensor_t inputs[n_inputs];
  fft_tensor_t towers[n_inputs];
  char name[64];
  for (int i = 0; i < n_inputs; ++i) {
    int dims[2] = {batch_size, widths[i]};
    snprintf(name, sizeof(name), "feature_%d", i);
    inputs[i] = fft_model_create_tensor(ff, dims, 2, FFT_DT_FLOAT, name);
    CHECK(inputs[i].impl);
    fft_tensor_t t = inputs[i];
    if (widths[i] > 1) {  /* scalar features skip the tower (reference) */
      for (int l = 0; l < 3; ++l) {
        snprintf(name, sizeof(name), "tower_%d_%d", i, l);
        t = fft_model_add_dense(ff, t, tower[l], FFT_AC_MODE_RELU, 1, name);
      }
    }
    towers[i] = t;
  }
  fft_tensor_t t = fft_model_add_concat(ff, towers, n_inputs, 1, "join");
  for (int l = 0; l < 3; ++l) {
    snprintf(name, sizeof(name), "top_%d", l);
    t = fft_model_add_dense(ff, t, 1000, FFT_AC_MODE_RELU, 1, name);
  }
  t = fft_model_add_dense(ff, t, 1, FFT_AC_MODE_NONE, 1, "response");
  CHECK(t.impl);

  fft_optimizer_t opt = fft_sgd_optimizer_create(0.01, 0.9, 0, 0.0);
  fft_metrics_type metrics[1] = {FFT_METRICS_MEAN_SQUARED_ERROR};
  fft_tensor_t no_final = {nullptr};
  CHECK(fft_model_compile(ff, opt, FFT_LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                          metrics, 1, no_final) == 0);

  srand(7);
  std::vector<fft_dataloader_t> loaders;
  std::vector<std::vector<float>> feature_data(n_inputs);
  for (int i = 0; i < n_inputs; ++i) {
    feature_data[i].resize((size_t)num_samples * widths[i]);
    for (auto &v : feature_data[i]) v = (float)rand() / RAND_MAX - 0.5f;
    loaders.push_back(fft_single_dataloader_create(
        ff, inputs[i], feature_data[i].data(), num_samples));
    CHECK(loaders.back().impl);
  }
  std::vector<float> y((size_t)num_samples);
  for (auto &v : y) v = (float)rand() / RAND_MAX;
  fft_tensor_t label = fft_model_get_label_tensor(ff);
  loaders.push_back(
      fft_single_dataloader_create(ff, label, y.data(), num_samples));
  CHECK(loaders.back().impl);

  CHECK(fft_model_init_layers(ff) == 0);

  int num_batches = fft_dataloader_num_batches(loaders[0]);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < num_batches; ++it) {
    CHECK(fft_model_next_batch(ff) == 0);
    CHECK(fft_model_forward(ff) == 0);
    CHECK(fft_model_zero_gradients(ff) == 0);
    CHECK(fft_model_backward(ff) == 0);
    CHECK(fft_model_update(ff) == 0);
  }
  float loss = fft_model_get_last_loss(ff);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  printf("epoch: %d batches, loss=%.4f, THROUGHPUT = %.2f samples/s\n",
         num_batches, loss,
         dt > 0 ? num_batches * batch_size / dt : 0.0);
  CHECK(std::isfinite(loss));
  if (epochs > 1) CHECK(fft_model_fit(ff, epochs - 1) == 0);

  for (auto &dl : loaders) fft_dataloader_destroy(dl);
  fft_tensor_destroy(label);
  for (int i = 0; i < n_inputs; ++i) fft_tensor_destroy(inputs[i]);
  fft_optimizer_destroy(opt);
  fft_model_destroy(ff);
  fft_config_destroy(cfg);
  fft_finalize();
  printf("candle_uno_c: SUCCESS\n");
  return 0;
}
