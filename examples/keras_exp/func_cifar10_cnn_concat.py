"""keras_exp CIFAR-10 CNN with two conv branches concatenated on the
channel axis.

Reference: examples/python/keras_exp/func_cifar10_cnn_concat.py.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np


def top_level_task():
    import keras
    from keras import optimizers
    from keras.layers import (Activation, Concatenate, Conv2D, Dense,
                              Flatten, Input, MaxPooling2D)

    from flexflow_tpu.keras.datasets import cifar10
    from flexflow_tpu.keras_exp.models import Model

    num_classes = 10
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)

    cf = dict(data_format="channels_first")
    input_tensor = Input(shape=(3, 32, 32), dtype="float32")
    b1 = Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1),
                padding="valid", activation="relu", **cf)(input_tensor)
    b2 = Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1),
                padding="valid", activation="relu", **cf)(input_tensor)
    t = Concatenate(axis=1)([b1, b2])
    t = MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid",
                     **cf)(t)
    t = Flatten(**cf)(t)
    t = Dense(256, activation="relu")(t)
    t = Dense(num_classes)(t)
    output = Activation("softmax")(t)

    model = Model(inputs={1: input_tensor}, outputs=output)
    print(model.summary())
    opt = optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    model.fit(x_train, y_train, epochs=int(os.environ.get("EPOCHS", 1)))


if __name__ == "__main__":
    print("Functional API, cifar10 cnn concat (keras_exp)")
    top_level_task()
