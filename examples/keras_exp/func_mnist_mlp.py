"""keras_exp MNIST MLP: a GENUINE tf.keras functional model exported to
ONNX bytes and replayed through ONNXModelKeras.

Reference: examples/python/keras_exp/func_mnist_mlp.py (tf.keras Input/
Dense -> keras2onnx -> flexflow.keras_exp.models.Model). Same layer
stack, same optimizer/loss/metrics call shape.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np


def top_level_task():
    import keras
    from keras import optimizers
    from keras.layers import Activation, Dense, Input

    from flexflow_tpu.keras.datasets import mnist
    from flexflow_tpu.keras_exp.models import Model

    num_classes = 10
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    print("shape: ", x_train.shape)

    input_tensor = Input(shape=(784,))
    output = Dense(512, activation="relu")(input_tensor)
    output = Dense(512, activation="relu")(output)
    output = Dense(num_classes)(output)
    output = Activation("softmax")(output)
    model = Model(inputs={1: input_tensor}, outputs=output)
    print(model.summary())

    opt = optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    model.fit(x_train, y_train, epochs=int(os.environ.get("EPOCHS", 1)))


if __name__ == "__main__":
    print("Functional API, mnist mlp (keras_exp)")
    top_level_task()
