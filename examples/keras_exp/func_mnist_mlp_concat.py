"""keras_exp MNIST MLP with nested sub-models + Concatenate.

Reference: examples/python/keras_exp/func_mnist_mlp_concat.py — four
tf.keras sub-Models called on two shared Inputs, concatenated, then a
classifier head; exercises sub-model inlining and multi-input fit.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np


def top_level_task():
    import keras
    from keras import optimizers
    from keras.layers import Activation, Concatenate, Dense, Input

    from flexflow_tpu.keras.datasets import mnist
    from flexflow_tpu.keras_exp.models import Model

    num_classes = 10
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)

    def block(tag):
        it = Input(shape=(784,))
        t = Dense(256, activation="relu", name=f"dense{tag}")(it)
        t = Dense(256, activation="relu", name=f"dense{tag}{tag}")(t)
        return keras.Model(it, t, name=f"block{tag}")

    model1, model2, model3, model4 = (block(i) for i in range(1, 5))

    input_tensor1 = Input(shape=(784,))
    input_tensor2 = Input(shape=(784,))
    t1 = model1(input_tensor1)
    t2 = model2(input_tensor1)
    t3 = model3(input_tensor2)
    t4 = model4(input_tensor2)
    output = Concatenate(axis=1)([t1, t2, t3, t4])
    output = Dense(num_classes)(output)
    output = Activation("softmax")(output)

    model = Model(inputs={5: input_tensor1, 6: input_tensor2},
                  outputs=output)
    print(model.summary())
    opt = optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    model.fit([x_train, x_train], y_train,
              epochs=int(os.environ.get("EPOCHS", 1)))


if __name__ == "__main__":
    print("Functional API, mnist mlp concat (keras_exp)")
    top_level_task()
