"""RegNet-style grouped-convolution network imported through torch.fx
(VERDICT r2 #8: regnet-class import; exercises Conv2d groups>1 through the
.ff IR — torchvision is absent from this image, so the RegNet-X block
structure (1x1 -> grouped 3x3 -> 1x1 + residual) is defined locally)."""
import argparse
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel


class XBlock(nn.Module):
    """RegNet-X bottleneck: 1x1, grouped 3x3, 1x1, residual."""

    def __init__(self, cin, cout, groups, stride=1):
        super().__init__()
        self.c1 = nn.Conv2d(cin, cout, 1, bias=False)
        self.b1 = nn.BatchNorm2d(cout)
        self.c2 = nn.Conv2d(cout, cout, 3, stride, 1, groups=groups,
                            bias=False)
        self.b2 = nn.BatchNorm2d(cout)
        self.c3 = nn.Conv2d(cout, cout, 1, bias=False)
        self.b3 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.relu(self.b1(self.c1(x)))
        y = self.relu(self.b2(self.c2(y)))
        y = self.b3(self.c3(y))
        return self.relu(y + idt)


class RegNetX(nn.Module):
    def __init__(self, widths=(32, 64), depths=(1, 2), groups=8,
                 num_classes=10):
        super().__init__()
        layers = [nn.Conv2d(3, widths[0], 3, 2, 1, bias=False),
                  nn.BatchNorm2d(widths[0]), nn.ReLU()]
        cin = widths[0]
        for w, d in zip(widths, depths):
            for i in range(d):
                layers.append(XBlock(cin, w, groups, stride=2 if i == 0
                                     else 1))
                cin = w
        self.trunk = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2d((1, 1))
        self.flat = nn.Flatten()
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        return self.fc(self.flat(self.pool(self.trunk(x))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=16)
    ap.add_argument("--iters", type=int, default=2)
    args, _ = ap.parse_known_args()

    b = args.batch_size
    cfg = FFConfig(batch_size=b)
    ff = FFModel(cfg)
    x = ff.create_tensor([b, 3, 32, 32], name="x")
    outs = PyTorchModel(model=RegNetX()).apply(ff, [x])
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(b * 2, 3, 32, 32).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (b * 2, 1)).astype(np.int32))
    for _ in range(args.iters):
        loss, _ = ff._run_train_step(ff._stage_batch())
    print(f"regnet_fx: final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
