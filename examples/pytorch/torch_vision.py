"""torchvision model import (reference:
examples/python/pytorch/torch_vision.py: torchvision.models -> FX -> native).
The torchvision package is not bundled in this image; falls back to the
in-repo torch ResNet block so the FX path is still exercised."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel, torch_to_flexflow


def get_model():
    try:
        import torchvision.models as models
        print("using torchvision.models.resnet18")
        return models.resnet18(weights=None), (3, 224, 224), 1000
    except ImportError:
        print("torchvision not available; using in-repo torch CNN fallback")

        class SmallNet(nn.Module):
            def __init__(self):
                super().__init__()
                self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3)
                self.pool = nn.MaxPool2d(2, 2)
                self.conv2 = nn.Conv2d(64, 128, 3, padding=1)
                self.flat = nn.Flatten()
                self.fc = nn.Linear(128 * 16 * 16, 10)
                self.relu = nn.ReLU()

            def forward(self, x):
                x = self.relu(self.conv1(x))
                x = self.pool(x)
                x = self.relu(self.conv2(x))
                x = self.pool(x)
                x = self.flat(x)
                return self.fc(x)

        return SmallNet(), (3, 128, 128), 10


def main():
    net, in_shape, num_classes = get_model()
    ff_file = "/tmp/torch_vision.ff"
    torch_to_flexflow(net, ff_file)

    cfg = FFConfig.parse_args()
    cfg.batch_size = min(cfg.batch_size, 16)
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size] + list(in_shape), name="input")
    outs = PyTorchModel(ff_file).apply(ff, [inp])
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 2
    SingleDataLoader(ff, inp, rs.randn(n, *in_shape).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, num_classes, (n, 1)).astype(np.int32))
    ff.fit(epochs=1)


if __name__ == "__main__":
    main()
