"""MNIST MLP via the in-memory FX flow (reference:
examples/python/pytorch/mnist_mlp_torch2.py — the 'torch2' variant drives
the importer without an intermediate .ff file). Functional ops
(torch.relu, torch.flatten) exercise the FunctionNode path of the
tracer."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.torch import PyTorchModel


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 512)
        self.fc2 = nn.Linear(512, 512)
        self.fc3 = nn.Linear(512, 10)

    def forward(self, x):
        x = torch.flatten(x, 1)
        x = torch.relu(self.fc1(x))
        x = torch.relu(self.fc2(x))
        return self.fc3(x)


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="x")
    # no .ff file on disk: trace straight from the live module
    outs = PyTorchModel(model=MLP()).apply(ff, [x])
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    (x_train, y_train), _ = mnist.load_data()
    SingleDataLoader(ff, x,
                     x_train.reshape(-1, 784).astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
