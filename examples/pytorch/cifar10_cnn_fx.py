"""CIFAR10 CNN imported from torch via FX (reference:
examples/python/pytorch/cifar10_cnn.py: torch module -> .ff file -> native
training)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel, torch_to_flexflow


class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 32, 3, padding=1)
        self.conv2 = nn.Conv2d(32, 64, 3, padding=1)
        self.pool = nn.MaxPool2d(2, 2)
        self.flat = nn.Flatten()
        self.fc1 = nn.Linear(64 * 16 * 16, 256)
        self.fc2 = nn.Linear(256, 10)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.conv1(x))
        x = self.relu(self.conv2(x))
        x = self.pool(x)
        x = self.flat(x)
        x = self.relu(self.fc1(x))
        return self.fc2(x)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    (x, y), _ = cifar10.load_data()
    x = x.astype(np.float32) / 255.0
    y = y.reshape(-1, 1).astype(np.int32)

    ff_file = "/tmp/cifar10_cnn.ff"
    torch_to_flexflow(CNN(), ff_file)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    outs = PyTorchModel(ff_file).apply(ff, [inp])
    ff.compile(SGDOptimizer(lr=0.02),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])
    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=int(os.environ.get("EPOCHS", 1)))


if __name__ == "__main__":
    main()
