"""Small ResNet import via torch.fx (reference:
examples/python/pytorch/resnet.py): trace a residual torch CNN, export the
.ff IR, replay and train."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel, torch_to_flexflow


class Block(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv1 = nn.Conv2d(ch, ch, 3, padding=1)
        self.relu1 = nn.ReLU()
        self.conv2 = nn.Conv2d(ch, ch, 3, padding=1)
        self.relu2 = nn.ReLU()

    def forward(self, x):
        return self.relu2(x + self.conv2(self.relu1(self.conv1(x))))


class MiniResNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.stem = nn.Conv2d(3, 32, 3, padding=1)
        self.relu = nn.ReLU()
        self.b1 = Block(32)
        self.b2 = Block(32)
        self.pool = nn.MaxPool2d(4)
        self.flat = nn.Flatten()
        self.fc = nn.Linear(32 * 8 * 8, 10)

    def forward(self, x):
        x = self.relu(self.stem(x))
        x = self.b2(self.b1(x))
        return self.fc(self.flat(self.pool(x)))


def main():
    torch_to_flexflow(MiniResNet(), "/tmp/mini_resnet.ff")
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="x")
    outs = PyTorchModel("/tmp/mini_resnet.ff").apply(ff, [x])
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(256, 3, 32, 32).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (256, 1)).astype(np.int32))
    ff.fit(epochs=int(os.environ.get("EPOCHS", 1)))


if __name__ == "__main__":
    main()
