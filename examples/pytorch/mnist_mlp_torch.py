"""Pure-torch MNIST MLP twin (reference:
examples/python/pytorch/mnist_mlp_torch.py): the torch-side baseline used to
compare against the FX-imported run in mnist_mlp_fx.py."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import torch
import torch.nn as nn


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = torch.from_numpy(x.reshape(-1, 784).astype(np.float32) / 255.0)
    y = torch.from_numpy(y.astype(np.int64).reshape(-1))

    net = nn.Sequential(nn.Linear(784, 512), nn.ReLU(),
                        nn.Linear(512, 512), nn.ReLU(),
                        nn.Linear(512, 10))
    opt = torch.optim.SGD(net.parameters(), lr=0.01)
    loss_fn = nn.CrossEntropyLoss()
    bs = 64
    for epoch in range(int(os.environ.get("EPOCHS", 1))):
        total, correct, lsum = 0, 0, 0.0
        for i in range(0, len(x) - bs + 1, bs):
            xb, yb = x[i:i + bs], y[i:i + bs]
            opt.zero_grad()
            logits = net(xb)
            loss = loss_fn(logits, yb)
            loss.backward()
            opt.step()
            total += bs
            correct += int((logits.argmax(-1) == yb).sum())
            lsum += float(loss) * bs
        print(f"epoch {epoch}: accuracy={100.0 * correct / total:.2f}% "
              f"loss={lsum / total:.4f}")


if __name__ == "__main__":
    main()
