"""BERT-class encoder imported through torch.fx (VERDICT r2 #8 / reference
examples/python/pytorch breadth: a transformer-encoder import, exercising
the MultiheadAttention, LayerNorm, GELU and residual-add paths of the FX
importer). The torchvision/HF checkpoints are not downloadable in this
image, so the encoder is defined locally with the standard BERT block
structure (post-LN, 4x FFN width) and imported architecture-first, the
same way the reference's mnist/resnet pytorch examples define their
modules inline."""
import argparse
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel


class BertBlock(nn.Module):
    def __init__(self, hidden, heads):
        super().__init__()
        self.attn = nn.MultiheadAttention(hidden, heads, batch_first=True)
        self.ln1 = nn.LayerNorm(hidden)
        self.fc1 = nn.Linear(hidden, 4 * hidden)
        self.gelu = nn.GELU()
        self.fc2 = nn.Linear(4 * hidden, hidden)
        self.ln2 = nn.LayerNorm(hidden)

    def forward(self, x):
        a, _ = self.attn(x, x, x)
        x = self.ln1(x + a)
        f = self.fc2(self.gelu(self.fc1(x)))
        return self.ln2(x + f)


class BertEncoder(nn.Module):
    """Embeddings-in, classification-logits-out (the token embedding lookup
    stays outside, as in the native bert_proxy example)."""

    def __init__(self, hidden=64, heads=4, layers=2, seq=32, classes=8):
        super().__init__()
        self.blocks = nn.Sequential(*[BertBlock(hidden, heads)
                                      for _ in range(layers)])
        self.flat = nn.Flatten()
        self.cls = nn.Linear(hidden * seq, classes)

    def forward(self, x):
        return self.cls(self.flat(self.blocks(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=16)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seq", type=int, default=32)
    args, _ = ap.parse_known_args()

    b, s, h = args.batch_size, args.seq, args.hidden
    cfg = FFConfig(batch_size=b)
    ff = FFModel(cfg)
    x = ff.create_tensor([b, s, h], name="x")
    model = BertEncoder(h, 4, args.layers, s)
    outs = PyTorchModel(model=model).apply(ff, [x])
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(b * 2, s, h).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 8, (b * 2, 1)).astype(np.int32))
    for _ in range(args.iters):
        loss, _ = ff._run_train_step(ff._stage_batch())
    print(f"bert_fx: final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
