"""Deep bottleneck ResNet (torchvision resnet50/101/152 architecture)
imported through torch.fx and trained (reference:
examples/python/pytorch/resnet152_training.py, which imports torchvision's
resnet152 — torchvision is absent from this image, so the identical
bottleneck architecture is defined locally; --depth picks the standard
[3,4,6,3]/[3,4,23,3]/[3,8,36,3] stage configs)."""
import argparse
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.torch import PyTorchModel

DEPTHS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.c1 = nn.Conv2d(cin, width, 1, bias=False)
        self.b1 = nn.BatchNorm2d(width)
        self.c2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.b2 = nn.BatchNorm2d(width)
        self.c3 = nn.Conv2d(width, cout, 1, bias=False)
        self.b3 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.relu(self.b1(self.c1(x)))
        y = self.relu(self.b2(self.c2(y)))
        y = self.b3(self.c3(y))
        return self.relu(y + idt)


class ResNet(nn.Module):
    def __init__(self, depth=152, num_classes=10, width=64):
        super().__init__()
        stages = DEPTHS[depth]
        layers = [nn.Conv2d(3, width, 7, 2, 3, bias=False),
                  nn.BatchNorm2d(width), nn.ReLU(), nn.MaxPool2d(3, 2, 1)]
        cin = width
        for si, blocks in enumerate(stages):
            w = width * (2 ** si)
            for bi in range(blocks):
                layers.append(Bottleneck(cin, w, stride=2
                                         if bi == 0 and si > 0 else 1))
                cin = w * Bottleneck.expansion
        self.trunk = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2d((1, 1))
        self.flat = nn.Flatten()
        self.fc = nn.Linear(cin, num_classes)

    def forward(self, x):
        return self.fc(self.flat(self.pool(self.trunk(x))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=8)
    ap.add_argument("--depth", type=int, default=152,
                    choices=sorted(DEPTHS))
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--iters", type=int, default=2)
    args, _ = ap.parse_known_args()

    b, im = args.batch_size, args.image_size
    cfg = FFConfig(batch_size=b)
    ff = FFModel(cfg)
    x = ff.create_tensor([b, 3, im, im], name="x")
    outs = PyTorchModel(model=ResNet(args.depth)).apply(ff, [x])
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=outs[0])

    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(b * 2, 3, im, im).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (b * 2, 1)).astype(np.int32))
    for _ in range(args.iters):
        loss, _ = ff._run_train_step(ff._stage_batch())
    print(f"resnet{args.depth}: final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
