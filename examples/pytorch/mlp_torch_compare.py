"""Numerical parity check torch vs flexflow_tpu (reference:
examples/python/pytorch/mnist_mlp_torch.py — the torch-side twin used to
compare losses): same MLP, same weights, one forward — outputs must agree."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.torch import PyTorchModel, torch_to_flexflow


def main():
    net = nn.Sequential(nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 8))
    torch_to_flexflow(net, "/tmp/mlp_cmp.ff")
    cfg = FFConfig(batch_size=16)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 64], name="x")
    outs = PyTorchModel("/tmp/mlp_cmp.ff").apply(ff, [x])
    ff.compile(optimizer=None, final_tensor=outs[0])
    # copy torch weights in
    for name, mod in [("_0", net[0]), ("_2", net[2])]:
        ff.set_weights(name, "kernel", mod.weight.detach().numpy().T)
        ff.set_weights(name, "bias", mod.bias.detach().numpy())
    xd = np.random.RandomState(0).randn(16, 64).astype(np.float32)
    got = np.asarray(ff.predict({"x": xd}))
    with torch.no_grad():
        want = net(torch.from_numpy(xd)).numpy()
    # TPU default matmul precision runs f32 through bf16 passes (~1e-3)
    np.testing.assert_allclose(got, want, atol=5e-3)
    print("torch parity OK: max err", float(np.abs(got - want).max()))


if __name__ == "__main__":
    main()
