"""Custom callbacks demo (reference: examples/python/keras/callback.py):
a user Callback subclass observing epoch metrics alongside the built-in
gates."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import Callback
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Dense


class EpochLogger(Callback):
    def __init__(self):
        super().__init__()
        self.history = []

    def on_epoch_end(self, epoch):
        perf = self.model._perf
        loss = perf.sparse_cce_loss / max(perf.train_all, 1)
        self.history.append((epoch, perf.accuracy, loss))
        print(f"[EpochLogger] epoch {epoch}: acc={perf.accuracy:.4f} "
              f"loss={loss:.4f}")


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0

    model = Sequential([
        Dense(256, activation="relu", input_shape=(784,)),
        Dense(10),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    logger = EpochLogger()
    model.fit(x_train, y_train, epochs=3, callbacks=[logger])
    assert len(logger.history) == 3, logger.history


if __name__ == "__main__":
    main()
