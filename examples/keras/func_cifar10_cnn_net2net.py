"""Net2Net on the CIFAR10 CNN (reference:
examples/python/keras/func_cifar10_cnn_net2net.py): teacher conv/dense
weights seed the student before continued training."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (Conv2D, Dense, Flatten, Input,
                                       MaxPooling2D)


def build(layers):
    inp = Input((3, 32, 32))
    c1, c2, d1, d2 = layers
    t = c1(inp)
    t = MaxPooling2D(2)(t)
    t = c2(t)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    t = d1(t)
    out = d2(t)
    return Model(inp, out)


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0

    t_layers = [Conv2D(32, 3, padding=1, activation="relu"),
                Conv2D(64, 3, padding=1, activation="relu"),
                Dense(256, activation="relu"), Dense(10)]
    teacher = build(t_layers)
    teacher.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    teacher.fit(x_train, y_train, epochs=1)

    s_layers = [Conv2D(32, 3, padding=1, activation="relu"),
                Conv2D(64, 3, padding=1, activation="relu"),
                Dense(256, activation="relu"), Dense(10)]
    student = build(s_layers)
    student.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    for tl, sl in zip(t_layers, s_layers):
        sl.set_weights(student.ffmodel, *tl.get_weights(teacher.ffmodel))
    student.fit(x_train, y_train, epochs=1)


if __name__ == "__main__":
    main()
