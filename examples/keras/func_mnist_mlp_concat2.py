"""Two-level concat MLP (reference:
examples/python/keras/func_mnist_mlp_concat2.py): four parallel dense
branches over two inputs, concatenated pairwise then together."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.layers import Concatenate, Dense, Input
from flexflow_tpu.keras.datasets import mnist


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0

    in1, in2 = Input((784,)), Input((784,))
    a = Dense(256, activation="relu")(in1)
    b = Dense(256, activation="relu")(in1)
    c = Dense(256, activation="relu")(in2)
    d = Dense(256, activation="relu")(in2)
    ab = Concatenate(axis=1)([a, b])
    cd = Concatenate(axis=1)([c, d])
    t = Concatenate(axis=1)([ab, cd])
    t = Dense(512, activation="relu")(t)
    out = Dense(10)(t)

    model = Model([in1, in2], out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit([x_train, x_train], y_train, epochs=2)


if __name__ == "__main__":
    main()
