"""REAL-data accuracy gate: MLP on the bundled UCI handwritten digits
(flexflow_tpu/data/digits.npz — the real-image stand-in for MNIST in this
zero-egress environment). Role parity with the reference's real-MNIST MLP gate
(examples/python/keras/mnist_mlp.py + accuracy.py MNIST_MLP=90)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import digits
from flexflow_tpu.keras.layers import Dense


def main():
    (x_train, y_train), (x_test, y_test) = digits.load_data()
    x_train = x_train.reshape(-1, 64).astype(np.float32) / 16.0

    model = Sequential([
        Dense(256, activation="relu", input_shape=(64,)),
        Dense(128, activation="relu"),
        Dense(10),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.MNIST_MLP)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x_train, y_train, epochs=int(os.environ.get("EPOCHS", 8)),
              callbacks=gates)


if __name__ == "__main__":
    main()
