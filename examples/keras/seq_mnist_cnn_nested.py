"""Nested models (reference: examples/python/keras/seq_mnist_cnn_nested.py):
a Sequential conv stack and a functional MLP head, composed by add()-ing the
models themselves into an outer Sequential."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model, Sequential
from flexflow_tpu.keras.layers import (Conv2D, Dense, Flatten, Input,
                                       MaxPooling2D)
from flexflow_tpu.keras.datasets import mnist


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0

    conv_stack = Sequential([
        Conv2D(32, 3, padding=1, activation="relu", input_shape=(1, 28, 28)),
        Conv2D(64, 3, padding=1, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
    ])

    inp = Input((12544,))
    out = Dense(512, activation="relu")(inp)
    out = Dense(10)(out)
    head = Model(inp, out)

    model = Sequential()
    model.add(conv_stack)
    model.add(head)
    print(model.summary())

    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
