"""Functional CIFAR10 CNN (reference:
examples/python/keras/func_cifar10_cnn.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.keras.layers import (Conv2D, Dense, Flatten, Input,
                                       MaxPooling2D)


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0

    inp = Input((3, 32, 32))
    t = Conv2D(32, 3, padding=1, activation="relu")(inp)
    t = Conv2D(32, 3, padding=1, activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Conv2D(64, 3, padding=1, activation="relu")(t)
    t = Conv2D(64, 3, padding=1, activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    t = Dense(512, activation="relu")(t)
    out = Dense(10)(t)

    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.CIFAR10_CNN)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x_train, y_train, epochs=2, callbacks=gates)


if __name__ == "__main__":
    main()
