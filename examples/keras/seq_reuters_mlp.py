"""Reuters topic MLP, Sequential API (reference:
examples/python/keras/seq_reuters_mlp.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.layers import Dense


def main():
    from flexflow_tpu.keras.datasets import reuters
    from flexflow_tpu.keras.preprocessing.text import Tokenizer
    max_words = 1000
    (x, y), _ = reuters.load_data(num_words=max_words)
    # bag-of-words vectorization, as the reference does before its Dense
    # stack (seq_reuters_mlp.py: tokenizer.sequences_to_matrix 'binary')
    tokenizer = Tokenizer(num_words=max_words)
    x = tokenizer.sequences_to_matrix(x, mode="binary")
    num_classes = int(y.max()) + 1
    model = Sequential([
        Dense(512, activation="relu", input_shape=(x.shape[1],)),
        Dense(num_classes),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.REUTERS_MLP)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 3)), callbacks=gates)


if __name__ == "__main__":
    main()
