"""Sequential MNIST MLP (reference:
examples/python/keras/seq_mnist_mlp.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Dense


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0

    model = Sequential([
        Dense(512, activation="relu", input_shape=(784,)),
        Dense(512, activation="relu"),
        Dense(10),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.MNIST_MLP)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x_train, y_train, epochs=2, callbacks=gates)


if __name__ == "__main__":
    main()
