"""Functional-API MNIST MLP (reference: examples/python/keras/func_mnist_mlp.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import Dense


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 784).astype(np.float32) / 255.0
    inp = Input((784,))
    t = Dense(512, activation="relu")(inp)
    t = Dense(512, activation="relu")(t)
    out = Dense(10)(t)
    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
