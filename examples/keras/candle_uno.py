"""CANDLE Uno via the Keras functional API (reference:
examples/python/keras/candle_uno/candle_uno.py — multi-input concat MLP
built with Input/Dense/Concatenate)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.layers import Concatenate, Dense, Input


def main():
    feature_shapes = {"dose": 1, "cell_rnaseq": 942,
                      "drug_descriptors": 5270, "drug_fingerprints": 2048}
    input_features = {"dose1": "dose", "dose2": "dose",
                      "cell_rnaseq": "cell_rnaseq",
                      "drug1_descriptors": "drug_descriptors",
                      "drug1_fingerprints": "drug_fingerprints",
                      "drug2_descriptors": "drug_descriptors",
                      "drug2_fingerprints": "drug_fingerprints"}
    inputs, encoded = [], []
    for name, feat in input_features.items():
        x = Input(shape=(feature_shapes[feat],), name=name)
        inputs.append(x)
        t = x
        for width in (1000, 1000, 1000):
            t = Dense(width, activation="relu")(t)
        encoded.append(t)
    out = Concatenate(axis=1)(encoded)
    for width in (1000, 1000, 1000):
        out = Dense(width, activation="relu")(out)
    out = Dense(1)(out)

    model = Model(inputs=inputs, outputs=out)
    model.compile(optimizer="sgd", loss="mean_squared_error",
                  metrics=["mean_squared_error"])

    rs = np.random.RandomState(0)
    n = 256
    xs = [rs.randn(n, feature_shapes[f]).astype(np.float32)
          for f in input_features.values()]
    y = rs.rand(n, 1).astype(np.float32)
    model.fit(xs, y, epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
