"""CIFAR-10 AlexNet, functional API (reference:
examples/python/keras/func_cifar10_alexnet.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.layers import Conv2D, Dense, Flatten, MaxPooling2D


def main():
    from flexflow_tpu.keras.datasets import cifar10
    (x, y), _ = cifar10.load_data()
    x = x.astype(np.float32) / 255.0
    inp = Input((3, 32, 32))
    t = Conv2D(64, 5, padding="same", activation="relu")(inp)
    t = MaxPooling2D(2)(t)
    t = Conv2D(192, 5, padding="same", activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Conv2D(256, 3, padding="same", activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Dense(512, activation="relu")(Flatten()(t))
    out = Dense(10)(t)
    model = Model(inp, out)
    # adam: the accuracy tier's epoch budget is a fraction of the
    # reference's (EPOCHS=4-6 vs 40), and plain SGD cannot reach the 90%
    # gate that fast on this depth of model
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.CIFAR10_ALEXNET)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 4)),
              callbacks=gates)


if __name__ == "__main__":
    main()
