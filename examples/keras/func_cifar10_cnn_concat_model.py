"""Concat of two nested functional models (reference:
examples/python/keras/func_cifar10_cnn_concat_model.py): two conv-branch
Models called on the same input, feature-concatenated into a shared head."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.layers import (Concatenate, Conv2D, Dense, Flatten,
                                       Input, MaxPooling2D)
from flexflow_tpu.keras.datasets import cifar10


def branch():
    cin = Input((3, 32, 32))
    t = Conv2D(32, 3, padding=1, activation="relu")(cin)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    return Model(cin, t)


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0

    inp = Input((3, 32, 32))
    a = branch()(inp)
    b = branch()(inp)
    t = Concatenate(axis=1)([a, b])
    t = Dense(256, activation="relu")(t)
    out = Dense(10)(t)
    model = Model(inp, out)

    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
