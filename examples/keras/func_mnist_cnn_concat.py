"""Functional MNIST CNN with a concat of two conv branches (reference:
examples/python/keras/func_mnist_cnn_concat.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.layers import (Concatenate, Conv2D, Dense, Flatten,
                                       Input, MaxPooling2D)
from flexflow_tpu.keras.datasets import mnist


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0

    inp = Input((1, 28, 28))
    a = Conv2D(32, 3, padding=1, activation="relu")(inp)
    b = Conv2D(32, 3, padding=1, activation="relu")(inp)
    t = Concatenate(axis=1)([a, b])  # channel concat
    t = Conv2D(64, 3, padding=1, activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    t = Dense(128, activation="relu")(t)
    out = Dense(10)(t)

    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
