"""REAL-data accuracy gate: CNN on the bundled UCI handwritten digits
(flexflow_tpu/data/digits.npz). Role parity with the reference's real-MNIST CNN gate
(examples/python/keras/mnist_cnn.py + accuracy.py MNIST_CNN=90)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import digits
from flexflow_tpu.keras.layers import Conv2D, Dense, Flatten, MaxPooling2D


def main():
    (x_train, y_train), _ = digits.load_data()
    x_train = x_train.reshape(-1, 1, 8, 8).astype(np.float32) / 16.0

    model = Sequential([
        Conv2D(32, 3, padding="same", activation="relu",
               input_shape=(1, 8, 8)),
        Conv2D(64, 3, padding="same", activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(128, activation="relu"),
        Dense(10),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    gates = ([EpochVerifyMetrics(ModelAccuracy.MNIST_CNN)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    model.fit(x_train, y_train, epochs=int(os.environ.get("EPOCHS", 8)),
              callbacks=gates)


if __name__ == "__main__":
    main()
