"""Concat of a Sequential and a functional Model (reference:
examples/python/keras/func_cifar10_cnn_concat_seq_model.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model, Sequential
from flexflow_tpu.keras.layers import (Concatenate, Conv2D, Dense, Flatten,
                                       Input, MaxPooling2D)
from flexflow_tpu.keras.datasets import cifar10


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0

    seq_branch = Sequential([
        Conv2D(32, 3, padding=1, activation="relu", input_shape=(3, 32, 32)),
        MaxPooling2D(2),
        Flatten(),
    ])

    fin = Input((3, 32, 32))
    t = Conv2D(32, 5, padding=2, activation="relu")(fin)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    func_branch = Model(fin, t)

    inp = Input((3, 32, 32))
    t = Concatenate(axis=1)([seq_branch(inp), func_branch(inp)])
    t = Dense(256, activation="relu")(t)
    out = Dense(10)(t)
    model = Model(inp, out)

    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
