"""Functional nesting (reference:
examples/python/keras/func_cifar10_cnn_nested.py): a conv-stack Model called
as a layer inside an outer functional Model."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.layers import (Conv2D, Dense, Flatten, Input,
                                       MaxPooling2D)
from flexflow_tpu.keras.datasets import cifar10


def main():
    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0

    cin = Input((3, 32, 32))
    t = Conv2D(32, 3, padding=1, activation="relu")(cin)
    t = Conv2D(64, 3, padding=1, activation="relu")(t)
    t = MaxPooling2D(2)(t)
    t = Flatten()(t)
    conv_model = Model(cin, t)

    inp = Input((3, 32, 32))
    feats = conv_model(inp)  # nested call replays the conv graph
    h = Dense(512, activation="relu")(feats)
    out = Dense(10)(h)
    model = Model(inp, out)

    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
