"""MNIST CNN via the Keras frontend with accuracy gate
(reference: examples/python/keras/mnist_cnn.py + accuracy callback).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Conv2D, Dense, Flatten, MaxPooling2D


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0

    model = Sequential([
        Conv2D(32, 3, padding="same", activation="relu",
               input_shape=(1, 28, 28)),
        Conv2D(64, 3, padding="same", activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(128, activation="relu"),
        Dense(10),
    ])
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train, y_train, epochs=4,
              callbacks=[EpochVerifyMetrics(ModelAccuracy.MNIST_CNN)])


if __name__ == "__main__":
    main()
