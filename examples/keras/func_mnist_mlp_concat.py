"""Two-branch MLP joined by Concatenate (reference:
examples/python/keras/func_mnist_mlp_concat.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import Concatenate, Dense


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 784).astype(np.float32) / 255.0
    inp = Input((784,))
    a = Dense(256, activation="relu")(inp)
    b = Dense(256, activation="relu")(inp)
    t = Concatenate(axis=1)([a, b])
    out = Dense(10)(Dense(256, activation="relu")(t))
    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
