"""CIFAR-10 CNN with concatenated conv branches (reference:
examples/python/keras/func_cifar10_cnn_concat.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import (Concatenate, Conv2D, Dense, Flatten,
                                       MaxPooling2D)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    (x, y), _ = cifar10.load_data()
    x = x.astype(np.float32) / 255.0
    inp = Input((3, 32, 32))
    a = Conv2D(32, 3, padding="same", activation="relu")(inp)
    b = Conv2D(32, 5, padding="same", activation="relu")(inp)
    t = Concatenate(axis=1)([a, b])
    t = MaxPooling2D(2)(t)
    t = Conv2D(64, 3, padding="same", activation="relu")(t)
    t = MaxPooling2D(2)(t)
    out = Dense(10)(Dense(256, activation="relu")(Flatten()(t)))
    model = Model(inp, out)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
