"""Sequential Net2Net MLP (reference:
examples/python/keras/seq_mnist_mlp_net2net.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Sequential
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Dense


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0

    t1 = Dense(512, activation="relu", input_shape=(784,))
    t2 = Dense(512, activation="relu")
    t3 = Dense(10)
    teacher = Sequential([t1, t2, t3])
    teacher.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    teacher.fit(x_train, y_train, epochs=2)

    s1 = Dense(512, activation="relu", input_shape=(784,))
    s2 = Dense(512, activation="relu")
    s3 = Dense(10)
    student = Sequential([s1, s2, s3])
    student.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    for tl, sl in zip((t1, t2, t3), (s1, s2, s3)):
        sl.set_weights(student.ffmodel, *tl.get_weights(teacher.ffmodel))
    student.fit(x_train, y_train, epochs=2)


if __name__ == "__main__":
    main()
