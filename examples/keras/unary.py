"""Unary activation layers exercise (reference: examples/python/keras/unary.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import Activation, Dense


def main():
    rs = np.random.RandomState(0)
    x = rs.randn(256, 64).astype(np.float32)
    y = rs.randint(0, 4, (256,)).astype(np.int32)
    inp = Input((64,))
    t = Activation("relu")(Dense(64)(inp))
    t = Activation("sigmoid")(Dense(64)(t))
    t = Activation("tanh")(Dense(64)(t))
    out = Dense(4)(t)
    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=1)


if __name__ == "__main__":
    main()
