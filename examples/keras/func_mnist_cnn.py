"""Functional-API MNIST CNN (reference: examples/python/keras/func_mnist_cnn.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import Conv2D, Dense, Flatten, MaxPooling2D


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0
    inp = Input((1, 28, 28))
    t = Conv2D(32, 3, padding="same", activation="relu")(inp)
    t = MaxPooling2D(2)(t)
    t = Conv2D(64, 3, padding="same", activation="relu")(t)
    t = MaxPooling2D(2)(t)
    out = Dense(10)(Dense(128, activation="relu")(Flatten()(t)))
    model = Model(inp, out)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
