"""Reshape layer exercise (reference: examples/python/keras/reshape.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu.keras import Input, Model
from flexflow_tpu.keras.layers import Dense, Reshape


def main():
    rs = np.random.RandomState(0)
    x = rs.randn(256, 784).astype(np.float32)
    y = rs.randint(0, 10, (256,)).astype(np.int32)
    inp = Input((784,))
    t = Reshape((16, 49))(inp)
    t = Reshape((784,))(t)
    out = Dense(10)(Dense(64, activation="relu")(t))
    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, epochs=1)


if __name__ == "__main__":
    main()
