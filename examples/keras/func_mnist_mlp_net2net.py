"""Net2Net teacher->student MLP (reference:
examples/python/keras/func_mnist_mlp_net2net.py): train a teacher, export its
layer weights, seed an identically-shaped student, keep training under the
accuracy gate."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu.keras import Model
from flexflow_tpu.keras.callbacks import EpochVerifyMetrics, ModelAccuracy
from flexflow_tpu.keras.datasets import mnist
from flexflow_tpu.keras.layers import Dense, Input


def main():
    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0

    # teacher
    inp1 = Input((784,))
    d1 = Dense(512, activation="relu")
    d2 = Dense(512, activation="relu")
    d3 = Dense(10)
    teacher = Model(inp1, d3(d2(d1(inp1))))
    teacher.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    teacher.fit(x_train, y_train, epochs=2)

    w1 = d1.get_weights(teacher.ffmodel)
    w2 = d2.get_weights(teacher.ffmodel)
    w3 = d3.get_weights(teacher.ffmodel)

    # student: same shape, seeded from the teacher
    inp2 = Input((784,))
    s1 = Dense(512, activation="relu")
    s2 = Dense(512, activation="relu")
    s3 = Dense(10)
    student = Model(inp2, s3(s2(s1(inp2))))
    student.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
    s1.set_weights(student.ffmodel, *w1)
    s2.set_weights(student.ffmodel, *w2)
    s3.set_weights(student.ffmodel, *w3)

    gates = ([EpochVerifyMetrics(ModelAccuracy.MNIST_MLP)]
             if os.environ.get("FF_ACCURACY_GATE") else [])
    student.fit(x_train, y_train, epochs=2, callbacks=gates)


if __name__ == "__main__":
    main()
