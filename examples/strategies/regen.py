#!/usr/bin/env python
"""Regenerate the shipped example strategy files in this directory.

Each file pairs with a model graph + mesh recorded in MANIFEST
(`file | model | mesh | model-args`, the format tests/test_fflint.py and
ci/run_ci.sh's lint tier consume). All shipped strategies must lint
clean under `python -m flexflow_tpu.analysis ... --strict`.

Usage: python examples/strategies/regen.py
"""

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, "..", ".."))

from flexflow_tpu.analysis.models import build_model  # noqa: E402
from flexflow_tpu.parallel.pconfig import (CONTRACT, STAGE,  # noqa: E402
                                           ParallelConfig)
from flexflow_tpu.parallel.strategy import save_strategies_to_file  # noqa: E402

MESH = {"data": 4, "model": 2}


def _pc(ff, name, am, mesh):
    op = next(o for o in ff.ops if o.name == name)
    return ParallelConfig.from_axis_map(op.outputs[0].num_dims, mesh, am)


def transformer_dp():
    """Pure data parallelism over the default encoder classifier."""
    ff = build_model("transformer", MESH, {})
    from flexflow_tpu.search.driver import data_parallel_strategy

    return ("transformer_dp.ff", "transformer", "data=4,model=2", "",
            {n: _pc(ff, n, am, MESH)
             for n, am in data_parallel_strategy(ff, MESH).items()})


def transformer_tp():
    """Megatron pair on the FFN: ffn1 column-parallel (out-features over
    'model'), ffn2 row-parallel (CONTRACT) — the resharding-free TP idiom
    the CONTRACT sentinel exists for."""
    ff = build_model("transformer", MESH, {})
    strategies = {}
    for op in ff.ops:
        if op.name.startswith("ffn1_"):
            strategies[op.name] = _pc(ff, op.name,
                                      {"data": 0, "model": 2}, MESH)
        elif op.name.startswith("ffn2_"):
            strategies[op.name] = _pc(ff, op.name,
                                      {"data": 0, "model": CONTRACT}, MESH)
        elif op.name.startswith(("attn_", "ln", "res", "head", "pool")):
            strategies[op.name] = _pc(ff, op.name, {"data": 0}, MESH)
    return ("transformer_tp.ff", "transformer", "data=4,model=2", "",
            strategies)


def pipeline_pp():
    """Layer-stacked pipeline parallelism: the stack STAGEs over 'pipe',
    everything else rides data parallelism."""
    mesh = {"data": 2, "pipe": 2}
    ff = build_model("pipeline", mesh, {"layers": 4})
    strategies = {
        "stack": _pc(ff, "stack", {"data": 0, "pipe": STAGE}, mesh),
        "pool": _pc(ff, "pool", {"data": 0}, mesh),
        "head": _pc(ff, "head", {"data": 0}, mesh),
    }
    return ("pipeline_pp.ff", "pipeline", "data=2,pipe=2", "layers=4",
            strategies)


def dlrm_dp_tp():
    """The DLRM reference idiom (examples/native/dlrm_strategy.py):
    embedding channels over 'model', MLPs data-parallel."""
    mesh = MESH
    strategies = {}
    for i in range(8):
        strategies[f"emb_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0, "model": 1})
    for i in range(3):
        strategies[f"bot_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    for i in range(4):
        strategies[f"top_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    strategies["interact"] = ParallelConfig.from_axis_map(
        2, mesh, {"data": 0})
    return ("dlrm_dp_tp.ff", "dlrm", "data=4,model=2", "", strategies)


def dlrm_hetero():
    """Reference dlrm_strategy_hetero.cc: embeddings on the host CPU
    backend (device-type int 1), MLPs data-parallel on the pool."""
    mesh = MESH
    strategies = {}
    for i in range(8):
        strategies[f"emb_{i}"] = ParallelConfig.host(2)
    for i in range(3):
        strategies[f"bot_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    for i in range(4):
        strategies[f"top_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    strategies["interact"] = ParallelConfig.from_axis_map(
        2, mesh, {"data": 0})
    return ("dlrm_hetero.ff", "dlrm", "data=4,model=2", "", strategies)


def main():
    rows = []
    for gen in (transformer_dp, transformer_tp, pipeline_pp, dlrm_dp_tp,
                dlrm_hetero):
        fname, model, mesh, margs, strategies = gen()
        save_strategies_to_file(os.path.join(HERE, fname), strategies)
        rows.append(f"{fname} | {model} | {mesh} | {margs}")
        print(f"wrote {fname} ({len(strategies)} ops)")
    with open(os.path.join(HERE, "MANIFEST"), "w") as f:
        f.write("# shipped example strategies: file | model | mesh | "
                "model-args\n# regenerate with examples/strategies/regen.py;"
                " all must pass fflint --strict\n")
        f.write("\n".join(rows) + "\n")
    print("wrote MANIFEST")


if __name__ == "__main__":
    main()
