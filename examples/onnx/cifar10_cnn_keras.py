"""keras_exp flow for CIFAR-10 (reference:
examples/python/onnx/cifar10_cnn_keras.py — tf.keras -> keras2onnx ->
ONNXModelKeras). Built offline with the in-repo minimal codec; Keras
exporters emit Dense nodes plus standard Conv/MaxPool."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModelKeras
from flexflow_tpu.onnx import minionnx as mo


def export_keras_style(path):
    rs = np.random.RandomState(0)

    def conv_w(cout, cin, k, name):
        return mo.from_array(
            rs.randn(cout, cin, k, k).astype(np.float32) * 0.05, name)

    ws = [
        conv_w(32, 3, 3, "conv2d/kernel"),
        conv_w(64, 32, 3, "conv2d_1/kernel"),
        mo.from_array(rs.randn(512, 64 * 8 * 8).astype(np.float32) * 0.01,
                      "dense/kernel"),
        mo.from_array(rs.randn(10, 512).astype(np.float32) * 0.05,
                      "dense_1/kernel"),
    ]
    nodes = [
        mo.make_node("Conv", ["input", "conv2d/kernel"], ["c1"],
                     name="conv2d", kernel_shape=[3, 3], pads=[1, 1, 1, 1],
                     strides=[1, 1]),
        mo.make_node("Relu", ["c1"], ["a1"]),
        mo.make_node("MaxPool", ["a1"], ["p1"], kernel_shape=[2, 2],
                     strides=[2, 2]),
        mo.make_node("Conv", ["p1", "conv2d_1/kernel"], ["c2"],
                     name="conv2d_1", kernel_shape=[3, 3], pads=[1, 1, 1, 1],
                     strides=[1, 1]),
        mo.make_node("Relu", ["c2"], ["a2"]),
        mo.make_node("MaxPool", ["a2"], ["p2"], kernel_shape=[2, 2],
                     strides=[2, 2]),
        mo.make_node("Flatten", ["p2"], ["f"]),
        mo.make_node("Dense", ["f", "dense/kernel"], ["d1"], name="dense"),
        mo.make_node("Relu", ["d1"], ["a3"]),
        mo.make_node("Dense", ["a3", "dense_1/kernel"], ["logits"],
                     name="dense_1"),
    ]
    g = mo.make_graph(
        nodes, "keras_cifar10_cnn",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [64, 3, 32, 32])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [64, 10])],
        initializer=ws)
    mo.save(mo.make_model(g), path)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    path = "/tmp/cifar10_cnn_keras.onnx"
    export_keras_style(path)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModelKeras(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
