"""PyTorch -> ONNX -> import round trip for a residual network (reference:
examples/python/onnx/resnet_pt.py). Exercises the BatchNormalization and
residual-Add import paths."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx.torch_export import export


class BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.c1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.b1 = nn.BatchNorm2d(cout)
        self.c2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.b2 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.b2(self.c2(torch.relu(self.b1(self.c1(x)))))
        return torch.relu(y + idt)


class ResNet(nn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        self.stem = nn.Sequential(nn.Conv2d(3, 16, 3, 1, 1, bias=False),
                                  nn.BatchNorm2d(16), nn.ReLU())
        self.layer1 = nn.Sequential(BasicBlock(16, 16), BasicBlock(16, 16))
        self.layer2 = nn.Sequential(BasicBlock(16, 32, 2),
                                    BasicBlock(32, 32))
        self.pool = nn.AvgPool2d(16)
        self.flat = nn.Flatten()
        self.fc = nn.Linear(32, num_classes)

    def forward(self, x):
        x = self.layer2(self.layer1(self.stem(x)))
        return self.fc(self.flat(self.pool(x)))


def main():
    from flexflow_tpu.keras.datasets import cifar10
    path = "/tmp/resnet_pt.onnx"
    m = ResNet().eval()  # fold BN to inference form for a stable export
    export(m, torch.randn(4, 3, 32, 32), path,
           input_names=["input"], output_names=["logits"])

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
