"""CIFAR10 CNN from an ONNX graph (reference:
examples/python/onnx/cifar10_cnn.py), built with the in-repo minimal ONNX
codec — runs without the onnx package."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def export_cnn(path, batch):
    rs = np.random.RandomState(0)
    k1 = mo.from_array(rs.randn(32, 3, 3, 3).astype(np.float32), "k1")
    k2 = mo.from_array(rs.randn(64, 32, 3, 3).astype(np.float32), "k2")
    wd1 = mo.from_array(rs.randn(256, 64 * 16 * 16).astype(np.float32), "wd1")
    wd2 = mo.from_array(rs.randn(10, 256).astype(np.float32), "wd2")
    nodes = [
        mo.make_node("Conv", ["input", "k1"], ["c1"], name="conv1",
                     kernel_shape=[3, 3], strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["c1"], ["r1"]),
        mo.make_node("Conv", ["r1", "k2"], ["c2"], name="conv2",
                     kernel_shape=[3, 3], strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["c2"], ["r2"]),
        mo.make_node("MaxPool", ["r2"], ["p1"], kernel_shape=[2, 2],
                     strides=[2, 2], pads=[0, 0, 0, 0]),
        mo.make_node("Flatten", ["p1"], ["f"]),
        mo.make_node("Gemm", ["f", "wd1"], ["h"], name="fc1"),
        mo.make_node("Relu", ["h"], ["hr"]),
        mo.make_node("Gemm", ["hr", "wd2"], ["logits"], name="fc2"),
    ]
    g = mo.make_graph(
        nodes, "cifar10_cnn",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [batch, 3, 32, 32])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [batch, 10])],
        initializer=[k1, k2, wd1, wd2])
    mo.save(mo.make_model(g), path)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    cfg = FFConfig.parse_args()
    path = "/tmp/cifar10_cnn_mini.onnx"
    export_cnn(path, cfg.batch_size)

    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.02),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.reshape(-1, 1).astype(np.int32))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
