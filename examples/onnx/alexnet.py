"""AlexNet-CIFAR10 from an ONNX graph (reference:
examples/python/onnx/alexnet.py), built with the in-repo minimal ONNX codec."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def export_alexnet(path, batch):
    rs = np.random.RandomState(0)

    def conv(name, cin, cout, k):
        return mo.from_array(rs.randn(cout, cin, k, k).astype(np.float32), name)

    inits = [conv("k1", 3, 64, 11), conv("k2", 64, 192, 5),
             conv("k3", 192, 384, 3), conv("k4", 384, 256, 3),
             conv("k5", 256, 256, 3),
             mo.from_array(rs.randn(10, 256).astype(np.float32), "wfc")]
    nodes = [
        mo.make_node("Conv", ["input", "k1"], ["c1"], kernel_shape=[11, 11],
                     strides=[4, 4], pads=[2, 2, 2, 2]),
        mo.make_node("Relu", ["c1"], ["r1"]),
        mo.make_node("MaxPool", ["r1"], ["p1"], kernel_shape=[2, 2],
                     strides=[2, 2], pads=[0, 0, 0, 0]),
        mo.make_node("Conv", ["p1", "k2"], ["c2"], kernel_shape=[5, 5],
                     strides=[1, 1], pads=[2, 2, 2, 2]),
        mo.make_node("Relu", ["c2"], ["r2"]),
        mo.make_node("MaxPool", ["r2"], ["p2"], kernel_shape=[2, 2],
                     strides=[2, 2], pads=[0, 0, 0, 0]),
        mo.make_node("Conv", ["p2", "k3"], ["c3"], kernel_shape=[3, 3],
                     strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["c3"], ["r3"]),
        mo.make_node("Conv", ["r3", "k4"], ["c4"], kernel_shape=[3, 3],
                     strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["c4"], ["r4"]),
        mo.make_node("Conv", ["r4", "k5"], ["c5"], kernel_shape=[3, 3],
                     strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["c5"], ["r5"]),
        mo.make_node("GlobalAveragePool", ["r5"], ["g"]),
        mo.make_node("Flatten", ["g"], ["f"]),
        mo.make_node("Gemm", ["f", "wfc"], ["logits"], name="fc"),
    ]
    g = mo.make_graph(
        nodes, "alexnet",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [batch, 3, 224, 224])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [batch, 10])],
        initializer=inits)
    mo.save(mo.make_model(g), path)


def main():
    cfg = FFConfig.parse_args()
    cfg.batch_size = min(cfg.batch_size, 16)
    path = "/tmp/alexnet_mini.onnx"
    export_alexnet(path, cfg.batch_size)

    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 224, 224], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 2
    SingleDataLoader(ff, x, rs.randn(n, 3, 224, 224).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 10, (n, 1)).astype(np.int32))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
