"""keras_exp flow: a Keras-exported-style ONNX graph replayed through
ONNXModelKeras (reference: examples/python/onnx/mnist_mlp_keras.py +
python/flexflow/keras_exp/models/model.py — tf.keras -> keras2onnx ->
ONNXModelKeras). Built offline with the in-repo minimal codec; Keras
exporters emit Dense nodes, which ONNXModelKeras maps like Gemm."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModelKeras
from flexflow_tpu.onnx import minionnx as mo


def export_keras_style(path):
    rs = np.random.RandomState(0)
    w1 = mo.from_array(rs.randn(512, 784).astype(np.float32), "dense/kernel")
    w2 = mo.from_array(rs.randn(10, 512).astype(np.float32), "dense_1/kernel")
    nodes = [
        mo.make_node("Dense", ["input", "dense/kernel"], ["d1"], name="dense"),
        mo.make_node("Relu", ["d1"], ["a1"]),
        mo.make_node("Dense", ["a1", "dense_1/kernel"], ["logits"],
                     name="dense_1"),
    ]
    g = mo.make_graph(
        nodes, "keras_mlp",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [64, 784])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [64, 10])],
        initializer=[w1, w2])
    mo.save(mo.make_model(g), path)


def main():
    from flexflow_tpu.keras.datasets import mnist
    path = "/tmp/mnist_mlp_keras.onnx"
    export_keras_style(path)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="input")
    out = ONNXModelKeras(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = mnist.load_data()
    SingleDataLoader(ff, x,
                     x_train.reshape(-1, 784).astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
