"""Small residual network from an ONNX graph with BatchNormalization + Add
skip connections (reference: examples/python/onnx/resnet.py), built with the
in-repo minimal ONNX codec."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def export_resnet(path, batch):
    rs = np.random.RandomState(0)
    C = 32
    inits = [mo.from_array(rs.randn(C, 3, 3, 3).astype(np.float32), "k0")]
    nodes = [
        mo.make_node("Conv", ["input", "k0"], ["s0"], kernel_shape=[3, 3],
                     strides=[1, 1], pads=[1, 1, 1, 1]),
        mo.make_node("Relu", ["s0"], ["t0"]),
    ]
    prev = "t0"
    for i in range(2):  # two residual blocks
        ka, kb = f"ka{i}", f"kb{i}"
        inits += [mo.from_array(rs.randn(C, C, 3, 3).astype(np.float32), ka),
                  mo.from_array(rs.randn(C, C, 3, 3).astype(np.float32), kb)]
        nodes += [
            mo.make_node("Conv", [prev, ka], [f"a{i}"], kernel_shape=[3, 3],
                         strides=[1, 1], pads=[1, 1, 1, 1]),
            mo.make_node("BatchNormalization", [f"a{i}"], [f"bn{i}"]),
            mo.make_node("Relu", [f"bn{i}"], [f"ar{i}"]),
            mo.make_node("Conv", [f"ar{i}", kb], [f"b{i}"], kernel_shape=[3, 3],
                         strides=[1, 1], pads=[1, 1, 1, 1]),
            mo.make_node("Add", [f"b{i}", prev], [f"res{i}"]),
            mo.make_node("Relu", [f"res{i}"], [f"t{i + 1}"]),
        ]
        prev = f"t{i + 1}"
    inits.append(mo.from_array(rs.randn(10, C).astype(np.float32), "wfc"))
    nodes += [
        mo.make_node("GlobalAveragePool", [prev], ["g"]),
        mo.make_node("Flatten", ["g"], ["f"]),
        mo.make_node("Gemm", ["f", "wfc"], ["logits"], name="fc"),
    ]
    g = mo.make_graph(
        nodes, "mini_resnet",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [batch, 3, 32, 32])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [batch, 10])],
        initializer=inits)
    mo.save(mo.make_model(g), path)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    cfg = FFConfig.parse_args()
    path = "/tmp/resnet_mini.onnx"
    export_resnet(path, cfg.batch_size)

    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.02),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.reshape(-1, 1).astype(np.int32))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
