"""PyTorch -> ONNX -> import round trip for the CIFAR-10 CNN (reference:
examples/python/onnx/cifar10_cnn_pt.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx.torch_export import export


class CNN(nn.Module):
    """Matches the reference cifar10_cnn topology (2x[conv,conv,pool] +
    dense)."""

    def __init__(self):
        super().__init__()
        self.c1 = nn.Conv2d(3, 32, 3, padding=1)
        self.c2 = nn.Conv2d(32, 32, 3, padding=1)
        self.p1 = nn.MaxPool2d(2)
        self.c3 = nn.Conv2d(32, 64, 3, padding=1)
        self.c4 = nn.Conv2d(64, 64, 3, padding=1)
        self.p2 = nn.MaxPool2d(2)
        self.flat = nn.Flatten()
        self.d1 = nn.Linear(64 * 8 * 8, 512)
        self.d2 = nn.Linear(512, 10)

    def forward(self, x):
        x = self.p1(torch.relu(self.c2(torch.relu(self.c1(x)))))
        x = self.p2(torch.relu(self.c4(torch.relu(self.c3(x)))))
        return self.d2(torch.relu(self.d1(self.flat(x))))


def main():
    from flexflow_tpu.keras.datasets import cifar10
    path = "/tmp/cifar10_cnn_pt.onnx"
    export(CNN(), torch.randn(8, 3, 32, 32), path,
           input_names=["input"], output_names=["logits"])

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
