"""PyTorch -> ONNX -> import round trip for AlexNet (reference:
examples/python/onnx/alexnet_pt.py; CIFAR-size adaptation like the
reference's alexnet examples)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx.torch_export import export


class AlexNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, 5, padding=2), nn.ReLU(), nn.MaxPool2d(2),
            nn.Conv2d(64, 192, 5, padding=2), nn.ReLU(), nn.MaxPool2d(2),
            nn.Conv2d(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2d(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2d(256, 256, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        )
        self.classifier = nn.Sequential(
            nn.Flatten(),
            nn.Linear(256 * 4 * 4, 1024), nn.ReLU(),
            nn.Linear(1024, 1024), nn.ReLU(),
            nn.Linear(1024, 10),
        )

    def forward(self, x):
        return self.classifier(self.features(x))


def main():
    from flexflow_tpu.keras.datasets import cifar10
    path = "/tmp/alexnet_pt.onnx"
    export(AlexNet(), torch.randn(4, 3, 32, 32), path,
           input_names=["input"], output_names=["logits"])

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    SingleDataLoader(ff, x, x_train.astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
