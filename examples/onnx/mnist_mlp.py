"""MNIST MLP from an ONNX graph (reference:
examples/python/onnx/mnist_mlp.py). The graph is built and serialized with
the in-repo minimal ONNX codec (flexflow_tpu/onnx/minionnx.py), so this runs
without the onnx package."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx import minionnx as mo


def export_mlp(path):
    rs = np.random.RandomState(0)
    w1 = mo.from_array(rs.randn(512, 784).astype(np.float32), "w1")
    w2 = mo.from_array(rs.randn(512, 512).astype(np.float32), "w2")
    w3 = mo.from_array(rs.randn(10, 512).astype(np.float32), "w3")
    nodes = [
        mo.make_node("Gemm", ["input", "w1"], ["h1"], name="fc1"),
        mo.make_node("Relu", ["h1"], ["a1"]),
        mo.make_node("Gemm", ["a1", "w2"], ["h2"], name="fc2"),
        mo.make_node("Relu", ["h2"], ["a2"]),
        mo.make_node("Gemm", ["a2", "w3"], ["logits"], name="fc3"),
    ]
    g = mo.make_graph(
        nodes, "mnist_mlp",
        [mo.make_tensor_value_info("input", mo.DT_FLOAT, [64, 784])],
        [mo.make_tensor_value_info("logits", mo.DT_FLOAT, [64, 10])],
        initializer=[w1, w2, w3])
    mo.save(mo.make_model(g), path)


def main():
    from flexflow_tpu.keras.datasets import mnist
    path = "/tmp/mnist_mlp_mini.onnx"
    export_mlp(path)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = mnist.load_data()
    SingleDataLoader(ff, x, x_train.reshape(-1, 784).astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
