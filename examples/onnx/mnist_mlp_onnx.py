"""MNIST MLP via the ONNX importer (reference:
examples/python/onnx/mnist_mlp_pt.py: torch -> onnx export -> ONNXModel).

The `onnx` package is not bundled in this image; this example exports with
torch.onnx when available and exits gracefully otherwise."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    try:
        import onnx  # noqa: F401
        import torch
        import torch.nn as nn
    except ImportError as e:
        print(f"SKIP: {e} (onnx export path unavailable in this image)")
        return

    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.keras.datasets import mnist
    from flexflow_tpu.onnx import ONNXModel

    net = nn.Sequential(nn.Linear(784, 512), nn.ReLU(),
                        nn.Linear(512, 512), nn.ReLU(), nn.Linear(512, 10))
    path = "/tmp/mnist_mlp.onnx"
    torch.onnx.export(net, torch.randn(64, 784), path,
                      input_names=["input"], output_names=["output"])

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    SingleDataLoader(ff, x, x_train)
    SingleDataLoader(ff, ff.label_tensor, y_train)
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
