"""PyTorch -> ONNX -> import round trip for the MNIST MLP (reference:
examples/python/onnx/mnist_mlp_pt.py, which runs torch.onnx.export then
replays the file). Works without the onnx package: the export goes through
flexflow_tpu.onnx.torch_export and the import through the minionnx codec."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.onnx import ONNXModel
from flexflow_tpu.onnx.torch_export import export


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 512)
        self.fc2 = nn.Linear(512, 512)
        self.fc3 = nn.Linear(512, 10)

    def forward(self, x):
        return self.fc3(torch.relu(self.fc2(torch.relu(self.fc1(x)))))


def main():
    from flexflow_tpu.keras.datasets import mnist
    path = "/tmp/mnist_mlp_pt.onnx"
    export(MLP(), torch.randn(64, 784), path,
           input_names=["input"], output_names=["logits"])

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="input")
    out = ONNXModel(path).apply(ff, {"input": x})
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    (x_train, y_train), _ = mnist.load_data()
    SingleDataLoader(ff, x,
                     x_train.reshape(-1, 784).astype(np.float32) / 255.0)
    SingleDataLoader(ff, ff.label_tensor,
                     y_train.astype(np.int32).reshape(-1, 1))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
