"""Transformer benchmark app (reference: examples/cpp/Transformer/
transformer.cc — encoder-decoder, hidden 512, 16 heads, 12 layers, seq 128,
MSE head, SGD 0.01).

Run: python examples/native/transformer.py [--num-layers N] [--hidden-size H]
     [--sequence-length S] [--num-heads A] [-b BATCH] [--budget N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import (TransformerConfig,
                                             build_reference_transformer)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=12)
    p.add_argument("--hidden-size", type=int, default=512)
    p.add_argument("--sequence-length", type=int, default=128)
    p.add_argument("--num-heads", type=int, default=16)
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()
    tf_cfg = TransformerConfig(hidden_size=args.hidden_size,
                               num_heads=args.num_heads,
                               num_layers=args.num_layers,
                               sequence_length=args.sequence_length)

    ff = FFModel(cfg)
    x, out = build_reference_transformer(ff, cfg.batch_size, tf_cfg)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.METRICS_MEAN_SQUARED_ERROR], final_tensor=out)

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xd = rs.randn(n, tf_cfg.sequence_length,
                  tf_cfg.hidden_size).astype(np.float32)
    yd = rs.randn(n, tf_cfg.sequence_length, 1).astype(np.float32)
    SingleDataLoader(ff, x, xd)
    SingleDataLoader(ff, ff.label_tensor, yd)
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
