"""MNIST MLP via the native API (reference: examples/python/native/mnist_mlp.py).

Run: python examples/native/mnist_mlp.py [-e EPOCHS] [-b BATCH]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.keras.datasets import mnist


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="x")
    t = ff.dense(x, 512, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 512, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 10, name="fc3")
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    SingleDataLoader(ff, x, x_train)
    SingleDataLoader(ff, ff.label_tensor, y_train)
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
