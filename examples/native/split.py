"""Split op exercise (reference: examples/python/native/split.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def main():
    rs = np.random.RandomState(0)
    x = rs.randn(256, 64).astype(np.float32)
    y = rs.randint(0, 4, (256, 1)).astype(np.int32)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 64], name="input")
    parts = ff.split(inp, 2, axis=1)
    a = ff.dense(parts[0], 32, ActiMode.AC_MODE_RELU)
    b = ff.dense(parts[1], 32, ActiMode.AC_MODE_RELU)
    t = ff.concat([a, b], axis=1)
    t = ff.dense(t, 4)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=1)


if __name__ == "__main__":
    main()
