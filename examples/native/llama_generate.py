"""KV-cache autoregressive generation on the Llama-family decoder
(runtime/generation.py): one jitted prefill + lax.scan decode program.

Net-new vs the reference (its inference mode, CompMode::COMP_MODE_INFERENCE,
re-runs the full training graph on the growing prefix); shows greedy and
temperature/top-k sampling plus eos early-stop padding.

Run: python examples/native/llama_generate.py [--hidden H] [--num-layers N]
     [--max-new-tokens T] [-b BATCH]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-kv-heads", type=int, default=2)
    p.add_argument("--prompt-length", type=int, default=16)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--vocab", type=int, default=1024)
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()

    ff = FFModel(cfg)
    tokens, logits = llama_lm(ff, cfg.batch_size,
                              seq_len=args.prompt_length,
                              hidden=args.hidden, layers=args.num_layers,
                              heads=args.num_heads,
                              kv_heads=args.num_kv_heads,
                              vocab_size=args.vocab)
    ff.compile(final_tensor=logits)

    rs = np.random.RandomState(42)
    prompt = rs.randint(0, args.vocab,
                        (cfg.batch_size, args.prompt_length)).astype(np.int32)

    t0 = time.time()
    greedy = ff.generate(prompt, args.max_new_tokens)
    compile_s = time.time() - t0
    t0 = time.time()
    greedy = ff.generate(prompt, args.max_new_tokens)
    steady_s = time.time() - t0
    n_new = cfg.batch_size * args.max_new_tokens
    print(f"greedy: {greedy.shape} compile {compile_s:.1f}s, steady "
          f"{steady_s * 1e3:.1f}ms = {n_new / steady_s:.1f} tokens/s")
    print("greedy row 0:", greedy[0].tolist())

    sampled = ff.generate(prompt, args.max_new_tokens, temperature=0.8,
                          top_k=40, seed=7)
    print("sampled row 0:", sampled[0].tolist())
    assert sampled.shape == greedy.shape
    assert (greedy[:, :args.prompt_length] == prompt).all()
    print("OK")


if __name__ == "__main__":
    main()
