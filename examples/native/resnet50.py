"""ResNet-50 training throughput via the native API (reference:
examples/cpp/ResNet/resnet.cc — the BASELINE.md north-star model).

Synthetic data; prints samples/s like the reference apps
(alexnet.cc:127-128). Use --image-size to scale down for CPU smoke runs.

Run: python examples/native/resnet50.py [-b BATCH] [--iters N]
     [--image-size 224] [--budget B --export s.txt]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)
from flexflow_tpu.models.cnn import resnet50


def main():
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    extra, rest = ap.parse_known_args()
    cfg = FFConfig.parse_args(rest)

    ff = FFModel(cfg)
    x, out = resnet50(ff, cfg.batch_size, num_classes=extra.num_classes,
                      image_size=extra.image_size)
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    rs = np.random.RandomState(0)
    B = cfg.batch_size
    batch = {
        "input": rs.randn(B, 3, extra.image_size,
                          extra.image_size).astype(np.float32),
        "label": rs.randint(0, extra.num_classes, (B, 1)).astype(np.int32),
    }
    import jax

    ff._run_train_step(batch)  # compile
    jax.block_until_ready(ff.params)
    t0 = time.time()
    for _ in range(extra.iters):
        ff._run_train_step(batch)
    jax.block_until_ready(ff.params)
    dt = time.time() - t0
    print(f"THROUGHPUT = {extra.iters * B / dt:.2f} samples/s")


if __name__ == "__main__":
    main()
