"""MNIST CNN via the native API (reference: examples/python/native/mnist_cnn.py)."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          PoolType, SGDOptimizer, SingleDataLoader)


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 1, 28, 28).astype(np.float32) / 255.0
    y = y.reshape(-1, 1).astype(np.int32)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    t = inp = ff.create_tensor([cfg.batch_size, 1, 28, 28], name="input")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 128, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
