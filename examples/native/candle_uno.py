"""CANDLE Uno drug-response model via the native API (reference:
examples/cpp/candle_uno/candle_uno.cc — 7-input concat MLP).

Synthetic feature data (the reference reads CSVs from the CANDLE project).

Run: python examples/native/candle_uno.py [-b BATCH] [-e EPOCHS]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.cnn import candle_uno


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inputs, out = candle_uno(ff, cfg.batch_size,
                             dense_layers=(1000, 1000, 1000),
                             dense_feature_layers=(1000, 1000, 1000))
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.METRICS_MEAN_SQUARED_ERROR], final_tensor=out)

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    for name, t in inputs.items():
        SingleDataLoader(ff, t, rs.randn(n, t.dims[1]).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor, rs.rand(n, 1).astype(np.float32))
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
