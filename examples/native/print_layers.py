"""Graph inspection (reference: examples/python/native/print_layers.py +
print_input.py): dump every op with shapes, weights, and the resolved
strategy after compile."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import ActiMode, FFConfig, FFModel


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    t = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    t = ff.conv2d(t, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 10)
    ff.compile(optimizer=None)
    for op in ff.ops:
        ws = {w.name: w.shape for w in op.weight_specs()}
        outs = [o.dims for o in op.outputs]
        am = ff.executor._op_axis_maps.get(op.name, {})
        print(f"{op.name:14s} {type(op).__name__:12s} out={outs} "
              f"weights={ws} axis_map={am}")


if __name__ == "__main__":
    main()
