"""Standalone multi-head attention training (reference:
examples/python/native/multi_head_attention.py — a single MHA layer trained
with MSE against random targets).

Run: python examples/native/multi_head_attention.py [-b BATCH] [-e EPOCHS]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def main():
    cfg = FFConfig.parse_args()
    B, seq, hidden, heads = cfg.batch_size, 10, 64, 4
    ff = FFModel(cfg)
    q = ff.create_tensor([B, seq, hidden], name="query")
    k = ff.create_tensor([B, seq, hidden], name="key")
    v = ff.create_tensor([B, seq, hidden], name="value")
    out = ff.multihead_attention(q, k, v, embed_dim=hidden, num_heads=heads,
                                 name="mha")
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.METRICS_MEAN_SQUARED_ERROR], final_tensor=out)

    rs = np.random.RandomState(0)
    n = B * 4
    dat = rs.randn(n, seq, hidden).astype(np.float32)
    SingleDataLoader(ff, q, dat)
    SingleDataLoader(ff, k, dat)
    SingleDataLoader(ff, v, dat)
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randn(n, seq, hidden).astype(np.float32))
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
