"""Input/weight inspection (reference:
examples/python/native/print_input.py + tensor_attach.py patterns): build a
tiny model, attach a known input batch, run forward, and print/verify the
tensors coming back from the device."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import ActiMode, FFConfig, FFModel


def main():
    cfg = FFConfig.parse_args()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    inp = ff.create_tensor([8, 16], name="input")
    out = ff.dense(inp, 4, ActiMode.AC_MODE_NONE, name="fc")
    ff.compile(optimizer=None, final_tensor=out)

    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16) / 100.0
    y = np.asarray(ff.predict({"input": x}))
    print("input[0,:5]  =", x[0, :5])
    print("output[0]    =", y[0])
    k = ff.get_weights("fc", "kernel")
    b = ff.get_weights("fc", "bias")
    np.testing.assert_allclose(y, x @ k + b, rtol=1e-4, atol=1e-5)
    print("forward matches input @ kernel + bias OK")


if __name__ == "__main__":
    main()
