"""Tensor attach round-trip (reference:
examples/python/native/tensor_attach.py — numpy attach to Legion regions via
Tensor::set_tensor/get_tensor, model.cu:314-437): set every weight of a model
from host arrays, read them back, verify bit-exact round-trip, then train one
epoch to confirm the attached weights are live."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 32], name="input")
    t = ff.dense(inp, 64, ActiMode.AC_MODE_RELU, name="fc1")
    out = ff.dense(t, 4, name="fc2")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    rs = np.random.RandomState(7)
    attached = {}
    for op_name in ("fc1", "fc2"):
        for w in ff.get_op_by_name(op_name).weight_specs():
            a = rs.randn(*w.shape).astype(np.float32) * 0.1
            ff.set_weights(op_name, w.name, a)
            attached[(op_name, w.name)] = a
    for (op_name, wname), a in attached.items():
        np.testing.assert_array_equal(ff.get_weights(op_name, wname), a)
    print(f"attached + round-tripped {len(attached)} weights bit-exact")

    n = cfg.batch_size * 4
    SingleDataLoader(ff, inp, rs.randn(n, 32).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 4, (n, 1)).astype(np.int32))
    ff.fit(epochs=1)
    drift = np.abs(ff.get_weights("fc1", "kernel")
                   - attached[("fc1", "kernel")]).max()
    assert drift > 0, "training did not update attached weights"
    print("post-train drift:", float(drift))


if __name__ == "__main__":
    main()
