"""DLRM app (reference: examples/cpp/DLRM/dlrm.cc, run_summit.sh config).

Run: python examples/native/dlrm.py [-b BATCH] [--arch-embedding-size N]...
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.dlrm import dlrm


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arch-sparse-feature-size", type=int, default=64)
    p.add_argument("--arch-embedding-size", type=int, default=100000)
    p.add_argument("--num-tables", type=int, default=8)
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()

    ff = FFModel(cfg)
    dense_in, sparse_ins, out = dlrm(
        ff, cfg.batch_size,
        embedding_size=args.arch_sparse_feature_size,
        embedding_entries=args.arch_embedding_size,
        num_tables=args.num_tables)
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               [MetricsType.METRICS_MEAN_SQUARED_ERROR], final_tensor=out)

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 8
    SingleDataLoader(ff, dense_in, rs.randn(n, 64).astype(np.float32))
    for i, s in enumerate(sparse_ins):
        SingleDataLoader(ff, s, rs.randint(
            0, args.arch_embedding_size, (n, 1)).astype(np.int32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.rand(n, 1).astype(np.float32))
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
