"""CIFAR10 CNN with attached conv weights (reference:
examples/python/native/cifar10_cnn_attach.py): seed the first conv layer from
host arrays before training."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          PoolType, SGDOptimizer, SingleDataLoader)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    (x, y), _ = cifar10.load_data()
    x = x.astype(np.float32) / 255.0
    y = y.reshape(-1, 1).astype(np.int32)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    t = ff.conv2d(inp, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU,
                  name="conv1")
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 128, ActiMode.AC_MODE_RELU, name="fc1")
    out = ff.dense(t, 10, name="fc2")
    ff.compile(SGDOptimizer(lr=0.02),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    rs = np.random.RandomState(0)
    k = ff.get_weights("conv1", "kernel")
    seeded = rs.randn(*k.shape).astype(np.float32) * 0.05
    ff.set_weights("conv1", "kernel", seeded)

    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=int(os.environ.get("EPOCHS", 1)))
    print("conv1 drift:",
          float(np.abs(ff.get_weights("conv1", "kernel") - seeded).max()))


if __name__ == "__main__":
    main()
