"""AlexNet on CIFAR-10 via the native API (reference:
examples/cpp/AlexNet/alexnet.cc:34-130 — the canonical train loop).

Run: python examples/native/alexnet.py [-e EPOCHS] [-b BATCH]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.keras.datasets import cifar10
from flexflow_tpu.models.cnn import alexnet_cifar10


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x, out = alexnet_cifar10(ff, cfg.batch_size)
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY,
                MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
               final_tensor=out)

    (x_train, y_train), _ = cifar10.load_data()
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32).reshape(-1, 1)
    SingleDataLoader(ff, x, x_train)
    SingleDataLoader(ff, ff.label_tensor, y_train)
    ff.init_layers()
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
