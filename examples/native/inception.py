"""Full InceptionV3 via the native API (reference:
examples/cpp/InceptionV3/inception.cc:150-174). The branchy graph is the
op-parallel search showcase: run with --budget N --export s.txt to let the
MCMC search discover a strategy, then --import s.txt to train under it.

Run: python examples/native/inception.py [-b BATCH] [--iters N]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer)
from flexflow_tpu.models.cnn import inception_v3


def main():
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--iters", type=int, default=4)
    extra, rest = ap.parse_known_args()
    cfg = FFConfig.parse_args(rest)

    ff = FFModel(cfg)
    x, out = inception_v3(ff, cfg.batch_size, num_classes=10)
    ff.compile(SGDOptimizer(lr=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY,
                MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
               final_tensor=out)

    rs = np.random.RandomState(0)
    B = cfg.batch_size
    batch = {"input": rs.randn(B, 3, 299, 299).astype(np.float32),
             "label": rs.randint(0, 10, (B, 1)).astype(np.int32)}
    import jax

    ff._run_train_step(batch)
    jax.block_until_ready(ff.params)
    t0 = time.time()
    for _ in range(extra.iters):
        ff._run_train_step(batch)
    jax.block_until_ready(ff.params)
    dt = time.time() - t0
    print(f"THROUGHPUT = {extra.iters * B / dt:.2f} samples/s")


if __name__ == "__main__":
    main()
