"""NMT LSTM seq2seq driver (reference: nmt/nmt.cc:31-99 — 2 layers, seq 20,
hidden/embed 2048, vocab 20k, 64 samples/worker, 10 iters, wall-clock
print). Defaults scaled by --hidden/--vocab for smoke runs."""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.nmt import nmt_seq2seq


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--seq", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=2000)
    ap.add_argument("--layers", type=int, default=2)
    args = ap.parse_args()

    cfg = FFConfig(batch_size=args.batch, epochs=1)
    ff = FFModel(cfg)
    src, tgt, logits = nmt_seq2seq(ff, args.batch, src_len=args.seq,
                                   tgt_len=args.seq, embed_size=args.hidden,
                                   hidden_size=args.hidden,
                                   vocab_size=args.vocab,
                                   num_layers=args.layers)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    rs = np.random.RandomState(0)
    n = args.batch * 2
    SingleDataLoader(ff, src, rs.randint(0, args.vocab, (n, args.seq))
                     .astype(np.int32))
    SingleDataLoader(ff, tgt, rs.randint(0, args.vocab, (n, args.seq))
                     .astype(np.int32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, args.vocab, (n, args.seq, 1))
                     .astype(np.int32))

    batch = ff._stage_batch()
    ff._run_train_step(batch)  # compile
    t0 = time.time()
    loss = None
    for _ in range(args.iters):
        loss, _ = ff._run_train_step(ff._stage_batch())
    loss = float(loss)
    dt = time.time() - t0
    # reference wall-clock print (nmt.cc:86-99)
    print(f"NMT: {args.iters} iters in {dt:.3f}s "
          f"({args.iters * args.batch / dt:.1f} samples/s), loss={loss:.4f}")
    assert np.isfinite(loss)


if __name__ == "__main__":
    main()
