"""Generate a DLRM parallelization strategy file (reference:
examples/cpp/DLRM/strategies/dlrm_strategy.py + dlrm_strategy_hetero.cc —
programmatic strategy generation placing embedding tables across devices
while MLPs run data-parallel).

Mesh terms: each embedding output's channel dim shards over 'model' (table
vocab rows stay whole, channels split — the memory-balancing analog of the
reference's per-GPU table placement), interaction + MLPs run data-parallel.

Usage: python examples/native/dlrm_strategy.py --out dlrm_strategy.txt
       [--num-tables 8] [--data 4] [--model 2] [--hetero]
Then:  python examples/native/dlrm.py --import dlrm_strategy.txt

--hetero emits the reference's HETEROGENEOUS strategy
(dlrm_strategy_hetero.cc): embedding tables on the HOST CPU backend
(device_type CPU in the file, the embedding_avx2.cc analog), MLPs
data-parallel on the accelerator pool.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="dlrm_strategy.txt")
    ap.add_argument("--num-tables", type=int, default=8)
    ap.add_argument("--data", type=int, default=4)
    ap.add_argument("--model", type=int, default=2)
    ap.add_argument("--mlp-bot", type=int, default=3)
    ap.add_argument("--mlp-top", type=int, default=4)
    ap.add_argument("--hetero", action="store_true",
                    help="embeddings on the host CPU backend "
                         "(dlrm_strategy_hetero.cc analog)")
    args = ap.parse_args()

    from flexflow_tpu.parallel.pconfig import ParallelConfig
    from flexflow_tpu.parallel.strategy import save_strategies_to_file

    mesh = {"data": args.data, "model": args.model}
    strategies = {}
    # embeddings: hetero -> host CPU backend (reference CPU embeddings);
    # otherwise batch over 'data', embedding channels over 'model'
    for i in range(args.num_tables):
        strategies[f"emb_{i}"] = (
            ParallelConfig.host(2) if args.hetero
            else ParallelConfig.from_axis_map(
                2, mesh, {"data": 0, "model": 1}))
    # MLPs: pure data parallel (the reference keeps MLPs data-parallel and
    # embeddings placed, run_summit.sh strategy files)
    for i in range(args.mlp_bot):
        strategies[f"bot_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    for i in range(args.mlp_top):
        strategies[f"top_{i}"] = ParallelConfig.from_axis_map(
            2, mesh, {"data": 0})
    strategies["interact"] = ParallelConfig.from_axis_map(
        2, mesh, {"data": 0})

    save_strategies_to_file(args.out, strategies)
    print(f"wrote {len(strategies)} op strategies for mesh {mesh} "
          f"to {args.out}")


if __name__ == "__main__":
    main()
