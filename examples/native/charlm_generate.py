"""Character-level language model trained on REAL text (this repo's own
README) and sampled with the KV-cache generation stack — the full
train -> generate loop on data that ships with the repo, no downloads.

Uses the Llama-family decoder (RMSNorm + RoPE + GQA + SwiGLU), a cosine
LR schedule, and temperature sampling with ragged prompts.

Run: python examples/native/charlm_generate.py [-e EPOCHS] [-b BATCH]
     [--hidden H] [--num-layers L] [--sample-chars N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, SingleDataLoader, WarmupCosine)
from flexflow_tpu.models.llama import llama_lm

README = os.path.join(os.path.dirname(__file__), "..", "..", "README.md")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--sample-chars", type=int, default=80)
    p.add_argument("--prompt", type=str, default="flexflow_tpu is ")
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()

    text = open(README, encoding="utf-8").read()
    chars = sorted(set(text))
    vocab = len(chars) + 1  # 0 reserved for pad
    c2i = {c: i + 1 for i, c in enumerate(chars)}
    i2c = {i + 1: c for i, c in enumerate(chars)}
    ids = np.array([c2i[c] for c in text], np.int32)

    seq = args.seq
    n = (len(ids) - 1) // seq
    n = (n // cfg.batch_size) * cfg.batch_size  # full batches
    x = ids[: n * seq].reshape(n, seq)
    y = ids[1: n * seq + 1].reshape(n, seq)[..., None]
    print(f"README char-LM: {len(ids)} chars, vocab {vocab}, "
          f"{n} sequences of {seq}")

    ff = FFModel(cfg)
    tokens, logits = llama_lm(ff, cfg.batch_size, seq_len=seq,
                              hidden=args.hidden, layers=args.num_layers,
                              heads=args.num_heads, kv_heads=2,
                              vocab_size=vocab, tie_embeddings=True)
    steps = max(1, n // cfg.batch_size) * max(cfg.epochs, 1)
    ff.compile(AdamOptimizer(alpha=3e-3,
                             schedule=WarmupCosine(min(10, steps // 4 + 1),
                                                   steps + 1)),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)
    SingleDataLoader(ff, tokens, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit()

    known = [c for c in args.prompt if c in c2i]
    if len(known) != len(args.prompt):
        dropped = [c for c in args.prompt if c not in c2i]
        print(f"warning: dropping prompt chars not in the README vocab: "
              f"{dropped!r}")
    if not known:
        raise SystemExit("prompt has no characters from the README vocab")
    prompt_ids = np.array([[c2i[c] for c in known]], np.int32)
    out = ff.generate(prompt_ids, args.sample_chars, temperature=0.5,
                      top_k=12, seed=0)
    sample = "".join(i2c.get(int(i), "?") for i in out[0])
    print("sample:", repr(sample))


if __name__ == "__main__":
    main()
