"""Branchy CIFAR-10 CNN with concat (reference:
examples/python/native/cifar10_cnn_concat.py) — the graph shape where the
strategy search can discover op placement; pass --budget to search."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def main():
    from flexflow_tpu.keras.datasets import cifar10
    (x, y), _ = cifar10.load_data()
    x = x.astype(np.float32) / 255.0
    y = y.reshape(-1, 1).astype(np.int32)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 3, 32, 32], name="input")
    a = ff.conv2d(inp, 32, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU, name="br_a")
    b = ff.conv2d(inp, 32, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU, name="br_b")
    t = ff.concat([a, b], axis=1)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.conv2d(t, 64, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = ff.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.dense(t, 256, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=int(os.environ.get("EPOCHS", 2)))


if __name__ == "__main__":
    main()
