"""BERT proxy benchmark via the native API (reference:
examples/python/native/bert_proxy_native.py — BERT-Large-shaped encoder run
on random tokens to measure training step time).

Run: python examples/native/bert_proxy.py [-b BATCH] [--layers N]
     [--hidden H] [--seq-len S] [--iters N]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType)
from flexflow_tpu.models.bert import bert_base


def main():
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--iters", type=int, default=8)
    extra, rest = ap.parse_known_args()
    cfg = FFConfig.parse_args(rest)

    ff = FFModel(cfg)
    tokens, pos, out = bert_base(ff, cfg.batch_size, seq_len=extra.seq_len,
                                 hidden=extra.hidden, layers=extra.layers,
                                 heads=extra.heads, vocab_size=extra.vocab)
    ff.compile(AdamOptimizer(alpha=cfg.learning_rate),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)

    rs = np.random.RandomState(0)
    B = cfg.batch_size
    batch = {
        "input": rs.randint(0, extra.vocab, (B, extra.seq_len)).astype(np.int32),
        "positions": np.tile(np.arange(extra.seq_len, dtype=np.int32), (B, 1)),
        "label": rs.randint(0, 2, (B, 1)).astype(np.int32),
    }
    import jax

    ff._run_train_step(batch)
    jax.block_until_ready(ff.params)
    t0 = time.time()
    for _ in range(extra.iters):
        ff._run_train_step(batch)
    jax.block_until_ready(ff.params)
    dt = time.time() - t0
    print(f"THROUGHPUT = {extra.iters * B / dt:.2f} samples/s "
          f"({dt / extra.iters * 1000:.1f} ms/iter)")


if __name__ == "__main__":
    main()
