"""MNIST MLP with external weight attach (reference:
examples/python/native/mnist_mlp_attach.py — numpy attach via
Parameter::set_weights): initialize fc1 from a host-computed PCA-like
projection, train, read weights back."""
import os, sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import numpy as np

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)


def main():
    from flexflow_tpu.keras.datasets import mnist
    (x, y), _ = mnist.load_data()
    x = x.reshape(-1, 784).astype(np.float32) / 255.0
    y = y.reshape(-1, 1).astype(np.int32)

    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    inp = ff.create_tensor([cfg.batch_size, 784], name="input")
    t = ff.dense(inp, 128, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 10, name="fc2")
    ff.compile(SGDOptimizer(lr=0.05),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])

    # attach externally computed weights (reference set_weights flow)
    rs = np.random.RandomState(0)
    w = rs.randn(784, 128).astype(np.float32) * 0.05
    ff.set_weights("fc1", "kernel", w)
    np.testing.assert_allclose(ff.get_weights("fc1", "kernel"), w, rtol=1e-6)

    SingleDataLoader(ff, inp, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=int(os.environ.get("EPOCHS", 1)))
    back = ff.get_weights("fc1", "kernel")
    print("fc1 kernel drifted by", float(np.abs(back - w).max()))


if __name__ == "__main__":
    main()
