"""Train + DECODE an encoder-decoder transformer (round 5).

The reference's NMT subsystem trains its seq2seq models but has no
decode story (inference = the training graph forward). This example
trains the token-level seq2seq LM on a synthetic copy task and then
serves it with generate_seq2seq — one encode, static cross-attention
k/v, KV-cached decoder scan (runtime/seq2seq_generation.py).

Run: python examples/native/seq2seq_translate.py  # ~100 s on the
2-device CPU mesh; reaches 100% held-out copy accuracy
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          AdamOptimizer, SingleDataLoader)
from flexflow_tpu.models.transformer import seq2seq_lm


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int,
                    default=int(os.environ.get("EPOCHS", 40)))
    ap.add_argument("--vocab", type=int, default=30)
    ap.add_argument("--seq", type=int, default=6)
    args, _ = ap.parse_known_args()

    bos, vocab, s = 1, args.vocab, args.seq
    cfg = FFConfig(batch_size=32, mesh_shape={"data": 2}, seed=0)
    ff = FFModel(cfg)
    src_t, tgt_t, logits = seq2seq_lm(ff, cfg.batch_size, src_len=s,
                                      tgt_len=s, hidden=64, layers=2,
                                      heads=4, vocab_size=vocab)
    ff.compile(AdamOptimizer(alpha=3e-3),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)

    # copy task: target = source, teacher-forced with BOS-shifted input
    rs = np.random.RandomState(0)
    n = 4096
    src = rs.randint(2, vocab, (n, s)).astype(np.int32)
    tgt_in = np.concatenate([np.full((n, 1), bos, np.int32),
                             src[:, :-1]], axis=1)
    SingleDataLoader(ff, src_t, src)
    SingleDataLoader(ff, tgt_t, tgt_in)
    SingleDataLoader(ff, ff.label_tensor, src.copy())
    ff.fit(epochs=args.epochs)

    # decode a held-out batch and report copy accuracy
    test = rs.randint(2, vocab, (8, s)).astype(np.int32)
    out = ff.generate_seq2seq(test, max_new_tokens=s, bos_token_id=bos)
    hyp = out[:, 1:1 + s]
    acc = float((hyp == test).mean())
    print(f"decode copy accuracy: {100 * acc:.1f}% "
          f"({(hyp == test).sum()}/{test.size} tokens)")
    print("sample src:", test[0].tolist())
    print("sample hyp:", hyp[0].tolist())


if __name__ == "__main__":
    main()
