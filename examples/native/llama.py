"""Llama-family decoder LM (models/llama.py): RMSNorm + RoPE + grouped-query
attention + SwiGLU, causal next-token training on synthetic tokens.

Net-new vs the reference model zoo (its newest workload is the cuDNN-MHA
encoder, examples/cpp/Transformer) — the modern decoder family the TPU
rebuild targets, deliberately head_dim-128-friendly for the MXU.

Run: python examples/native/llama.py [--hidden H] [--num-layers N]
     [--num-heads A] [--num-kv-heads G] [--sequence-length S] [-b BATCH]
     [-e EPOCHS] [--budget N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                          SGDOptimizer, SingleDataLoader)
from flexflow_tpu.models.llama import llama_lm


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-kv-heads", type=int, default=2)
    p.add_argument("--sequence-length", type=int, default=128)
    p.add_argument("--vocab", type=int, default=1024)
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()

    ff = FFModel(cfg)
    tokens, logits = llama_lm(ff, cfg.batch_size,
                              seq_len=args.sequence_length,
                              hidden=args.hidden, layers=args.num_layers,
                              heads=args.num_heads,
                              kv_heads=args.num_kv_heads,
                              vocab_size=args.vocab)
    ff.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)

    rs = np.random.RandomState(0)
    n = cfg.batch_size * 4
    x = rs.randint(0, args.vocab, (n, args.sequence_length)).astype(np.int32)
    y = ((x + 1) % args.vocab)[..., None].astype(np.int32)  # successor task
    SingleDataLoader(ff, tokens, x)
    SingleDataLoader(ff, ff.label_tensor, y)
    ff.fit(epochs=cfg.epochs)


if __name__ == "__main__":
    main()
