"""Vision Transformer (models/vit.py): patchify-conv + RoPE pre-norm
encoder + mean-pool head, trained on synthetic images.

Net-new model family vs the reference zoo (its vision workloads are all
CNNs). Run: python examples/native/vit.py [-b BATCH] [-e EPOCHS]
[--image-size S] [--patch P] [--hidden H] [--num-layers L]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

from flexflow_tpu import (AdamOptimizer, FFConfig, FFModel, LossType,
                          MetricsType, SingleDataLoader, WarmupCosine)
from flexflow_tpu.models.vit import vit


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--patch", type=int, default=8)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--classes", type=int, default=10)
    args, _ = p.parse_known_args()
    cfg = FFConfig.parse_args()

    ff = FFModel(cfg)
    x, logits = vit(ff, cfg.batch_size, image_size=args.image_size,
                    patch_size=args.patch, hidden=args.hidden,
                    layers=args.num_layers, heads=args.num_heads,
                    num_classes=args.classes)
    ff.compile(AdamOptimizer(alpha=1e-3,
                             schedule=WarmupCosine(warmup_steps=5,
                                                   total_steps=200)),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=logits)

    n = 4 * cfg.batch_size
    rs = np.random.RandomState(0)
    xd = rs.randn(n, 3, args.image_size, args.image_size).astype(np.float32)
    yd = rs.randint(0, args.classes, (n, 1)).astype(np.int32)
    SingleDataLoader(ff, x, xd)
    SingleDataLoader(ff, ff.label_tensor, yd)
    ff.fit()


if __name__ == "__main__":
    main()
