#!/bin/bash
# CI matrix (analog of the reference's .circleci/config.yml: build matrix
# {parameter-server, NCCL} x {build, 4-GPU tests} + nightly accuracy runs).
#
# Our matrix replaces gradient-sync backends (one XLA path here) with
# execution tiers:
#   unit      — pytest on the 8-device virtual CPU mesh (tests/conftest.py)
#   sweep     — every example end-to-end on the virtual mesh
#   accuracy  — accuracy-gated training runs (nightly tier)
#   native    — C shim + C++ apps build & run
#
#   resilience — fault-injection tests (FF_FAULT: kill-and-resume, NaN
#               skip/rewind, IO retry) + a 2-process multihost resume
#               smoke when the jax build has gloo CPU collectives
#   serving   — continuous-batching engine tests (incl. radix prefix
#               cache + speculative decoding) + a 200-request CPU smoke
#               with FF_FAULT=nan_loss injection and a skewed
#               shared-prefix phase (hits, 0 recompiles, no page leaks)
#   overlap   — host-overlap step engine tests (prefetch pipeline +
#               dispatch-ahead fit) + a slow-loader smoke asserting
#               throughput improves and host_wait drops; plus the
#               IN-GRAPH overlap drill (ISSUE 10): bucketed grad sync +
#               ZeRO-1 update pinned vs the serial epilogue, an
#               async-written manifest-verified checkpoint resuming
#               bitwise, and — gloo-gated — the same overlapped-sync
#               training preempted and resumed bitwise across TWO
#               controller processes
#   elastic   — elastic-recovery tests (topology-change resume, integrity
#               manifests, serving drain) + the corruption-injection
#               resume smoke + a 2-process run killed mid-epoch and
#               resumed SINGLE-process with on_topology_change=
#               resume_resharded (gloo-gated)
#   kernels   — Pallas kernel tier: paged-attention kernel parity vs the
#               einsum oracle + serving token-identity with the kernel
#               path forced (interpret mode on CPU = the REAL kernel
#               code), the block autotuner suite, and a tune-then-
#               consume smoke that writes and re-reads a real on-disk
#               autotune table
#   quant     — quantized serving tier: int8/fp8 KV pages (per-page-per-
#               head scales, in-kernel dequant) + weight-only int8/fp8
#               suites (scale round-trip, per-channel regression vs
#               per-tensor, pallas/einsum parity + token identity on
#               quantized pools, COW with quantized pages, divergence
#               budget vs full-width) + a serve-smoke leg running the
#               skewed shared-prefix workload on a bf16/int8 engine
#               pair — hit rate and zero warm-window recompiles must
#               match across dtypes
#   disagg    — disaggregated-fleet tier (ISSUE 12): the tiered-prefix-
#               cache state machine suite (pure host: demote/promote
#               ordering under the ordered publisher, cross-tier
#               refcounts, host LRU, abandoned-migration generation
#               check) + the fleet/engine integration suite (slab
#               handoff bitwise + token identity, role split, tier
#               faults, warmup variant sweep) + a 1-prefill/2-decode
#               smoke on skewed shared-prefix traffic with FF_FAULT
#               crashing the PREFILL replica mid-handoff — every
#               request completes exactly once via cold-path fallback,
#               token-identical, zero survivor recompiles — and a
#               working-set-3x-pool tiered-cache leg
#   obs       — unified-telemetry tier (ISSUE 13): the registry/tracing
#               suite (labeled series, histogram bucket math + quantile
#               estimates, concurrent-increment stress, Prometheus
#               exposition golden, trace-ring bounds, handoff/failover
#               span continuity, stats()/health() key superset pins) +
#               an obs smoke: a 1-prefill/2-decode fleet on skewed
#               shared-prefix traffic with a decode-replica crash
#               drill, /metrics scraped MID-RUN (TTFT/ITL histograms +
#               failover counters as labeled series over all replicas)
#               and the trace ring exported as perfetto-loadable
#               Chrome JSON in which every request has a complete span
#               tree and the failover/handoff requests each cross
#               replicas under ONE trace id; plus the flight-recorder /
#               SLO health plane (ISSUE 15): the recorder state-machine
#               suite (ring bounds, trigger debounce/cooldown, bundle
#               atomicity + torn-write drill, keep-K retention, SLO
#               window math with hysteresis, HBM ledger, /healthz
#               rollup), a post-mortem leg (the crash drill yields
#               exactly ONE manifest-intact bundle with complete
#               failed-over span trees) and an SLO leg (deterministic
#               slow()-fault TTFT breach: /healthz flips to breach
#               within one window and recovers)
#   router    — fleet-router tier: the multi-replica ServingRouter suite
#               (failover exactly-once + token identity incl. prefix
#               cache + speculation, deadline/shedding/affinity
#               semantics, hang detection, engine thread-safety) + a
#               2-replica 200-request smoke with FF_FAULT crashing
#               replica 0 mid-flight — all non-expired requests complete
#               exactly once, zero lost/duplicated, zero warm recompiles
#               on the survivor
#   tenancy   — multi-tenant serving tier (ISSUE 14): per-slot sampling
#               (counter-based seeded RNG, greedy bitwise at
#               temperature 0) + the paged LoRA adapter pool (host
#               allocator/LRU state machine, merged-weights stream
#               oracle, 8-tenant mixed-config zero-recompile pin,
#               per-adapter prefix-cache isolation) + rejection-sampled
#               speculation property tests (spec vs non-spec token
#               frequencies at K=1/3/8, small-draft and self-draft) +
#               seeded-reproducibility drills (slot reassignment,
#               engine instances, fleet failover) + an 8-adapter
#               mixed-sampling 2-replica fleet smoke under a mid-flight
#               crash: every seeded stream token-identical through
#               failover, adapter evicted + re-faulted under pool
#               pressure, zero warm-window recompiles, per-adapter
#               telemetry series present
#   deploy    — rolling-deployment tier (ISSUE 17): the weight-version
#               registry + RollingDeployer suite (drain->reopen, version-
#               salted prefix isolation, refused corrupt artifacts, torn-
#               swap rollback), then the 2-replica rolling-swap smoke: a
#               version published mid-flood rolls through the fleet with
#               every request served exactly once and zero warm-window
#               recompiles, and a second leg forces a canary SLO breach
#               (slow@canary) that must end in an automatic rollback —
#               fleet back on v1, exactly one manifest-intact post-mortem
#               bundle naming the breached SLO
#   longctx   — long-context serving tier (ISSUE 18): chunk-interleaved
#               admission + sequence-parallel prefill suites (token
#               identity interleaved vs run-to-completion, 2/3-shard
#               partial-slab merges bitwise, mid-prefill fault/deadline/
#               drain legs), then the smoke twice — plain and under
#               FF_SANITIZE=1: a maximal prompt admitted mid-decode-
#               flood must shrink the flood's worst inter-token gap
#               under interleave with zero timed-window recompiles, and
#               the 2-shard fleet merge stays bitwise + token-identical
#   search    — search v2 (ISSUE 19): persistent op-cost DB + multi-
#               objective (time x HBM) strategy search. The cost-DB /
#               warm-start / mem-mode / expert-axis suite, then the
#               smoke: a cold search persists one entry per op signature,
#               a warm re-run across a simulated process boundary
#               re-measures ZERO keyed ops (100% hit rate), a tight HBM
#               cap makes the multi-objective search choose remat/ZeRO/
#               offload relief that lints UNDER cap where the time-only
#               strategy lints over (escalated to error), and
#               calibration gauges (ff_csim_error_ratio et al.) land in
#               a telemetry scrape + a calib entry in the DB
#   elastic_serve — elastic fleet (ISSUE 20): SLO-driven autoscaling +
#               preemption-tolerant serving. The policy/membership/
#               evacuation suite (hysteresis + bounds, live add/remove
#               token identity, the drain-contract requeue regression,
#               bitwise survivor inheritance of prefix pages and
#               adapters, preempt exactly-once, deadline-starved fence
#               fallback), then the 2-leg smoke: a ~2x-capacity flood
#               breaches queue_wait and the autoscaler grows the fleet
#               to 3 (/healthz ok, zero survivor recompiles); a
#               preempt(800)@replica drill mid-flood evacuates the home
#               replica's requests + hot prefixes to survivors exactly
#               once (warm round-2 hits, one manifest-intact bundle
#               naming the preemption) — repeated under FF_SANITIZE=1
#   sanitize  — ffsan plane (ISSUE 16): static concurrency/
#               tracestability passes clean over runtime/ (tiered exit:
#               warnings fail too) + the seeded-violation harness, then
#               the router and disagg crash-drill smokes re-run under
#               FF_SANITIZE=1 (order-asserting lock proxies + armed
#               retrace sentinels) asserting zero violations and zero
#               post-warmup retraces
#
# Usage: ci/run_ci.sh [unit|sweep|accuracy|native|docs|lint|resilience|serving|overlap|elastic|kernels|quant|disagg|obs|router|tenancy|deploy|longctx|search|elastic_serve|sanitize|all]
set -e

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"
TIER="${1:-all}"

# All CI tiers are CPU-only. In the axon environment, sitecustomize dials
# the TPU tunnel at EVERY interpreter start when PALLAS_AXON_POOL_IPS is
# set, and a half-open tunnel hangs that call (round-3 finding) — so CI
# must never depend on tunnel state. Unset it and pin the CPU platform
# for every child process in this script.
export -n PALLAS_AXON_POOL_IPS 2>/dev/null || unset PALLAS_AXON_POOL_IPS
export JAX_PLATFORMS=cpu

run_unit()     { python -m pytest tests/ -x -q; }
run_sweep()    { bash tests/multi_device_tests.sh "${NDEV:-8}"; }
# accuracy tier defaults to 2 virtual devices: XLA CPU collectives need all
# participants at a rendezvous within 40 s, and 8 devices on a small host
# can starve one (see tests/accuracy_tests.sh)
run_accuracy() { bash tests/accuracy_tests.sh "${ACC_NDEV:-2}"; }
run_native()   {
  make -C flexflow_tpu/capi
  make -C examples/cpp
  FFT_JAX_PLATFORMS=cpu FFT_NUM_CPU_DEVICES=4 FFT_REPO_ROOT="$ROOT" \
    ./examples/cpp/alexnet 16 1 32
}
run_docs()     { make -C docs html; }
# lint tier: (1) fflint --strict over every shipped example strategy (the
# MANIFEST pairs each file with its model graph + mesh), (2) ruff over the
# Python package when the tool is available (config in pyproject.toml; the
# minimal CI image has no ruff — gate, don't fail, per the no-new-deps rule)
run_lint()     {
  local manifest="examples/strategies/MANIFEST"
  [ -f "$manifest" ] || { echo "lint: $manifest missing"; return 1; }
  while IFS='|' read -r f m mesh margs; do
    f=$(echo "$f" | xargs); m=$(echo "$m" | xargs)
    mesh=$(echo "$mesh" | xargs); margs=$(echo "$margs" | xargs)
    [ -z "$f" ] && continue
    case "$f" in \#*) continue ;; esac
    local extra=""
    for a in $margs; do extra="$extra --model-arg $a"; done
    echo "lint: fflint $m examples/strategies/$f (mesh $mesh)"
    # shellcheck disable=SC2086
    python -m flexflow_tpu.analysis "$m" "examples/strategies/$f" \
      --mesh "$mesh" --strict --quiet $extra
  done < <(grep -v '^#' "$manifest")
  if command -v ruff >/dev/null 2>&1; then
    ruff check flexflow_tpu
  elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check flexflow_tpu
  else
    echo "lint: ruff not installed in this image — skipping style gate"
  fi
}

# resilience tier: the fault-injection suite (every FF_FAULT path:
# kill-and-resume bitwise, NaN skip-step + rewind, injected orbax IO
# failure + retry, SIGTERM checkpoint-then-stop, watchdog), then the
# 2-process multihost training test as a resume smoke — it round-trips a
# sharded orbax checkpoint across controllers ("ckpt=ok") through the
# same atomic save/restore path the supervisor drives. The multihost leg
# needs gloo CPU collectives; probe and skip (loudly) where this jax
# build lacks them.
# gloo probe shared by every multihost smoke: the 2-process legs need
# CPU collectives, which some jax builds lack.
has_gloo() {
  JAX_PLATFORMS="" python -c "
import jax
jax.config.update('jax_cpu_collectives_implementation', 'gloo')" \
      >/dev/null 2>&1
}

run_resilience() {
  python -m pytest tests/test_resilience.py -q
  if has_gloo; then
    python -m pytest tests/test_multihost.py -q -k two_process_training
  else
    echo "resilience: no gloo CPU collectives in this jax build —" \
         "skipping the 2-process resume smoke"
  fi
}

# serving tier: the continuous-batching test file (token-identity vs
# sequential decode, bitwise paged-vs-dense attention, early-exit parity,
# recompile-counter flatness, prefix-cache COW/eviction/refcounts,
# speculative greedy identity), then the 200-request smoke with an
# injected nan_loss fault — request 37 is poisoned in-graph and must be
# retired as failed while the other 199 complete (no batch stall) —
# followed by its skewed shared-prefix phase (80% of requests share a
# 64-token system prompt: hits fire, warm window compiles nothing, and
# drain + flush leave zero leaked pages).
run_serving() {
  python -m pytest tests/test_serving.py -q
  FF_FAULT="nan_loss@serve:37" python scripts/serve_smoke.py 200
}

# overlap tier: the host-overlap step engine suite (bitwise identity vs
# the sync loop, checkpoint-cursor exactness under prefetch, io_fail
# retry inside the worker, retrace flatness), then the slow-loader smoke
# asserting throughput improves and the host_wait fraction drops.
# In-graph leg (ISSUE 10): the collective-overlap suite (bucketed grad
# sync + ZeRO-1 pinned numerics, async checkpointing, machine-model
# hierarchical pricing) and its smoke — local always; the 2-process
# overlapped-sync preempt/resume-bitwise drill where gloo exists.
run_overlap() {
  python -m pytest tests/test_overlap.py tests/test_pipeline_loader.py -q
  python -m pytest tests/test_collective_overlap.py \
    tests/test_machine_model.py -q
  python scripts/overlap_smoke.py
  python scripts/collective_overlap_smoke.py
  if has_gloo; then
    python scripts/collective_overlap_smoke.py two_process
  else
    echo "overlap: no gloo CPU collectives in this jax build —" \
         "skipping the 2-process overlapped-sync resume drill"
  fi
}

# elastic tier: the recovery suite (resume onto fewer devices /
# differently-shaped meshes, manifest verification + corrupted-latest
# fallback, retention sparing the last intact step, drain/health), the
# single-process corruption-injection resume smoke, and — where this jax
# build has gloo CPU collectives — the full changed-topology drill: a
# 2-process multihost run preempted mid-epoch, then relaunched as ONE
# surviving process that reshards onto 4 devices with the global batch
# preserved via grad-accum.
run_elastic() {
  python -m pytest tests/test_elastic.py -q
  python scripts/elastic_smoke.py corrupt
  if has_gloo; then
    python scripts/elastic_smoke.py shrink
  else
    echo "elastic: no gloo CPU collectives in this jax build —" \
         "skipping the 2-process shrink smoke"
  fi
}

# kernels tier: the paged-attention kernel + autotuner suites (slow-marked
# serving token-identity variants included — pytest -q runs the whole
# files), then the tune->persist->consume smoke against a real table file.
run_kernels() {
  python -m pytest tests/test_pallas_paged.py tests/test_kernel_tune.py -q
  python scripts/kernel_tune_smoke.py
}

# quant tier: the quantized-serving suite (slow-marked engine pairs
# included — pytest -q runs the whole file), then the bf16/int8
# serve-smoke pair on the skewed shared-prefix workload: identical hit
# counts, zero warm-window recompiles on both, ~2x tokens-per-pool-GB.
run_quant() {
  python -m pytest tests/test_quantized_serving.py -q
  python scripts/serve_smoke.py 120 quant
}

# disagg tier: the tier state machine + fleet integration suites, then
# the role-split smoke under a deterministic mid-handoff crash of the
# prefill replica (identity-indexed, so warmup consumes nothing; tick
# 12 lands while background handoffs stream through replica 0).
run_disagg() {
  python -m pytest tests/test_tiered_prefix.py tests/test_disagg.py -q
  FF_FAULT="crash(6)@replica:0" python scripts/disagg_smoke.py 160
}

# obs tier: the telemetry suite (slow-marked span-continuity variants
# included — pytest -q runs the whole file) + the flight-recorder /
# SLO / HBM-ledger suite (ISSUE 15), then the observability smoke:
# mid-run /metrics scrape + perfetto-loadable trace export with
# complete per-request span trees through a crash drill and a handoff,
# a post-mortem bundle leg and a /healthz SLO breach-and-recover leg.
run_obs() {
  python -m pytest tests/test_telemetry.py tests/test_flightrec.py -q
  python scripts/obs_smoke.py 120
}

# router tier: the fleet suite (failover/deadline/shedding/affinity +
# the concurrent-submit engine stress in test_serving), then the
# 2-replica smoke under a deterministic mid-flight crash of replica 0
# (crash@replica is identity-indexed, so the smoke's warmup consumes
# nothing from the plan; tick 10 guarantees work is genuinely
# mid-stream when the replica dies).
run_router() {
  python -m pytest tests/test_router.py -q
  python -m pytest tests/test_serving.py -q \
    -k "thread_safe or deadline_expires"
  FF_FAULT="crash(10)@replica:0" python scripts/router_smoke.py 200
}

# sanitize tier (ISSUE 16): the ffsan plane, both halves. Static: the
# concurrency + tracestability source passes must be CLEAN over
# flexflow_tpu/runtime (severity-tiered exit codes: any error OR
# warning fails the tier) and the seeded-violation harness in
# tests/test_ffsan.py must still catch every planted bug class.
# Dynamic: the router and disagg smokes re-run with their crash drills
# under FF_SANITIZE=1 — every runtime lock is an order-asserting proxy
# and every engine sentinel is armed after warmup; the smokes assert
# zero lock-order violations and zero post-warmup retraces before
# printing PASSED.
run_sanitize() {
  python -m flexflow_tpu.analysis \
    --passes concurrency,tracestability --tiered-exit
  python -m pytest tests/test_ffsan.py -q
  FF_SANITIZE=1 FF_FAULT="crash(10)@replica:0" \
    python scripts/router_smoke.py 200
  FF_SANITIZE=1 FF_FAULT="crash(6)@replica:0" \
    python scripts/disagg_smoke.py 160
}

# tenancy tier (ISSUE 14): the multi-tenant suites — per-slot sampling
# + paged LoRA adapter pool (test_tenancy) and rejection-sampled
# speculation property/reproducibility tests (test_sampled_spec, slow
# variants included: the K=1/3/8 distribution sweep and the sampled
# failover drill) — then the 8-adapter mixed-sampling fleet smoke under
# a deterministic mid-flight crash of replica 0 (tick 6: the drill must
# catch seeded sampled streams genuinely mid-decode; identity-indexed,
# so the smoke's warmup consumes nothing from the plan).
run_tenancy() {
  python -m pytest tests/test_tenancy.py tests/test_sampled_spec.py -q
  FF_FAULT="crash(6)@replica:0" python scripts/tenancy_smoke.py 48
}

# deploy tier (ISSUE 17): SLO-gated rolling deployment. The full suite
# (slow tests included: drain->reopen token identity, version-salted
# prefix isolation, the A/B mid-roll fleet, live rolling deploy), then
# the 2-leg smoke: a rolling swap under a skewed flood (exactly-once,
# capacity >= N-1, zero warm-window recompiles) and a forced canary
# breach that must roll the fleet back to v1 with exactly one
# manifest-intact bundle naming the breached SLO (the smoke arms its
# own slow@canary plan internally).
run_deploy() {
  python -m pytest tests/test_deploy.py -q
  python scripts/deploy_smoke.py 80
}

# longctx tier (ISSUE 18): long-context serving. The interleave/
# seq-parallel suites (slow tests included: interleaved-vs-run-to-
# completion token identity, the router's sharded handoff, the warmup
# variant sweep), then the smoke — once plain and once sanitized (the
# FF_SANITIZE leg also proves the new admission paths take the engine
# lock in order and never retrace warm programs).
run_longctx() {
  python -m pytest tests/test_longctx_serving.py tests/test_seq_parallel.py -q
  python scripts/longctx_smoke.py
  FF_SANITIZE=1 python scripts/longctx_smoke.py 24
}

# search tier (ISSUE 19): the persistent cost-DB / warm-start /
# multi-objective suite, then the cold->warm->drill->calibration smoke
# against a real DB file across a simulated process boundary.
run_search() {
  python -m pytest tests/test_cost_db.py -q
  python scripts/search_smoke.py
}

# elastic_serve tier (ISSUE 20): SLO-driven autoscaling + preemption-
# tolerant serving. The suite (policy hysteresis/bounds, live
# add/remove_replica token identity, the drain-contract requeue
# regression, bitwise survivor inheritance, preempt exactly-once, the
# deadline-starved fence fallback), then the 2-leg smoke — a flood at
# ~2x capacity must breach queue_wait and autoscale to 3 replicas
# (/healthz back to ok, zero survivor recompiles), and a preempt(800)
# drill mid-flood must complete every request exactly once with the
# evacuated prefix serving warm survivor hits and one manifest-intact
# bundle naming the preemption — re-run under FF_SANITIZE=1 to prove
# the membership/evacuation paths lock in order and never retrace.
run_elastic_serve() {
  python -m pytest tests/test_elastic_serve.py -q
  python scripts/elastic_serve_smoke.py 60
  FF_SANITIZE=1 python scripts/elastic_serve_smoke.py 40
}

case "$TIER" in
  unit)     run_unit ;;
  sweep)    run_sweep ;;
  accuracy) run_accuracy ;;
  native)   run_native ;;
  docs)     run_docs ;;
  lint)     run_lint ;;
  resilience) run_resilience ;;
  serving)  run_serving ;;
  overlap)  run_overlap ;;
  elastic)  run_elastic ;;
  kernels)  run_kernels ;;
  quant)    run_quant ;;
  disagg)   run_disagg ;;
  obs)      run_obs ;;
  router)   run_router ;;
  tenancy)  run_tenancy ;;
  deploy)   run_deploy ;;
  longctx)  run_longctx ;;
  search)   run_search ;;
  elastic_serve) run_elastic_serve ;;
  sanitize) run_sanitize ;;
  all)      run_lint; run_unit; run_resilience; run_serving; run_overlap; run_elastic; run_kernels; run_quant; run_disagg; run_obs; run_router; run_tenancy; run_deploy; run_longctx; run_search; run_elastic_serve; run_sanitize; run_native; run_docs; run_sweep ;;
  *) echo "unknown tier $TIER"; exit 2 ;;
esac
echo "ci($TIER): PASSED"
