#!/usr/bin/env python
"""CI disaggregated-fleet smoke (ci/run_ci.sh `disagg` tier).

Phase 1 — role-split crash drill: a skewed shared-prefix workload on a
1-prefill/2-decode fleet, with FF_FAULT ``crash(<t>)@replica:0`` felling
the PREFILL replica mid-handoff. Proves the ISSUE-12 acceptance end to
end on CPU:

  * long-prompt admission routes through the prefill replica and hands
    off as page slabs (handoffs > 0, zero routed completions there);
  * when the prefill tier dies, in-flight and later long prompts fall
    back to the COLD path on decode replicas — every request completes
    EXACTLY ONCE (router ledger == decode-engine completions), none
    lost, none duplicated, each losing at most one replica;
  * greedy streams stay token-identical to solo generate through the
    handoff AND through the fallback;
  * ZERO survivor recompiles: router.warmup() drove every (bucket,
    matched_pages) variant plus the page-import writer on every replica.

Phase 2 — tiered prefix cache: a prefix working set ~3x the HBM pool on
one engine with a host tier. Demotions and promotions fire, repeat
traffic hits where an untiered pool would go cold, streams stay
identical to a pressure-free engine, and drain leaves no refcounts, no
pending migrations and no leaked pages.

Usage: [FF_FAULT=crash(6)@replica:0] python scripts/disagg_smoke.py [N]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402

VOCAB = 128
PS = 8


def build_model():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=4,
                   kv_page_size=PS)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def skewed_prompts(rs, n, system):
    """60% share the 64-token system prompt (8 full pages, handed off
    once then affinity-homed); 40% distinct backgrounds of 1-2 full
    pages — EVERY one handoff-eligible, so the prefill replica stays
    busy and the crash genuinely lands mid-handoff."""
    prompts = []
    for i in range(n):
        if i % 5 < 3:
            tail = rs.randint(1, VOCAB, (int(rs.randint(2, 9)),))
            prompts.append(np.concatenate([system, tail.astype(np.int32)]))
        else:
            prompts.append(rs.randint(
                1, VOCAB, (int(rs.randint(9, 25)),)).astype(np.int32))
    return prompts


def fleet_phase(ff, n_requests):
    fault = os.environ.get("FF_FAULT", "")
    rs = np.random.RandomState(0)
    system = rs.randint(1, VOCAB, (64,)).astype(np.int32)
    prompts = skewed_prompts(rs, n_requests, system)

    router = ff.make_serving_router(
        replicas=3, roles=["prefill", "decode", "decode"],
        max_seq_len=112, decode_buckets=[32, 96], start=False)
    # warm every (bucket, matched_pages) variant the workload — and its
    # post-crash cold fallbacks — can reach, on EVERY replica, plus the
    # page-import writer (ServingEngine.warmup does the two-pass sweep;
    # crash@replica is identity-indexed, so warmup consumes nothing)
    warm_tail = rs.randint(1, VOCAB, (3,)).astype(np.int32)
    router.warmup([rs.randint(1, VOCAB, (10,)).astype(np.int32),
                   rs.randint(1, VOCAB, (18,)).astype(np.int32),
                   np.concatenate([system, warm_tail]),
                   np.concatenate([system, warm_tail + 1])],
                  max_new_tokens=4)
    for r, eng in enumerate(router.engines):
        assert eng.stats()["prefix_hits"] >= 1, \
            f"replica {r} warmup never ran the hit prefill"
        assert ("page_import",) in eng._programs, \
            f"replica {r} warmup never compiled the page-import writer"
    warm_compiles = [e.recompile_count for e in router.engines]
    warm_done = [e.stats()["completed"] for e in router.engines]

    t0 = time.perf_counter()
    reqs = router.run(prompts, max_new_tokens=12, timeout=1800)
    dt = time.perf_counter() - t0
    st = router.stats()
    done = [r for r in reqs if r.state == "done"]
    print(f"disagg_smoke[fleet]: {len(done)}/{n_requests} done in "
          f"{dt:.1f}s — handoffs {st['handoffs']}, fallbacks "
          f"{st['handoff_fallbacks']}, fenced {st['fenced']}, "
          f"resubmitted {st['resubmitted']}, fleet hit rate "
          f"{st['fleet']['prefix_hit_rate']}")

    # exactly once, nothing lost, nothing duplicated
    assert len(done) == n_requests, \
        f"{n_requests - len(done)} requests did not complete"
    assert st["completed"] == n_requests
    engine_done = sum(e.stats()["completed"] - w
                      for e, w in zip(router.engines, warm_done))
    assert engine_done == n_requests, (
        f"engines completed {engine_done} != {n_requests}: duplicated "
        f"or lost work")
    # the prefill replica routed ZERO completions — prefill-only is its
    # whole job (its engine_done delta is counted above and must be 0)
    assert router.engines[0].stats()["completed"] == warm_done[0], \
        "the prefill replica completed routed work"
    assert router.engines[0].stats()["prefill_only_requests"] > 0
    assert st["handoffs"] >= 1, "no prompt ever handed off"
    assert all(r.losses <= 1 for r in reqs), "a request lost 2 replicas"

    if "crash" in fault and "@replica:0" in fault:
        assert st["fenced"] == 1, \
            f"crash fault armed but fenced == {st['fenced']}"
        assert st["handoff_fallbacks"] >= 1, (
            "the crash was supposed to catch handoff work in flight "
            "(cold-path fallback never fired)")
        # the drill's trace annotation marks exactly where the fault
        # landed (runtime/telemetry.py; faultinject reports every fire)
        from flexflow_tpu.runtime import telemetry

        assert any(e["args"]["kind"] == "crash"
                   and e["args"]["site"] == "replica"
                   and e["args"]["index"] == 0
                   for e in telemetry.fault_events()), \
            "crash fired but left no fault annotation in the trace ring"
        for r in (1, 2):
            assert router.engines[r].recompile_count \
                == warm_compiles[r], (
                    f"survivor {r} recompile leak: "
                    f"{router.engines[r].recompile_count - warm_compiles[r]}"
                    f" programs built after warmup")
        print(f"disagg_smoke[fleet]: prefill replica crashed mid-handoff"
              f" ({st['per_replica'][0]['fence_reason']}); "
              f"{st['handoff_fallbacks']} cold-path fallbacks, survivors"
              f" built 0 new programs")
    else:
        assert st["fenced"] == 0
        for r, eng in enumerate(router.engines):
            assert eng.recompile_count == warm_compiles[r], \
                f"replica {r} recompile leak without any fault"

    # token identity through handoff AND fallback: every failed-over
    # request + a sample of the rest vs solo generate
    resub = [r for r in reqs if r.losses >= 1]
    for r in resub + done[:: max(1, len(done) // 10)]:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=12)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (handoff={r.handoff}, losses="
                    f"{r.losses}) diverged from its solo run")
    print(f"disagg_smoke[fleet]: token identity held for {len(resub)} "
          f"failed-over + sampled requests")


def tier_phase(ff):
    rs = np.random.RandomState(1)
    # 18 distinct 2-page prefixes (+ tails) vs a pool that can cache
    # only a few: the working set is ~3x the HBM pool, so the untiered
    # engine would churn-and-die where the host tier keeps every prefix
    prompts = [rs.randint(1, VOCAB, (18,)).astype(np.int32)
               for _ in range(18)]
    roomy = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                   max_seq_len=48)
    want = [[list(r.tokens) for r in roomy.run(prompts, max_new_tokens=6)]
            for _ in range(2)]
    eng = ff.make_serving_engine(serve_slots=2, kv_page_size=PS,
                                 max_seq_len=48, kv_pages=20,
                                 host_kv_pages=64)
    got = [[list(r.tokens) for r in eng.run(prompts, max_new_tokens=6)]
           for _ in range(2)]
    assert got == want, "tier migrations changed a greedy stream"
    st = eng.stats()
    print(f"disagg_smoke[tier]: demotions {st['tier_demotions']}, "
          f"promotions {st['tier_promotions']}, hits "
          f"{st['prefix_hits']}/{st['prefix_lookups']}, host pages "
          f"{st['kv_pages_host']}")
    assert st["tier_demotions"] > 0 and st["tier_promotions"] > 0
    assert st["prefix_hits"] >= len(prompts), \
        "round 2 should hit every prefix via the host tier"
    snap = eng.drain()
    assert snap["prefix_refs_live"] == 0
    assert snap["tier_pending_migrations"] == 0
    freed = eng.flush_prefix_cache()
    assert eng.stats()["free_pages"] == eng.num_pages - 1, \
        "tier migrations leaked pool pages"
    print(f"disagg_smoke[tier]: drained clean, flush reclaimed {freed} "
          f"pages, zero leaks")


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 160
    ff = build_model()
    fleet_phase(ff, n_requests)
    tier_phase(ff)

    if os.environ.get("FF_SANITIZE"):
        # CI sanitize tier: both phases (fleet crash drill + tiered KV
        # migrations) ran sanitized — the global evidence rings cover
        # every engine created above
        from flexflow_tpu.runtime import locks

        assert locks.mode() != "off", "FF_SANITIZE set but sanitizer off"
        assert locks.violations() == [], (
            "lock-order violations under FF_SANITIZE:\n"
            + "\n".join(f"{v['outer']} -> {v['inner']}\n{v['inner_stack']}"
                        for v in locks.violations()))
        assert locks.retrace_log() == [], (
            "post-warmup retraces under FF_SANITIZE:\n"
            + "\n".join(f"{r['program']} {r['signature']}\n{r['stack']}"
                        for r in locks.retrace_log()))
        snap = locks.lock_graph_snapshot()
        print(f"disagg_smoke[sanitize]: mode={snap['mode']}, "
              f"{len(snap['tracked_locks'])} tracked locks, "
              f"zero violations, zero retraces")

    print("disagg_smoke: PASSED")


if __name__ == "__main__":
    main()
