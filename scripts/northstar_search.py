#!/usr/bin/env python
"""North-star search demo: MCMC strategy vs pure data-parallel on a
simulated TPU v5e-32.

BASELINE.md's rebuild target (from the reference's SysML'19 headline claim):
the MCMC-discovered strategy should beat pure data parallelism by >=1.5x on
ResNet-50 and Transformer at v5e-32 scale, with DLRM's embedding-partitioned
hybrid also beating DP. The real pod is not attachable in this environment,
so this script runs the full search pipeline — graph build, cost tables,
native C++ annealer (search/csrc/sim.cc), per-device timelines, two-tier
ICI/DCN machine model — on a simulated 4-host x 8-chip v5e-32 and reports
the simulated iteration time of the best-found strategy vs DP-32.

Role parity: the reference's search prints simulated per-iteration runtime
during MCMC (model.cc:1687-1690) and its paper compares that same simulated
objective across strategies; this is the identical experiment on the TPU
machine model.

Usage: python scripts/northstar_search.py [--budget N] [--workload NAME]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel
from flexflow_tpu.models.cnn import inception_v3, resnet50
from flexflow_tpu.models.dlrm import dlrm
from flexflow_tpu.models.transformer import (TransformerConfig,
                                             build_reference_transformer)
from flexflow_tpu.search.csim import get_search_problem
from flexflow_tpu.search.cost_model import CostModel
from flexflow_tpu.search.machine import MachineModel

HOSTS = 4
CHIPS_PER_HOST = 8  # v5e-32: 4 hosts x 8 chips


def v5e32_machine() -> MachineModel:
    """v5e-32: ICI within each 8-chip host slice, DCN across the 4 hosts.
    The 'data' mesh axis is laid out across hosts (the natural layout: model
    axes ride ICI, batch rides DCN)."""
    return MachineModel(dcn_axes={"data": HOSTS})


def full_dp_strategy(model, mesh_shape):
    """Pure data parallelism over EVERY mesh axis (the honest DP-32
    baseline): each axis shards the sample dim where divisible."""
    from flexflow_tpu.ops.base import InputOp

    out = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        am, deg = {}, 1
        dims = op.outputs[0].dims
        for ax, size in mesh_shape.items():
            if size > 1 and dims and dims[0] % (deg * size) == 0 \
                    and 0 in op.partitionable_output_dims():
                am[ax] = 0
                deg *= size
        out[op.name] = am
    return out


def build_workload(name: str, batch: Optional[int] = None):
    """Returns (model, mesh_shape). Default global batch sizes follow the
    reference's own defaults (batch 64, model.cc:1917-1938) — the regime the
    reference's search targets, where pure DP is gradient-sync-bound. Pass
    `batch` for other regimes (e.g. 512 = 16/chip large-batch)."""
    mesh = {"data": HOSTS, "model": CHIPS_PER_HOST}
    if name == "transformer":
        # reference examples/cpp/Transformer defaults (hidden 512, 16 heads,
        # 12 layers, seq 128, batch 64)
        cfg = FFConfig(batch_size=batch or 64, mesh_shape=mesh)
        ff = FFModel(cfg)
        build_reference_transformer(ff, cfg.batch_size, TransformerConfig())
    elif name == "bert_fx":
        # BASELINE target table names "BERT-base via FX import" as a
        # transformer-throughput config: import the BERT-base-shaped torch
        # encoder (hidden 768, 12 layers, 12 heads, seq 128) through the
        # FX frontend, then search THAT graph
        pt_examples = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "examples",
            "pytorch")
        if pt_examples not in sys.path:
            sys.path.append(pt_examples)  # append: don't shadow stdlib/pkgs
        from bert_fx import BertEncoder

        from flexflow_tpu.torch import PyTorchModel

        cfg = FFConfig(batch_size=batch or 64, mesh_shape=mesh)
        ff = FFModel(cfg)
        x = ff.create_tensor([cfg.batch_size, 128, 768], name="x")
        PyTorchModel(model=BertEncoder(hidden=768, heads=12, layers=12,
                                       seq=128, classes=2)).apply(ff, [x])
    elif name == "resnet50":
        # reference examples/cpp/ResNet, default batch 64
        cfg = FFConfig(batch_size=batch or 64, mesh_shape=mesh)
        ff = FFModel(cfg)
        resnet50(ff, cfg.batch_size)
    elif name == "inception":
        cfg = FFConfig(batch_size=batch or 64, mesh_shape=mesh)
        ff = FFModel(cfg)
        inception_v3(ff, cfg.batch_size, num_classes=1000)
    elif name == "llama":
        # modern decoder (RMSNorm + RoPE + GQA + SwiGLU, models/llama.py) —
        # the Llama-3-8B-class family BASELINE.json's north star names,
        # at a searchable proxy size (hidden 1024, 8 layers, 16 heads /
        # 4 kv heads, seq 512)
        from flexflow_tpu.models.llama import llama_lm

        cfg = FFConfig(batch_size=batch or 64, mesh_shape=mesh)
        ff = FFModel(cfg)
        llama_lm(ff, cfg.batch_size, seq_len=512, hidden=1024, layers=8,
                 heads=16, kv_heads=4, vocab_size=32_000)
    elif name == "llama8b":
        # the REAL Llama-3-8B shape BASELINE.json config 5 names (hidden
        # 4096, 32 layers, 32 heads / 8 kv, ffn 14336, vocab 128256) on a
        # simulated 64-chip two-tier pod (8 hosts x 8 chips): the
        # scale-shaped joint search — expect the winner to COMBINE axes
        # (TP over 'model' x DP/FSDP over 'data'), not pick one
        from flexflow_tpu.models.llama import llama_lm

        mesh = {"data": 8, "model": 8}
        # default batch 16 @ seq 4096 = 65k tokens: the memory/latency-
        # bound regime (fine-tune/RL-scale) where pure DP both exceeds
        # HBM (weights replicated) and cannot shard 64 ways — the regime
        # where joint search must find combined-axis structure
        cfg = FFConfig(batch_size=batch or 16, mesh_shape=mesh)
        ff = FFModel(cfg)
        llama_lm(ff, cfg.batch_size, seq_len=4096, hidden=4096, layers=32,
                 heads=32, kv_heads=8, ffn_hidden=14336, vocab_size=128_256)
    elif name == "dlrm":
        # reference run_summit.sh: 512 samples/device batch, 1M-row x 64-dim
        # tables, mlp-bot 64-512-512-64, mlp-top 576-1024-1024-1024-1
        cfg = FFConfig(batch_size=512 * 32, mesh_shape=mesh)
        ff = FFModel(cfg)
        dlrm(ff, cfg.batch_size, embedding_size=64,
             embedding_entries=1_000_000, num_tables=8,
             mlp_bot=(512, 512, 64), mlp_top=(1024, 1024, 1024, 1))
    else:
        raise SystemExit(f"unknown workload {name!r}")
    return ff, mesh


def run_one(name: str, budget: int, seed: int = 0, verbose: bool = True,
            batch: Optional[int] = None, costs: str = "analytic",
            fsdp: bool = False, measure_budget_s: Optional[float] = None):
    ff, mesh = build_workload(name, batch)
    if name == "llama8b":
        fsdp = True  # an 8B can't replicate weights per chip: ZeRO-3 regime
    if fsdp:
        # price the run under FSDP (FFConfig.fsdp_axis): CostModel picks
        # the axis up from the config; the annealer then skips placement
        # proposals (csim.native semantics) — mirrored here via
        # allow_place on the direct prob.mcmc call below
        ff.config.fsdp_axis = "data"
    if name == "llama8b":
        # two-tier 64-chip pod: ICI within each 8-chip host, DCN across 8
        machine = MachineModel(dcn_axes={"data": mesh["data"]})
        machine_desc = "simulated 64-chip pod (8 hosts x 8 chips, ICI+DCN)"
    else:
        machine = v5e32_machine()
        machine_desc = "simulated v5e-32 (4 hosts x 8 chips, ICI+DCN)"
    measured = None
    if costs == "analyze":
        # compile-only XLA cost analysis per shard signature on the attached
        # device (the middle fidelity tier, SURVEY §7 hard part 1)
        from flexflow_tpu.search.measure import analyze_op_costs

        measured = analyze_op_costs(ff, mesh, machine=machine)
    elif costs == "measure":
        # real per-shard fwd+bwd timings on the attached chip — the
        # reference's design: measure on device 0, simulate the cluster
        # (simulator.cc:296-316)
        from flexflow_tpu.search.measure import measure_op_costs

        measured = measure_op_costs(ff, mesh,
                                    time_budget_s=measure_budget_s)
    # dtype_bytes=2: the flagship trains bf16 on the MXU (bench.py config),
    # so strategies are priced at bf16 compute + bf16 activations
    cost = CostModel(ff, mesh, machine=machine, dtype_bytes=2,
                     measured=measured)
    t0 = time.time()
    prob = get_search_problem(ff, cost, mesh)
    build_s = time.time() - t0

    dp_map = full_dp_strategy(ff, mesh)
    dp_choices = prob.choices_for(dp_map)
    dp_cost = prob.simulate(dp_choices)

    # memory honesty: when pure DP does not FIT per-chip HBM, its
    # simulated time is dominated by the 1 ms/MB over-capacity penalty
    # (the reference's pricing, simulator.cc:595-620) — report per-chip
    # bytes and a second DP number on a hypothetical infinite-HBM machine
    # so the speedup can be read as feasibility + time, not conflated
    from flexflow_tpu.ops.base import InputOp

    dp_mem = sum(cost.op_mem_bytes(op, dp_map.get(op.name, {}))
                 for op in ff.ops if not isinstance(op, InputOp))
    dp_fits = dp_mem <= machine.hbm_bytes
    dp_nopenalty_cost = None
    if not dp_fits:
        import dataclasses

        # price ONE fixed strategy on the infinite-HBM machine via the
        # Python schedule mirror — no O(edges x choices^2) table rebuild
        machine_inf = dataclasses.replace(machine, hbm_bytes=1e18)
        cost_inf = CostModel(ff, mesh, machine=machine_inf, dtype_bytes=2,
                             measured=measured)
        dp_nopenalty_cost = cost_inf.iteration_time(dp_map)

    t0 = time.time()
    # authoritative gate: whatever ended up in the cost model (CLI flag OR
    # a workload config that set fsdp_axis itself) disables placement
    best_c, best_p, best_cost = prob.mcmc(dp_choices, budget, 0.05, seed,
                                          restarts=4,
                                          allow_place=not cost.fsdp_axis)
    search_s = time.time() - t0
    speedup = dp_cost / max(best_cost, 1e-12)

    # summarize what the search chose, per mesh axis: which PARALLELISM
    # KINDS the winner uses (dp = sample dim, tp = non-sample output dim,
    # contract = row-parallel weight shard, stage = pipeline) — the
    # scale-shaped check is that a big-model winner COMBINES axes
    from flexflow_tpu.parallel.pconfig import CONTRACT, STAGE

    n_tp = n_placed = 0
    axes_used: dict = {}
    for i, op in enumerate(prob.ops):
        am = prob.op_maps[i][int(best_c[i])]
        if any(d is not None and d != 0 for d in am.values()):
            n_tp += 1
        if int(best_p[i]) != 0:
            n_placed += 1
        for ax, d in am.items():
            if d is None:
                continue
            kind = ("dp" if d == 0 else "contract" if d == CONTRACT
                    else "stage" if d == STAGE else "tp")
            axes_used.setdefault(ax, set()).add(kind)
    # NB: 'fsdp' here is config-imposed pricing (every weight shards over
    # that axis), not a search choice — assertions about search-CHOSEN
    # structure must look at dp/tp/contract/stage entries instead
    if cost.fsdp_axis:
        axes_used.setdefault(cost.fsdp_axis, set()).add("fsdp")
    axes_used = {k: sorted(v) for k, v in axes_used.items()}
    # per-chip bytes of the winner: exact only when no op is placed on a
    # proper device block (then every op spans the full mesh and each
    # chip holds the sum); with placement, blocks don't co-reside, so
    # report None rather than an overstated sum
    best_mem = (sum(cost.op_mem_bytes(op, prob.op_maps[i][int(best_c[i])])
                    for i, op in enumerate(prob.ops))
                if n_placed == 0 else None)

    result = {
        "workload": name,
        "fsdp": fsdp,
        "costs": costs,
        "global_batch": ff.config.batch_size,
        "machine": machine_desc,
        "num_ops": len(prob.ops),
        "dp_iter_ms": round(dp_cost * 1e3, 3),
        "best_iter_ms": round(best_cost * 1e3, 3),
        "speedup_vs_dp": round(speedup, 3),
        "target": 1.5,
        "ops_with_model_parallel_dims": n_tp,
        "ops_placed_off_block0": n_placed,
        "axes_used": axes_used,
        "dp_mem_gb_per_chip": round(dp_mem / 1e9, 1),
        "best_mem_gb_per_chip": (round(best_mem / 1e9, 1)
                                 if best_mem is not None else None),
        "hbm_gb_per_chip": round(machine.hbm_bytes / 1e9, 1),
        "dp_fits_hbm": dp_fits,
        # None when DP fits (dp_iter_ms already penalty-free then)
        "dp_nopenalty_iter_ms": (round(dp_nopenalty_cost * 1e3, 3)
                                 if dp_nopenalty_cost is not None else None),
        "speedup_vs_dp_nopenalty": (
            round(dp_nopenalty_cost / max(best_cost, 1e-12), 3)
            if dp_nopenalty_cost is not None else None),
        "budget": budget,
        "table_build_s": round(build_s, 1),
        "search_s": round(search_s, 1),
        # provenance of the cost table (measured is None on the pure
        # analytic tier): measured_entries counts cost-table keys
        # (op + sharding choices); measured_signatures counts DISTINCT
        # timed signatures (MeasuredTable.signatures_timed) — twins fill
        # from _SIGNATURE_CACHE and share one timing, so entries >=
        # signatures. The analyze tier has no signature dedup: every
        # entry is its own compile, so the counts coincide there.
        "measured_entries": (len(measured)
                             if measured is not None else None),
        "measured_signatures": (
            getattr(measured, "signatures_timed", len(measured))
            if measured is not None else None),
    }
    if verbose:
        print(json.dumps(result), flush=True)
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=50_000,
                    help="MCMC iterations (reference --budget)")
    ap.add_argument("--workload", default="all",
                    choices=["all", "transformer", "bert_fx", "llama",
                             "llama8b", "resnet50", "inception",
                             "dlrm"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch", type=int, default=None,
                    help="override global batch (default: reference configs)")
    ap.add_argument("--costs", default="analytic",
                    choices=["analytic", "analyze", "measure"],
                    help="per-op cost tier: analytic roofline, compile-only "
                         "XLA cost analysis, or real-device timing")
    ap.add_argument("--large-batch", action="store_true",
                    help="also run the 16-samples/chip large-batch regime")
    ap.add_argument("--fsdp", action="store_true",
                    help="price the search under FSDP over 'data' "
                         "(weight gathers + grad reduce-scatter; no "
                         "placement proposals)")
    ap.add_argument("--measure-budget", type=float, default=None,
                    help="wall-clock cap (s) for --costs measure table "
                         "builds; impact-ordered, tail falls back to "
                         "analytic (logged)")
    args = ap.parse_args()

    names = (["transformer", "bert_fx", "llama", "llama8b", "resnet50",
              "inception", "dlrm"]
             if args.workload == "all" else [args.workload])
    results = [run_one(n, args.budget, args.seed, batch=args.batch,
                       costs=args.costs, fsdp=args.fsdp,
                       measure_budget_s=args.measure_budget)
               for n in names]
    if args.large_batch:
        results += [run_one(n, args.budget, args.seed, batch=16 * 32,
                            costs=args.costs, fsdp=args.fsdp,
                            measure_budget_s=args.measure_budget)
                    for n in names if n != "dlrm"]
    print("\n== north-star summary (simulated) ==")
    for r in results:
        flag = "MET" if r["speedup_vs_dp"] >= r["target"] else "below"
        line = (f"  {r['workload']:<12} b={r['global_batch']:<6} "
                f"DP {r['dp_iter_ms']:>9.3f} ms -> "
                f"best {r['best_iter_ms']:>9.3f} ms  "
                f"({r['speedup_vs_dp']:.2f}x vs target 1.5x: {flag})")
        if not r["dp_fits_hbm"]:
            line += (f"  [DP needs {r['dp_mem_gb_per_chip']} GB/chip vs "
                     f"{r['hbm_gb_per_chip']} HBM — infeasible; vs "
                     f"no-penalty DP: {r['speedup_vs_dp_nopenalty']:.2f}x]")
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
