#!/usr/bin/env python
"""CI long-context serving smoke (ci/run_ci.sh `longctx` tier).

Phase 1 — chunk-interleaved admission under a decode flood: a decode
stream is mid-flight when a MAXIMAL prompt (the largest the engine
admits) arrives. Run-to-completion admission stalls the stream for the
whole multi-chunk prefill; interleaved admission spends one chunk per
tick. Proves the ISSUE-18 head-of-line acceptance end to end on CPU:

  * the flood stream's worst inter-token gap shrinks with interleave ON
    vs OFF while the maximal prompt admits (same warm engines, same
    cold workload);
  * both arms emit IDENTICAL tokens — scheduling is invisible in the
    streams;
  * ZERO recompiles in the timed window (the warm round drove every
    chunk/final variant the workload reaches).

Phase 2 — sequence-parallel prefill: a 2-shard partial-slab merge lands
the decode pool BITWISE identical to a single-replica prefill, and a
1-prefill+1-prefill+1-decode fleet with ``seq_parallel_shards=2`` emits
greedy streams token-identical to solo generate while the new fleet
counters account the sharded handoffs.

Under FF_SANITIZE both phases must leave the sanitizer evidence rings
empty (no lock-order violations, no post-warmup retraces).

Usage: [FF_SANITIZE=1] python scripts/longctx_smoke.py [N_FLOOD_TOKENS]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402

VOCAB = 128
PS = 8
MAX_SEQ = 520       # 65 pages/slot; explicit buckets [16, 512]
CHUNK = 16
MONSTER = 500       # buckets to 512: 32 prefill chunks of 16


def build_model():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=2,
                   kv_page_size=PS)
    ff = FFModel(cfg)
    # heavy enough that a full-prompt prefill visibly stalls a decode
    # tick (the head-of-line effect the interleave phase measures);
    # 2 layers x hidden 128 puts the 32-chunk stall well above CPU
    # dispatch noise
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=128, layers=2, heads=4,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def flood_round(eng, flood_prompt, monster_prompt, flood_tokens):
    """One cold round: start the flood stream decoding, drop the
    maximal prompt on it mid-stream, and record the flood's inter-token
    gaps until both retire. Returns (gaps, flood_tokens, monster_tokens)."""
    fr = eng.submit(flood_prompt, max_new_tokens=flood_tokens)
    while len(fr.tokens) < 4:           # a live stream, not a cold start
        eng.step()
    mr = eng.submit(monster_prompt, max_new_tokens=4)
    gaps, last, prev = [], len(fr.tokens), time.perf_counter()
    while fr.state not in ("done", "failed") \
            or mr.state not in ("done", "failed"):
        eng.step()
        now = time.perf_counter()
        if len(fr.tokens) > last:
            gaps.append((now - prev) / (len(fr.tokens) - last))
            last, prev = len(fr.tokens), now
    assert fr.state == "done" and mr.state == "done", \
        f"flood={fr.state} monster={mr.state}"
    return gaps, list(fr.tokens), list(mr.tokens)


def interleave_phase(ff, flood_tokens):
    rs = np.random.RandomState(2)
    flood = rs.randint(1, VOCAB, (12,)).astype(np.int32)
    monster = rs.randint(1, VOCAB, (MONSTER,)).astype(np.int32)

    results = {}
    for budget in (0, 1):
        # prefix cache OFF so the timed round replays the warm round's
        # exact cold programs (a HIT round would skip the chunks)
        eng = ff.make_serving_engine(
            serve_slots=2, kv_page_size=PS, max_seq_len=MAX_SEQ,
            decode_buckets=[16, 512],
            prefill_chunk=CHUNK, prefill_interleave_chunks=budget,
            prefix_cache=False)
        flood_round(eng, flood, monster, flood_tokens)      # warm
        rc = eng.recompile_count
        # min over rounds: a scheduler blip can inflate one round's
        # worst gap, but only the admission policy inflates ALL of them
        worst, ftoks, mtoks = None, None, None
        for _ in range(2):
            gaps, ftoks, mtoks = flood_round(eng, flood, monster,
                                             flood_tokens)
            worst = min(worst, max(gaps)) if worst else max(gaps)
        assert eng.recompile_count == rc, (
            f"{eng.recompile_count - rc} programs compiled in the timed "
            f"window (interleave={budget})")
        results[budget] = (worst, ftoks, mtoks)
        st = eng.stats()
        if budget:
            assert st["prefill_chunks_interleaved"] >= 2 * (MONSTER
                                                            // CHUNK), \
                "the monster's chunks never rode the interleave quanta"
            assert st["prefill_partial_slots"] == 0

    off, on = results[0], results[1]
    assert on[1:] == off[1:], \
        "interleaved admission changed a greedy stream"
    print(f"longctx_smoke[interleave]: flood worst inter-token gap "
          f"{off[0] * 1e3:.1f}ms run-to-completion -> {on[0] * 1e3:.1f}ms"
          f" interleaved ({MONSTER}-token monster, chunk {CHUNK})")
    assert on[0] < off[0], (
        f"interleave did not flatten the head-of-line stall: "
        f"{on[0] * 1e3:.1f}ms >= {off[0] * 1e3:.1f}ms")


def seq_parallel_phase(ff):
    rs = np.random.RandomState(3)
    prompt = rs.randint(1, VOCAB, (48,)).astype(np.int32)   # 6 pages
    kw = dict(serve_slots=2, kv_page_size=PS, max_seq_len=64)

    # engine-level 2-shard merge, bitwise vs one-replica prefill
    ref = ff.make_serving_engine(**kw)
    assert ref.prefill_into_cache(prompt) == 6
    a = ff.make_serving_engine(**kw)
    assert a.prefill_into_cache(prompt[:3 * PS]) == 3
    slab0 = a.export_prefix_slab(prompt[:3 * PS])
    b = ff.make_serving_engine(**kw)
    assert b.import_prefix_slab(slab0) == 3
    assert b.prefill_into_cache(prompt) == 6
    slab1 = b.export_prefix_slab(prompt, start_page=3)
    dec = ff.make_serving_engine(**kw)
    assert dec.import_prefix_slab(slab0) == 3
    assert dec.import_prefix_slab(slab1) == 3
    rpath = ref.prefix_cache.match(prompt, 6)
    dpath = dec.prefix_cache.match(prompt, 6)
    assert len(rpath) == len(dpath) == 6
    for op in ref.gen.attn_ops:
        for plane in ("k", "v"):
            want = np.stack([np.asarray(ref.pool[op.name][plane][n.page])
                             for n in rpath])
            got = np.stack([np.asarray(dec.pool[op.name][plane][n.page])
                            for n in dpath])
            assert (want == got).all(), \
                f"sharded merge diverged at {op.name}/{plane}"
    assert dec.stats()["partial_slab_imports"] == 1
    print("longctx_smoke[seq_parallel]: 2-shard merge bitwise identical "
          "to single-replica prefill")

    # fleet leg: sharded handoff, token identity vs solo generate
    prompts = [rs.randint(1, VOCAB, (int(n),)).astype(np.int32)
               for n in (48, 50, 52, 11)]
    router = ff.make_serving_router(
        replicas=3, roles=["prefill", "prefill", "decode"],
        seq_parallel_shards=2, handoff_min_pages=2,
        serve_slots=2, kv_page_size=PS, max_seq_len=96)
    try:
        reqs = router.run(prompts, max_new_tokens=6, timeout=600)
        assert all(r.state == "done" for r in reqs), \
            [r.state for r in reqs]
        for r in reqs:
            solo = ff.generate(r.prompt[None, :], max_new_tokens=6)
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
                err_msg=f"request {r.rid} diverged from its solo run")
        fleet = router.stats()["fleet"]
        assert fleet["seq_parallel_prefills"] == 3, \
            f"seq_parallel_prefills={fleet['seq_parallel_prefills']}"
        assert fleet["partial_slab_imports"] >= 3
        print(f"longctx_smoke[seq_parallel]: fleet ran "
              f"{fleet['seq_parallel_prefills']} sharded prefills, "
              f"{fleet['partial_slab_imports']} partial-slab merges, "
              f"streams identical to solo generate")
    finally:
        router.close()


def main():
    flood_tokens = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    ff = build_model()
    interleave_phase(ff, flood_tokens)
    seq_parallel_phase(ff)

    if os.environ.get("FF_SANITIZE"):
        from flexflow_tpu.runtime import locks

        assert locks.mode() != "off", "FF_SANITIZE set but sanitizer off"
        assert locks.violations() == [], (
            "lock-order violations under FF_SANITIZE:\n"
            + "\n".join(f"{v['outer']} -> {v['inner']}\n{v['inner_stack']}"
                        for v in locks.violations()))
        assert locks.retrace_log() == [], (
            "post-warmup retraces under FF_SANITIZE:\n"
            + "\n".join(f"{r['program']} {r['signature']}\n{r['stack']}"
                        for r in locks.retrace_log()))
        print("longctx_smoke[sanitize]: zero violations, zero retraces")

    print("longctx_smoke: PASSED")


if __name__ == "__main__":
    main()
