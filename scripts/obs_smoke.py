#!/usr/bin/env python
"""CI observability smoke (ci/run_ci.sh `obs` tier, ISSUES 13 + 15).

A 1-prefill/2-decode fleet serves a skewed shared-prefix workload with
FF_FAULT crashing a DECODE replica mid-flight (handoffs keep flowing
through the prefill tier while failover runs). Mid-run, the Prometheus
endpoint is scraped; afterwards the trace ring is exported as Chrome
trace-event JSON. Proves the ISSUE-13 acceptance end to end on CPU:

  * the mid-run scrape carries the TTFT and inter-token HISTOGRAMS and
    the router failover counters (fenced/resubmitted/timeouts/rejected)
    as labeled series, with engine series covering ALL replicas;
  * every submitted request has a COMPLETE span tree (root "request"
    span + queue/prefill/decode children, every span starting inside
    the root);
  * a crash-failover request and a prefill->decode handoff request each
    show a single CONNECTED span tree across replicas (one trace id:
    resubmit annotation + spans on two replicas; handoff_export on the
    prefill replica + handoff_import/decode on a decode replica);
  * the fault drill's trace annotation marks where the crash landed;
  * the exported JSON is perfetto-loadable (traceEvents list, complete
    events carry name/ph/ts/pid/tid/dur).

The ISSUE-15 legs on top:

  * POST-MORTEM — the crash drill's trigger storm (fault annotation +
    replica fence) must yield exactly ONE manifest-intact bundle naming
    its trigger cause, whose embedded trace holds COMPLETE span trees
    for every failed-over request;
  * SLO — a deterministic TTFT breach via the ``slow(<ms>)@serve:<n>``
    fault flips ``/healthz`` to "breach" within one evaluation window,
    raises ``ff_slo_breach_total``, and recovers (hysteresis-cleared)
    under healthy traffic.

Usage: python scripts/obs_smoke.py [N]
"""

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402
from flexflow_tpu.runtime import (faultinject, flightrec,  # noqa: E402
                                  telemetry)

VOCAB = 128
PS = 8
CRASH_REPLICA = 1       # a decode replica: handoffs keep flowing
FLIGHT_DIR = tempfile.mkdtemp(prefix="ff_obs_flightrec_")


def build_model():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=4,
                   kv_page_size=PS, metrics_port=0,
                   # ISSUE 15: bundle on trigger. The debounce is huge so
                   # the drill's whole trigger storm stays ONE pending
                   # record that flush() publishes after the fleet
                   # settles — the bundle's trace then holds the
                   # failover aftermath, not just the crash instant
                   flight_recorder_dir=FLIGHT_DIR,
                   flight_debounce_s=600.0, flight_cooldown_s=600.0,
                   flight_window_s=600.0)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def skewed_prompts(rs, n, system):
    """60% share the 64-token system prompt (handoff-eligible via the
    prefill tier); 40% shorter distinct backgrounds."""
    prompts = []
    for i in range(n):
        if i % 5 < 3:
            tail = rs.randint(1, VOCAB, (int(rs.randint(2, 9)),))
            prompts.append(np.concatenate([system, tail.astype(np.int32)]))
        else:
            prompts.append(rs.randint(
                1, VOCAB, (int(rs.randint(3, 25)),)).astype(np.int32))
    return prompts


def scrape(port, path="/metrics"):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read().decode()


def assert_scrape(text):
    """The Prometheus exposition must carry the SLO histograms and the
    failover counters as labeled series covering every replica."""
    for needle in ("ff_serving_ttft_seconds_bucket",
                   "ff_serving_intertoken_seconds_bucket",
                   "ff_serving_queue_wait_seconds_bucket",
                   "ff_router_ttft_seconds_bucket",
                   "ff_router_fenced", "ff_router_resubmitted",
                   "ff_router_timeouts", "ff_router_rejected",
                   "ff_router_handoffs", "ff_fleet_prefix_hits",
                   "ff_router_replica_up",
                   # ISSUE 15: the HBM accounting ledger rides every
                   # scrape (per-subsystem device-memory gauges)
                   "ff_hbm_bytes", "ff_hbm_total_tracked_bytes"):
        assert needle in text, f"scrape missing {needle}"
    for r, role in ((0, "prefill"), (1, "decode"), (2, "decode")):
        assert f'replica="{r}",role="{role}"' in text, \
            f"scrape has no series for replica {r} ({role})"
    print("obs_smoke[scrape]: histograms + failover counters present, "
          "series cover all 3 replicas")


def assert_trace_file(path):
    """Perfetto-loadability: a JSON object with a traceEvents list whose
    events carry the Chrome trace-event required keys."""
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs, "empty traceEvents"
    for ev in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(ev), ev
        assert ev["ph"] in ("X", "i"), ev
        if ev["ph"] == "X":
            assert "dur" in ev and ev["dur"] >= 0, ev
    print(f"obs_smoke[trace]: {len(evs)} events, chrome/perfetto schema "
          f"valid -> {path}")
    return evs


def _bundle_tree_complete(evs, trace_id):
    """Span-tree completeness re-derived from the BUNDLE's trace file
    (not the live ring): a root "request" span exists and every other
    span of the trace id starts inside it."""
    mine = [e for e in evs
            if e.get("args", {}).get("trace_id") == trace_id
            and e["ph"] == "X"]
    roots = [e for e in mine if e["name"] == "request"]
    if not roots:
        return False
    root = max(roots, key=lambda e: e.get("dur", 0.0))
    t0, t1 = root["ts"], root["ts"] + root.get("dur", 0.0)
    return all(t0 - 1.0 <= e["ts"] <= t1 + 1.0
               for e in mine if e is not root)


def postmortem_leg(reqs):
    """ISSUE 15: the crash drill's trigger storm (crash fault + replica
    fence) must have produced exactly ONE intact bundle naming its
    cause, whose trace holds complete span trees for every failed-over
    request."""
    path = flightrec.recorder().flush()
    assert path, "the drill tripped no flight record"
    bundles = flightrec.list_bundles(FLIGHT_DIR)
    assert len(bundles) == 1, \
        f"crash storm must write ONE bundle, found {bundles}"
    flightrec.verify_bundle(path)          # manifest-intact
    trig = json.load(open(os.path.join(path, "trigger.json")))
    causes = [trig["cause"]] + [m["cause"]
                                for m in trig["merged_triggers"]]
    # the crash fault annotation fires first (it opens the pending
    # record); the fence it causes merges in
    assert trig["cause"] == "fault" \
        and trig["args"]["kind"] == "crash", trig
    assert "replica_fence" in causes, causes
    assert trig["stack"]
    evs = json.load(open(os.path.join(path, "trace.json")))["traceEvents"]
    failed_over = [r for r in reqs if r.losses >= 1 and r.state == "done"]
    assert failed_over, "the crash caught no in-flight work"
    for r in failed_over:
        assert _bundle_tree_complete(evs, r.trace_id), \
            f"bundle trace incomplete for failed-over {r.trace_id}"
    engines = json.load(open(os.path.join(path, "engines.json")))
    assert "router" in engines and engines["router"]["stats"]["fenced"] == 1
    hbm = json.load(open(os.path.join(path, "hbm.json")))
    assert any(s.get("kv_pool", 0) > 0 for s in hbm["sources"].values())
    print(f"obs_smoke[postmortem]: ONE intact bundle ({causes}), "
          f"{len(failed_over)} failed-over span trees complete, "
          f"router + HBM ledger embedded -> {path}")


def healthz(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def slo_leg(ff, port):
    """ISSUE 15: a deterministic TTFT breach (the slow() admission
    fault) flips /healthz to "breach" within one evaluation window and
    recovers under healthy traffic (hysteresis)."""
    window_s = 0.5
    ff.config.slo_ttft_p99_s = 0.15
    ff.config.slo_window_s = window_s
    ff.config.slo_clear_windows = 2
    try:
        eng = ff.make_serving_engine(max_seq_len=64,
                                     decode_buckets=[16])
        eng.set_telemetry_identity("slo", "solo")
        rs = np.random.RandomState(17)
        prompts = [rs.randint(1, VOCAB, (8,)).astype(np.int32)
                   for _ in range(4)]
        eng.warmup(prompts, max_new_tokens=4)  # also rebaselines SLOs
        code, roll = healthz(port)
        assert code == 200 and roll["status"] != "breach", roll
        # the drill: stall the next admission 400ms >> the 150ms ceiling
        os.environ["FF_FAULT"] = "slow(400)@serve:1"
        faultinject.reset()
        t0 = time.perf_counter()
        eng.run(prompts, max_new_tokens=4)
        deadline = t0 + 12 * window_s
        code = 200
        while time.perf_counter() < deadline:
            code, roll = healthz(port)     # the GET drives evaluation
            if roll["status"] == "breach":
                break
            time.sleep(0.05)
        t_breach = time.perf_counter() - t0
        assert roll["status"] == "breach", \
            f"no breach within {deadline - t0:.1f}s: {roll}"
        assert code == 503
        assert isinstance(roll["slos"]["ttft_p99"], list)
        text = scrape(port)
        assert 'ff_slo_breach_total{slo="ttft_p99"' in text
        assert 'ff_slo_margin{slo="ttft_p99"' in text
        # recovery: healthy traffic through clear_windows windows
        deadline = time.perf_counter() + 30 * window_s
        while time.perf_counter() < deadline:
            eng.run(prompts[:2], max_new_tokens=2)
            code, roll = healthz(port)
            if roll["status"] != "breach":
                break
            time.sleep(0.05)
        assert roll["status"] != "breach", f"breach never cleared: {roll}"
        assert code == 200
        print(f"obs_smoke[slo]: /healthz flipped to breach "
              f"{t_breach:.2f}s after the slow() fault "
              f"(window {window_s}s) and recovered to "
              f"{roll['status']!r}")
    finally:
        os.environ.pop("FF_FAULT", None)
        faultinject.reset()
        ff.config.slo_ttft_p99_s = 0.0


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    os.environ["FF_FAULT"] = f"crash(6)@replica:{CRASH_REPLICA}"
    faultinject.reset()
    ff = build_model()
    rs = np.random.RandomState(0)
    system = rs.randint(1, VOCAB, (64,)).astype(np.int32)
    prompts = skewed_prompts(rs, n_requests, system)

    port = telemetry.start_http_server(0)
    router = ff.make_serving_router(
        replicas=3, roles=["prefill", "decode", "decode"],
        max_seq_len=112, decode_buckets=[32, 96], start=False)
    warm_tail = rs.randint(1, VOCAB, (3,)).astype(np.int32)
    router.warmup([rs.randint(1, VOCAB, (10,)).astype(np.int32),
                   rs.randint(1, VOCAB, (18,)).astype(np.int32),
                   np.concatenate([system, warm_tail]),
                   np.concatenate([system, warm_tail + 1])],
                  max_new_tokens=4)
    warm_compiles = [e.recompile_count for e in router.engines]

    t0 = time.perf_counter()
    reqs = [router.submit(p, 12) for p in prompts]
    router.start()
    # mid-run scrape: wait for partial progress, then hit /metrics while
    # the fleet is still decoding
    mid_text = None
    while any(not r.settled for r in reqs):
        done = sum(r.state == "done" for r in reqs)
        if mid_text is None and 5 <= done < n_requests:
            mid_text = scrape(port)
        time.sleep(0.02)
        if time.perf_counter() - t0 > 1800:
            raise TimeoutError("fleet did not settle")
    if mid_text is None:        # everything settled between polls
        mid_text = scrape(port)
    router.wait(reqs, timeout=60)
    dt = time.perf_counter() - t0
    st = router.stats()
    print(f"obs_smoke: {st['completed']}/{n_requests} done in {dt:.1f}s "
          f"— handoffs {st['handoffs']}, fenced {st['fenced']}, "
          f"resubmitted {st['resubmitted']}")
    assert st["completed"] == n_requests, "requests lost under the drill"
    assert st["fenced"] == 1 and st["resubmitted"] >= 1
    assert st["handoffs"] >= 1

    # (a) the scrape
    assert_scrape(mid_text)
    # JSON snapshot API serves the same registry
    snap = json.loads(scrape(port, "/metrics.json"))
    assert snap["ff_serving_ttft_seconds"]["type"] == "histogram"

    # (b) the trace file
    out = os.environ.get("OBS_TRACE_OUT", "/tmp/ff_obs_trace.json")
    telemetry.export_chrome_trace(out)
    assert_trace_file(out)

    # every submitted request in the ring has a complete span tree.
    # The ring is bounded — under a huge N old spans fall off; this
    # smoke's volume fits, and we assert that assumption too.
    missing = 0
    for r in reqs:
        tree = telemetry.trace_tree(r.trace_id)
        if not tree["complete"]:
            missing += 1
            continue
        assert tree["root"]["name"] == "request"
        assert {"queue_wait", "prefill", "decode"} <= set(tree["names"]), \
            (r.trace_id, tree["names"])
    assert missing == 0, f"{missing} requests lack a complete span tree"
    print(f"obs_smoke[spans]: all {n_requests} requests have complete "
          f"span trees")

    # crash-failover request: one connected tree across two replicas
    resub = [r for r in reqs if r.losses >= 1 and r.state == "done"]
    assert resub, "the crash caught no in-flight work"
    crossed = 0
    for r in resub:
        tree = telemetry.trace_tree(r.trace_id)
        marks = [e["name"] for e in tree["annotations"]]
        assert "resubmit" in marks, (r.trace_id, marks)
        tracks = {e["pid"] for e in tree["spans"]
                  if e["pid"].startswith("replica")}
        if len(tracks) >= 2:
            crossed += 1
    assert crossed >= 1, "no failover trace crossed two replicas"
    print(f"obs_smoke[failover]: {len(resub)} failed-over requests, "
          f"{crossed} with spans on both replicas under one trace id")

    # handoff request: prefill-replica export + decode-replica import,
    # one tree
    handed = [r for r in reqs if r.handoff and r.state == "done"]
    assert handed, "no request went through the handoff path"
    ok_handoff = 0
    for r in handed:
        tree = telemetry.trace_tree(r.trace_id)
        by = {}
        for e in tree["spans"]:
            by.setdefault(e["name"], set()).add(e["pid"])
        if ("handoff_export" in by and "handoff_import" in by
                and f"replica{0}" in by["handoff_export"]
                and by.get("decode", set()) - {"replica0"}):
            ok_handoff += 1
    assert ok_handoff >= 1, "no handoff trace spans prefill AND decode"
    print(f"obs_smoke[handoff]: {ok_handoff}/{len(handed)} handoff "
          f"traces connect prefill export -> decode import")

    # the fault annotation marks the drill's landing site
    faults = telemetry.fault_events()
    assert any(e["args"]["kind"] == "crash"
               and e["args"]["site"] == "replica"
               and e["args"]["index"] == CRASH_REPLICA
               for e in faults), faults
    print("obs_smoke[fault]: crash annotation present at "
          f"replica:{CRASH_REPLICA}")

    # zero survivor recompiles through all of it: telemetry must not
    # perturb the compiled-program story
    for r in (0, 2):
        assert router.engines[r].recompile_count == warm_compiles[r], \
            f"replica {r} recompiled after warmup"

    # ISSUE 15 leg 1: the crash drill's post-mortem bundle
    postmortem_leg(reqs)

    router.close()
    # drop the drilled fleet so its weakly-held health probes die —
    # the SLO leg's recovery must read the solo engine's health, not a
    # permanently-fenced corpse
    del router
    import gc

    gc.collect()

    # ISSUE 15 leg 2: deterministic SLO breach + /healthz flip + recovery
    slo_leg(ff, port)

    telemetry.stop_http_server()
    print("obs_smoke: PASSED")


if __name__ == "__main__":
    main()
