"""On-chip MFU probe: time the bench `full` transformer config under one
configuration knob per run, via the scanned multi-step trainer (so the
numbers are free of the tunnel's per-dispatch latency).

Usage (one jax process at a time — tunnel rule):
    python scripts/mfu_probe.py --no-flash          # XLA einsum attention
    python scripts/mfu_probe.py --heads 8           # head_dim 128
    python scripts/mfu_probe.py --master bfloat16
    python scripts/mfu_probe.py --seq 1024 --layers 4

Prints one JSON line comparable with the bench full_scan tier.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--hidden", type=int, default=1024)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--master", default="float32")
    p.add_argument("--no-flash", action="store_true")
    p.add_argument("--fused-ln", action="store_true")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args()

    import jax

    cache_dir = os.path.join(REPO, ".xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import numpy as np

    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.models.transformer import build_encoder_classifier
    from flexflow_tpu.ops.base import InputOp

    dev = jax.devices()[0]
    cfg = FFConfig(batch_size=args.batch, mesh_shape={"data": 1},
                   compute_dtype=args.dtype, master_dtype=args.master,
                   use_fused_ln=args.fused_ln,
                   use_flash_attention=not args.no_flash)
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, args.batch, args.seq, args.hidden,
                                      args.layers, args.heads)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    n = args.batch * 4
    SingleDataLoader(ff, x, rs.randn(n, args.seq, args.hidden)
                     .astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (n, 1)).astype(np.int32))

    losses, _ = ff.train_scanned(args.iters)  # compile + warm
    float(losses[-1])
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        losses, _ = ff.train_scanned(args.iters)
        float(losses[-1])
        dts.append((time.perf_counter() - t0) / args.iters)
    dt = min(dts)

    fwd = sum(op.flops() for op in ff.ops if not isinstance(op, InputOp))
    # same roofline denominator as the bench rows this probe is compared
    # against (device_kind lookup + measured-matmul fallback)
    from bench import _peak_flops_per_chip

    peak, _ = _peak_flops_per_chip(dev, dev.platform)
    print(json.dumps({
        "knobs": {"flash": not args.no_flash, "heads": args.heads,
                  "master": args.master, "fused_ln": args.fused_ln,
                  "seq": args.seq, "layers": args.layers,
                  "hidden": args.hidden, "batch": args.batch},
        "backend": dev.platform,
        "samples_per_s": round(args.batch / dt, 2),
        "step_time_ms": round(dt * 1e3, 3),
        "mfu": round(3 * fwd / dt / peak, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
