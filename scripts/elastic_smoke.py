#!/usr/bin/env python
"""CI elastic-recovery smoke (ci/run_ci.sh `elastic` tier).

Two legs, both deterministic on CPU:

  corrupt  — single process: a supervised run checkpoints periodically
             with FF_FAULT=corrupt_ckpt@save:<last> flipping bytes in the
             final save's payload after commit; the restart must FAIL the
             latest step's manifest verification, fall back to the
             previous intact step with a logged warning, and complete.

  shrink   — the changed-topology drill: phase 1 trains on TWO controller
             processes (8-device global mesh) through
             flexflow_tpu.launcher and is preempted mid-epoch
             (FF_FAULT=sigterm@step:5 -> collective checkpoint + stop);
             phase 2 relaunches ONE process whose multi-host rendezvous
             fails fast (dead peer + FF_INIT_TIMEOUT_S) — the launcher's
             --elastic fallback continues single-process, the
             FF_FAULT=shrink(4)@resume:1 fault presents 4 surviving
             devices, and the worker resumes with
             on_topology_change=resume_resharded: mesh refit to data=4,
             grad_accum doubled (global batch preserved), loss still
             decreasing. Needs gloo CPU collectives (the CI tier probes).

Usage: python scripts/elastic_smoke.py [corrupt|shrink|all]
"""

import os
import re
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def run_corrupt_leg():
    from flexflow_tpu._env import force_cpu_devices

    force_cpu_devices(2)

    import numpy as np

    from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer, SingleDataLoader,
                              TrainSupervisor)
    from flexflow_tpu.runtime import faultinject
    from flexflow_tpu.runtime.checkpoint import (latest_intact_step,
                                                 latest_step)

    ckpt = tempfile.mkdtemp(prefix="ff_elastic_corrupt_")

    def build():
        cfg = FFConfig(batch_size=16, epochs=1, seed=3, checkpoint_dir=ckpt,
                       checkpoint_every=2, mesh_shape={"data": 2})
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 8], name="x")
        t = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="fc1")
        ff.dense(t, 4, name="out")
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(7)
        SingleDataLoader(ff, x, rs.randn(64, 8).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 4, (64, 1)).astype(np.int32))
        return ff

    # saves land at steps 1, 3, 5 (periodic) + 6 (final) — occurrence 4,
    # the LATEST checkpoint, is corrupted AFTER it publishes
    os.environ["FF_FAULT"] = "corrupt_ckpt@save:4"
    faultinject.reset()
    ff = build()
    sup = TrainSupervisor(ff, ckpt)
    assert sup.run(6) == "completed"
    os.environ.pop("FF_FAULT")
    faultinject.reset()
    assert latest_step(ckpt) == 6
    intact = latest_intact_step(ckpt)
    assert intact == 5, f"expected intact step 5 behind corrupt 6, got {intact}"

    # the restart: verification rejects step 6, resume falls back to 5
    ff2 = build()
    sup2 = TrainSupervisor(ff2, ckpt)
    resumed = sup2.resume()
    assert resumed == 5, f"resumed from {resumed}, wanted intact step 5"
    assert sup2.run(10) == "completed"
    assert ff2._step_count == 10
    assert np.isfinite(sup2.losses).all()
    print(f"elastic_smoke[corrupt]: latest=6 corrupt -> resumed from "
          f"intact step {resumed}, completed to step 10  PASSED")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device counts
    env.pop("FF_FAULT", None)
    env["JAX_PLATFORMS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


def _parse_marker(out: str) -> dict:
    m = re.search(r"ELASTIC pid=(\d+) status=(\w+) resumed=(\w+) "
                  r"step=(\d+) mesh=(\S+) accum=(\d+) procs=(\d+) "
                  r"loss_ok=(\d)", out)
    assert m, f"no ELASTIC marker in output:\n{out[-4000:]}"
    return {"pid": int(m.group(1)), "status": m.group(2),
            "resumed": m.group(3), "step": int(m.group(4)),
            "mesh": m.group(5), "accum": int(m.group(6)),
            "procs": int(m.group(7)), "loss_ok": int(m.group(8))}


def run_shrink_leg():
    ckpt = tempfile.mkdtemp(prefix="ff_elastic_shrink_")

    # ---- phase 1: 2-process run on the 8-device mesh, preempted at step 5
    port = _free_port()
    procs = []
    for pid in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flexflow_tpu.launcher", WORKER,
             "--num-processes", "2", "--process-id", str(pid),
             "--coordinator", f"127.0.0.1:{port}",
             "--cpu-devices", "4", "--", ckpt, "10"],
            env=_worker_env(FF_FAULT="sigterm@step:5"), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=400)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"phase-1 worker {pid} failed:\n" \
                                  f"{out[-4000:]}"
        mk = _parse_marker(out)
        assert mk["status"] == "preempted" and mk["step"] == 5, mk
        assert mk["procs"] == 2 and mk["mesh"] == "data=8", mk
    print("elastic_smoke[shrink]: phase 1 OK — 2-process mesh data=8 "
          "preempted at step 5, collective checkpoint written")

    # ---- phase 2: the surviving host relaunches with its OLD multi-host
    # flags; the coordinator is gone, the launcher's elastic probe detects
    # that fast (a real initialize would hard-terminate the process on
    # this jax build), continues single-process, and shrink(4)@resume
    # presents the 4 surviving devices
    dead_port = _free_port()
    p = subprocess.Popen(
        [sys.executable, "-m", "flexflow_tpu.launcher", WORKER,
         "--num-processes", "2", "--process-id", "1",
         "--coordinator", f"127.0.0.1:{dead_port}",
         "--cpu-devices", "8", "--elastic", "--", ckpt, "10"],
        env=_worker_env(FF_FAULT="shrink(4)@resume:1",
                        FF_INIT_ATTEMPTS="1", FF_INIT_TIMEOUT_S="5"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    out, _ = p.communicate(timeout=400)
    assert p.returncode == 0, f"phase-2 worker failed:\n{out[-4000:]}"
    assert "continuing SINGLE-process" in out, out[-4000:]
    assert "shrink@resume" in out, out[-4000:]
    mk = _parse_marker(out)
    assert mk["status"] == "completed" and mk["step"] == 10, mk
    assert mk["resumed"] == "5", mk
    assert mk["procs"] == 1 and mk["mesh"] == "data=4", mk
    assert mk["accum"] == 2, f"grad accum must double to preserve the " \
                             f"global batch: {mk}"
    assert mk["loss_ok"] == 1, f"post-resume loss not decreasing: {mk}"
    print("elastic_smoke[shrink]: phase 2 OK — rendezvous failed fast, "
          "single-process resume resharded data=8 -> data=4, accum 1 -> 2, "
          "loss decreasing")

    # ---- phase 3: the COORDINATOR host (process 0) is the survivor this
    # time. It has nothing to probe (it IS the rendezvous address), so the
    # elastic path listens for a peer knock instead; none comes, it
    # continues single-process. The checkpoints now record mesh data=4
    # with accum=2 — a same-topology restart must ADOPT the saved accum
    # (the product of phase 2's elastic resume), not reset it to the
    # config default of 1.
    p = subprocess.Popen(
        [sys.executable, "-m", "flexflow_tpu.launcher", WORKER,
         "--num-processes", "2", "--process-id", "0",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         "--cpu-devices", "4", "--elastic", "--", ckpt, "12"],
        env=_worker_env(FF_INIT_ATTEMPTS="1", FF_INIT_TIMEOUT_S="5"),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    out, _ = p.communicate(timeout=400)
    assert p.returncode == 0, f"phase-3 worker failed:\n{out[-4000:]}"
    assert "no peer knocked" in out, out[-4000:]
    mk = _parse_marker(out)
    assert mk["status"] == "completed" and mk["step"] == 12, mk
    assert mk["resumed"] == "10" and mk["procs"] == 1, mk
    assert mk["mesh"] == "data=4", mk
    assert mk["accum"] == 2, f"same-topology restart must adopt the " \
                             f"checkpoint's accum, not reset it: {mk}"
    print("elastic_smoke[shrink]: phase 3 OK — surviving coordinator "
          "heard no peer knock, continued single-process, adopted the "
          "checkpoint's accum=2 on the unchanged mesh  PASSED")


def main():
    leg = sys.argv[1] if len(sys.argv) > 1 else "all"
    if leg in ("corrupt", "all"):
        run_corrupt_leg()
    if leg in ("shrink", "all"):
        run_shrink_leg()
    print(f"elastic_smoke({leg}): PASSED")


if __name__ == "__main__":
    main()
