#!/usr/bin/env python
"""CI multi-tenant serving smoke (ci/run_ci.sh `tenancy` tier): 8 LoRA
adapters x mixed sampling configs on a 2-replica fleet, with FF_FAULT
``crash(<tick>)@replica:0`` felling replica 0 mid-flight. Proves the
ISSUE-14 acceptance end to end on CPU:

  * 8 tenants (mixed greedy / temperature / top-p / top-k configs)
    serve concurrently through one fleet — every request completes
    exactly once;
  * every stream (sampled AND greedy) is token-identical to its solo
    single-engine reference at the same seed, THROUGH the failover
    resubmission — the counter-based per-request RNG replays
    bit-for-bit on the survivor;
  * ZERO warm-window recompiles on the survivor: tenant churn, adapter
    fault-ins and sampling-config mixes are data, not programs;
  * adapter-pool pressure (8 adapters through a 5-page pool) evicts at
    least one adapter and re-faults it in, with the re-faulted tenant's
    stream unchanged;
  * per-adapter telemetry: ff_serving_requests_total{adapter=...}
    series exist for every tenant.

Usage: [FF_FAULT=crash(6)@replica:0] python scripts/tenancy_smoke.py [N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402
from flexflow_tpu.runtime import telemetry  # noqa: E402

VOCAB = 64
RANK = 4
POOL_PAGES = 5
N_ADAPTERS = 8


def build_model():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1})
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=32, layers=1, heads=2,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def adapter_weights(geometry, seed):
    rs = np.random.RandomState(seed)
    return {name: {"a": (rs.randn(din, RANK) * 0.3).astype(np.float32),
                   "b": (rs.randn(RANK, dout) * 0.3).astype(np.float32)}
            for name, (din, dout) in geometry.items()}


def tenant_config(i):
    """Mixed sampling configs: even tenants greedy, odd tenants sampled
    with varying nucleus/top-k filters."""
    if i % 2 == 0:
        return dict(temperature=0.0)
    return dict(temperature=0.7 + 0.1 * (i % 4),
                top_p=1.0 if i % 3 else 0.9,
                top_k=0 if i % 3 == 1 else 8)


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    fault = os.environ.get("FF_FAULT", "")
    ff = build_model()
    rs = np.random.RandomState(0)
    base_prompts = [rs.randint(1, VOCAB, (L,)).astype(np.int32)
                    for L in (5, 9, 6, 12)]
    names = [f"tenant{i}" for i in range(N_ADAPTERS)]

    # the request plan: (prompt, adapter, sampling config, seed) —
    # fixed up front so the fleet run and the solo reference agree
    plan = []
    for j in range(n_requests):
        i = j % N_ADAPTERS
        plan.append((base_prompts[j % len(base_prompts)], names[i],
                     tenant_config(i), 1000 + j))

    eng_kw = dict(serve_slots=4, kv_page_size=4, max_seq_len=64,
                  adapter_pool_pages=POOL_PAGES, lora_rank=RANK)

    # ---- solo reference streams (one engine, no fleet) ----
    ref_eng = ff.make_serving_engine(**eng_kw)
    geo = ref_eng.lora.geometry
    for i, n in enumerate(names):
        ref_eng.register_adapter(n, adapter_weights(geo, i))
    refs = []
    for prompt, adapter, skw, seed in plan:
        r = ref_eng.run([prompt], max_new_tokens=8, adapter=adapter,
                        seed=seed, **skw)[0]
        assert r.state == "done", r.error
        refs.append(list(r.tokens))
    ref_st = ref_eng.stats()
    assert ref_st["adapter_evictions"] >= 1, (
        f"{N_ADAPTERS} adapters through {POOL_PAGES} pages must evict: "
        f"{ref_st['adapter_evictions']}")
    assert ref_st["adapter_refs_live"] == 0
    print(f"PASS solo reference: {len(refs)} streams, "
          f"{ref_st['adapter_faults']} faults, "
          f"{ref_st['adapter_evictions']} evictions (re-fault preserved "
          f"every stream by construction of the plan repeats)")

    # ---- the fleet ----
    router = ff.make_serving_router(replicas=2, start=False, **eng_kw)
    for i, n in enumerate(names):
        router.register_adapter(n, adapter_weights(geo, i))
    router.warmup(base_prompts, max_new_tokens=8)
    # drive one request per tenant per replica OUTSIDE the timed window
    # so tenant-namespace hit-prefill variants and fault-in writes are
    # all exercised before the drill
    for eng in router.engines:
        for i, n in enumerate(names):
            eng.run([base_prompts[i % len(base_prompts)]],
                    max_new_tokens=8, adapter=n, seed=7,
                    **tenant_config(i))
    warm_compiles = [eng.recompile_count for eng in router.engines]

    reqs = [router.submit(p, 8, adapter=a, seed=s, **skw)
            for p, a, skw, s in plan]
    router.start()
    router.wait(reqs, timeout=600)
    st = router.stats()
    assert st["completed"] == n_requests, st
    engine_done = sum(e["completed"] for e in (eng.stats()
                                               for eng in router.engines))
    mismatches = [
        (r.rid, r.tokens, want)
        for r, want in zip(reqs, refs) if list(r.tokens) != want]
    assert not mismatches, (
        f"{len(mismatches)} streams diverged from the solo reference "
        f"(first: {mismatches[0]})")
    if "crash" in fault:
        assert st["fenced"] == 1, \
            f"crash fault armed but fenced == {st['fenced']}"
        assert st["resubmitted"] >= 1, \
            "the crash was supposed to catch work in flight"
        survivor = router.engines[1]
        assert survivor.recompile_count == warm_compiles[1], (
            f"survivor compiled {survivor.recompile_count - warm_compiles[1]}"
            f" programs in the warm window — tenant churn must be data")
        print(f"PASS crash drill: fenced=1, resubmitted="
              f"{st['resubmitted']}, all {n_requests} seeded streams "
              f"(greedy + sampled) token-identical through failover, "
              f"survivor recompiles 0")
    else:
        for r, eng in enumerate(router.engines):
            assert eng.recompile_count == warm_compiles[r], \
                f"replica {r} compiled in the warm window"
        print(f"PASS steady state: {n_requests} requests exactly once "
              f"({engine_done} engine completions), 0 warm recompiles")

    fleet = st["fleet"]
    assert fleet["adapter_faults"] >= N_ADAPTERS, fleet["adapter_faults"]
    assert fleet["sampled_requests"] > 0
    print(f"PASS adapter pool: fleet faults={fleet['adapter_faults']} "
          f"evictions={fleet['adapter_evictions']} "
          f"resident={fleet['adapters_resident']}")

    # per-adapter telemetry series (the ISSUE-14 satellite): every
    # tenant has a labeled ff_serving_requests_total series
    text = telemetry.registry().to_prometheus()
    missing = [n for n in names
               if f'adapter="{n}"' not in text]
    assert not missing, f"missing per-adapter series: {missing}"
    assert "ff_serving_requests_total" in text
    assert "ff_serving_adapter_ttft_seconds" in text
    print("PASS telemetry: per-adapter requests_total + TTFT series "
          "present for all 8 tenants")

    router.drain()
    print("tenancy_smoke: ALL PASS")


if __name__ == "__main__":
    main()
