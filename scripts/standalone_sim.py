"""Standalone strategy-search prototype (analog of the reference's legacy
scripts/simulator.cc: a self-contained MCMC search over a synthetic CNN or
LSTM graph that emits a strategy file, decoupled from any training run).

Where the reference hardcodes a CNN task graph and protobuf output
(scripts/simulator.cc:16-40, scripts/cnn.h), this drives the real framework's
C++ event-driven simulator + MCMC core (search/csrc/sim.cc) over a model
built with the normal builder API, and writes the framework's text strategy
schema (parallel/strategy.py; reference src/runtime/strategy.cc:150-189).

Usage:
  python scripts/standalone_sim.py [--model cnn|lstm|inception]
      [--budget 2000] [--devices 8] [--export strategy.txt]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# cost-model/search only — no device backend needed; keep any accidental jax
# use off the (possibly absent) accelerator
os.environ.setdefault("FLEXFLOW_FORCE_CPU_DEVICES", "1")


def build(model_name: str, ff, batch):
    from flexflow_tpu.models.cnn import alexnet_cifar10, inception_v3
    from flexflow_tpu.models.nmt import nmt_seq2seq

    if model_name == "cnn":
        return alexnet_cifar10(ff, batch)[1]
    if model_name == "inception":
        return inception_v3(ff, batch, num_classes=10)[1]
    if model_name == "lstm":
        return nmt_seq2seq(ff, batch, src_len=10, tgt_len=10, embed_size=64,
                           hidden_size=64, vocab_size=500, num_layers=2)[2]
    raise SystemExit(f"unknown --model {model_name}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="cnn",
                    choices=("cnn", "lstm", "inception"))
    ap.add_argument("--budget", type=int, default=2000)
    ap.add_argument("--alpha", type=float, default=0.05)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--export", default="")
    args = ap.parse_args()

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.parallel.strategy import save_strategies_to_file
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.driver import (data_parallel_strategy,
                                            optimize_strategies)

    mesh_shape = {"data": max(args.devices // 2, 1),
                  "model": 2 if args.devices >= 2 else 1}
    cfg = FFConfig(batch_size=args.batch, mesh_shape=mesh_shape)
    ff = FFModel(cfg)
    build(args.model, ff, args.batch)

    cost = CostModel(ff, mesh_shape)
    dp_ms = cost.iteration_time(
        data_parallel_strategy(ff, mesh_shape)) * 1e3
    best = optimize_strategies(ff, budget=args.budget, alpha=args.alpha,
                               mesh_shape=mesh_shape, verbose=True)
    best_am = {name: (pc.axis_map or {}) for name, pc in best.items()}
    best_places = {name: (min(pc.device_ids) if pc.device_ids else 0)
                   for name, pc in best.items()}
    best_ms = cost.iteration_time(best_am, best_places) * 1e3
    print(f"[standalone_sim] {args.model} on {args.devices} devices: "
          f"DP {dp_ms:.3f} ms -> searched {best_ms:.3f} ms "
          f"({dp_ms / max(best_ms, 1e-9):.2f}x)")
    if args.export:
        save_strategies_to_file(args.export, best)
        print(f"[standalone_sim] strategy written to {args.export}")


if __name__ == "__main__":
    main()
