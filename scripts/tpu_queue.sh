#!/bin/bash
# TPU work queue (round 3): everything hardware-blocked, in priority order.
# Run when the tunnel is live (probe: python -c "import jax; jax.devices()"
# returns within ~90 s). Each step is independent; later steps are gravy.
# Results land in /tmp/tpu_queue/ — fold them into BENCH notes and
# docs/northstar.md.
set -x
OUT=/tmp/tpu_queue
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

# 1. The board number: staged tiers incl. full_scan_opt (bf16 master) and
#    the xl_scan head_dim-128 headline
FF_BENCH_BUDGET=1350 timeout 1400 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"

# 2. Flash streaming kernels at 8k+ on real hardware (the round-3 kernel
#    rework's hardware proof: compile + grad-exactness at the old cap x2)
timeout 900 python - > "$OUT/flash8k.log" 2>&1 <<'EOF'
import jax, jax.numpy as jnp, numpy as np, time
from flexflow_tpu.ops.pallas_kernels import flash_attention
rs = np.random.RandomState(0)
b, s, h, d = 1, 8192, 4, 128
q = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
k = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
v = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 0.088))
o = jax.block_until_ready(f(q, k, v)); t0 = time.perf_counter()
for _ in range(10): o = f(q, k, v)
jax.block_until_ready(o)
print("seq8192 fwd ok", (time.perf_counter()-t0)/10*1e3, "ms/iter")
g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v, True, 0.088).astype(jnp.float32)), argnums=(0,1,2)))
jax.block_until_ready(g(q, k, v)); print("seq8192 bwd compiles+runs OK")
EOF

# 3. ResNet-50 measure tier (the decisive north-star arbitration)
timeout 1800 python scripts/northstar_search.py --workload resnet50 \
    --costs measure --budget 40000 > "$OUT/resnet_measure.json" 2> "$OUT/resnet_measure.err"

# 3b. KV-cache decode throughput (round-3 generation subsystem)
timeout 1200 python scripts/decode_probe.py > "$OUT/decode.json" 2> "$OUT/decode.err"

# 4. Whole-program strategy validation on chip (single chip -> DP-1 configs
#    only; mesh-shaped runs need the virtual mesh, so this validates the
#    cost-measurement path end to end rather than multi-chip ranking)
timeout 900 python scripts/validate_strategies.py --budget 2000 --steps 10 \
    > "$OUT/validate.json" 2> "$OUT/validate.err"

echo "tpu_queue: done; results in $OUT"
