"""On-chip decode throughput probe: tokens/s for the KV-cache generate
path (runtime/generation.py) on a Llama-shaped decoder.

Decode is HBM-bandwidth-bound (each step streams all params + the KV
cache prefix through the chip for one token per row), so the roofline
metric here is achieved HBM GB/s = (param_bytes + kv_bytes) / step_time,
not MFU. Prints one JSON line per config.

Run on the real chip: python scripts/decode_probe.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from flexflow_tpu import FFConfig, FFModel
from flexflow_tpu.models.llama import llama_lm

CONFIGS = [
    # (batch, hidden, layers, heads, kv_heads, prompt, new)
    (8, 1024, 8, 8, 4, 256, 128),
    (32, 1024, 8, 8, 4, 256, 128),
    (8, 2048, 16, 16, 8, 256, 128),
]
if os.environ.get("FF_DECODE_PROBE_TINY"):  # CPU smoke of the script
    CONFIGS = [(2, 64, 2, 4, 2, 16, 8)]


def param_bytes(ff):
    return sum(int(np.prod(w.shape)) * w.dtype.itemsize
               for ws in ff.params.values() for w in ws.values())


def main():
    import jax

    backend = jax.default_backend()
    for batch, hidden, layers, heads, kvh, prompt_len, new in CONFIGS:
        cfg = FFConfig(batch_size=batch, compute_dtype="bfloat16",
                       master_dtype="bfloat16")
        ff = FFModel(cfg)
        _, logits = llama_lm(ff, batch, seq_len=prompt_len, hidden=hidden,
                             layers=layers, heads=heads, kv_heads=kvh,
                             vocab_size=32_000)
        ff.compile(final_tensor=logits)
        rs = np.random.RandomState(0)
        prompt = rs.randint(0, 32_000, (batch, prompt_len)).astype(np.int32)

        for quant in (None, "int8"):
            t0 = time.time()
            out = ff.generate(prompt, new, quantize=quant)
            compile_s = time.time() - t0
            t0 = time.time()
            iters = 3
            for i in range(iters):
                out = ff.generate(prompt, new, seed=i, quantize=quant)
            wall = (time.time() - t0) / iters
            tok_s = batch * new / wall
            step_ms = wall / new * 1e3
            d = hidden // heads
            kv_avg = batch * (prompt_len + new / 2) * kvh * d * 2 * 2 * layers
            pbytes = param_bytes(ff)
            if quant == "int8":
                # bytes of the ACTUAL quantized pytree (q + per-channel
                # scales + the 1-D weights that stay full precision) —
                # pbytes//2 overstates the cut and the reported bandwidth
                import jax as _jax

                gen = next(g for g in ff._generators.values()
                           if g.quantize == "int8")
                pbytes = sum(
                    x.nbytes for x in
                    _jax.tree_util.tree_leaves(gen._quantized_params()))
            hbm_gbs = (pbytes + kv_avg) / (wall / new) / 1e9
            print(json.dumps({
                "metric": "llama_decode_throughput", "unit": "tokens/s",
                "value": round(tok_s, 1), "step_ms": round(step_ms, 3),
                "approx_hbm_gbs": round(hbm_gbs, 1),
                "compile_s": round(compile_s, 1), "backend": backend,
                "weights": quant or "bf16",
                "config": {"batch": batch, "hidden": hidden,
                           "layers": layers, "heads": heads,
                           "kv_heads": kvh, "prompt": prompt_len,
                           "new_tokens": new},
            }), flush=True)


if __name__ == "__main__":
    main()
