#!/bin/bash
# MFU-lever ablation, round 4 (VERDICT r3 #4): quantify the fused
# single-kernel optimizer update on the d=64 `full` config — the shape
# imported BERT/ViT models actually have — and arbitrate the parked
# fused-LN kernel in its claimed wide-hidden regime (hidden 4096).
# Runs the bench CHILD directly, one lever combination per process, on
# scanned tiers (all iters inside ONE device program — rows free of the
# tunnel's per-dispatch latency).
# Strictly serialized: the axon tunnel wedges a second jax process at
# `import jax`, so never run this while any other jax process (bench,
# tests, search) is alive.
#
# Baseline rows come from the staged bench itself: full_scan (no levers),
# full_scan_opt (bf16 master only), xxl_scan (bf16 master, no fused LN).
set -x
OUT=${1:-/tmp/mfu_ablation}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

ALL_TIERS="tiny,mid,full,full_scan,full_scan_opt,xl_scan,xxl_scan"

run_combo() { # name tier master_dtype fused_ln fused_opt
  local skip
  skip=$(echo "$ALL_TIERS" | tr ',' '\n' | grep -v "^$2\$" | paste -sd,)
  FF_BENCH_CHILD=1 \
  FF_BENCH_SKIP_TIERS="$skip" \
  FF_BENCH_MASTER_DTYPE="$3" FF_BENCH_FUSED_LN="$4" FF_BENCH_FUSED_OPT="$5" \
  FF_BENCH_DEADLINE=$(($(date +%s) + 540)) \
  timeout 560 python bench.py > "$OUT/$1.json" 2> "$OUT/$1.err"
  # a tunnel drop makes the child fall back to a CPU cpu_smoke run that
  # would masquerade as an ablation row — quarantine anything non-TPU
  if ! grep -q '"backend": "tpu"' "$OUT/$1.json"; then
    mv "$OUT/$1.json" "$OUT/$1.json.not-tpu"
    echo "ablation row $1: NOT a TPU run, quarantined"
  fi
}

# fused optimizer on the d=64 full config: alone, then with bf16 master
# (the full-tier >=0.62 candidate)
run_combo fused_opt_only   full_scan_opt float32  0 1
run_combo bf16_fused_opt   full_scan_opt bfloat16 0 1
# fused-LN arbitration at hidden 4096 (its claimed win regime; baseline =
# the staged bench's plain xxl_scan row)
run_combo fused_ln_wide    xxl_scan      bfloat16 1 0
echo "mfu_ablation: done; results in $OUT"
