#!/bin/bash
# MFU-lever ablation on the bench `full` config (VERDICT r2 #4).
# Runs the bench CHILD directly, one lever combination per process, on the
# full_scan_opt tier with env-overridden levers: the scanned tier runs all
# iters inside ONE device program, so the rows are free of the tunnel's
# per-dispatch latency and isolate the levers themselves.
# Strictly serialized: the axon tunnel wedges a second jax process at
# `import jax`, so never run this while any other jax process (bench,
# tests, search) is alive.
#
# Rows: base (both off) = the staged bench's full_scan tier; both on =
# its full_scan_opt tier; this script fills in the two single-lever rows.
set -x
OUT=${1:-/tmp/mfu_ablation}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run_combo() { # name master_dtype fused_ln
  # deadline via shell arithmetic — spawning python here would dial the
  # tunnel through sitecustomize and can hang if it is half-open
  FF_BENCH_CHILD=1 \
  FF_BENCH_SKIP_TIERS=tiny,mid,full,full_scan,xl_scan \
  FF_BENCH_MASTER_DTYPE="$2" FF_BENCH_FUSED_LN="$3" \
  FF_BENCH_DEADLINE=$(($(date +%s) + 540)) \
  timeout 560 python bench.py > "$OUT/$1.json" 2> "$OUT/$1.err"
  # a tunnel drop makes the child fall back to a CPU cpu_smoke run that
  # would masquerade as an ablation row — quarantine anything non-TPU
  if ! grep -q '"backend": "tpu"' "$OUT/$1.json"; then
    mv "$OUT/$1.json" "$OUT/$1.json.not-tpu"
    echo "ablation row $1: NOT a TPU run, quarantined"
  fi
}

run_combo bf16_master_only bfloat16 0
run_combo fused_ln_only float32 1
echo "mfu_ablation: done; results in $OUT"
