#!/usr/bin/env python
"""CI rolling-deployment smoke (ci/run_ci.sh `deploy` tier): a 2-replica
fleet under a skewed closed-loop flood, a new weight version published
mid-flood, and a RollingDeployer rolling the fleet onto it one replica
at a time. Proves the ISSUE-17 acceptance end to end on CPU:

  leg 1 — rolling swap under load:
  * every flood request is served EXACTLY ONCE through the roll — none
    dropped, none duplicated (router ledger == per-engine completions);
  * the fleet never falls below N-1 capacity (at most one replica
    suspended at any sampled instant, zero fenced);
  * ZERO recompiles anywhere in the warm window: the same-geometry swap
    keeps every fixed-shape program valid on the swapped replica, and
    the survivor never compiles under the rerouted load;
  * post-roll traffic is token-identical to a reference model holding
    the NEW weights (and the fleet reports the new version everywhere).

  leg 2 — canary breach -> automatic rollback:
  * FF_FAULT ``slow(<ms>)@canary`` stalls the freshly-swapped canary's
    admissions, deterministically breaching its rebaselined TTFT SLO;
  * the deployer rolls the fleet BACK — every replica ends on the prior
    version, traffic still exactly-once and token-identical to it;
  * exactly ONE manifest-intact flight-recorder bundle lands, its
    trigger naming the breached SLO.

Usage: python scripts/deploy_smoke.py [N_per_leg]
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

import jax  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402
from flexflow_tpu.runtime import faultinject, flightrec  # noqa: E402
from flexflow_tpu.runtime.deploy import (RollingDeployer,  # noqa: E402
                                         WeightArtifactRegistry)

VOCAB = 128
MAX_NEW = 12


def build_model():
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=4,
                   kv_page_size=8, slo_window_s=1.0)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


def publish_bumped(ff, registry, step, scale):
    """Publish a same-geometry, visibly-different weight tree as
    v<step> — the 'new training run' — leaving the model untouched."""
    keep = ff.params
    bumped = jax.tree_util.tree_map(
        lambda x: (np.asarray(x) * scale).astype(np.asarray(x).dtype),
        keep)
    ff.params = ff.executor.reshard_params(bumped)
    try:
        return registry.publish(ff, step=step)
    finally:
        ff.params = keep


class Feeder(threading.Thread):
    """Closed-loop skewed flood: keeps up to ``max_inflight`` requests
    open (80% share a 64-token system prompt) until stopped, sampling
    the fleet's suspension count every iteration — the capacity>=N-1
    witness."""

    def __init__(self, router, rs, system, max_inflight=12):
        super().__init__(daemon=True)
        self.router, self.rs, self.system = router, rs, system
        self.max_inflight = max_inflight
        self.reqs, self.max_suspended = [], 0
        self._halt = threading.Event()

    def _prompt(self):
        if self.rs.randint(5) < 4:
            tail = self.rs.randint(
                1, VOCAB, (int(self.rs.randint(1, 8)),)).astype(np.int32)
            return np.concatenate([self.system, tail])
        return self.rs.randint(
            1, VOCAB, (int(self.rs.randint(3, 25)),)).astype(np.int32)

    def run(self):
        while not self._halt.is_set():
            self.max_suspended = max(
                self.max_suspended, sum(self.router._suspended))
            if sum(1 for r in self.reqs
                   if not r.settled) >= self.max_inflight:
                time.sleep(0.004)
                continue
            self.reqs.append(self.router.submit(self._prompt(), MAX_NEW))

    def stop(self):
        self._halt.set()
        self.join(timeout=60)


def ref_tokens(ff, tree, prompt):
    """Solo greedy reference under ``tree`` (the fleet must match it)."""
    keep = ff.params
    ff.params = tree
    try:
        out = ff.generate(prompt[None, :], max_new_tokens=MAX_NEW)
    finally:
        ff.params = keep
    return out[0, prompt.size:]


def settle(router, feeder, engines_before, warmups_since):
    """Stop the flood, wait everything out, and assert the exactly-once
    ledger: router completions == flood size, per-engine completions ==
    flood + the deploy warmups that ran engine-side."""
    feeder.stop()
    router.wait(feeder.reqs, timeout=1200)
    n = len(feeder.reqs)
    assert all(r.settled for r in feeder.reqs), "requests lost"
    assert [r.state for r in feeder.reqs] == ["done"] * n, \
        f"{sum(1 for r in feeder.reqs if r.state != 'done')} of {n} " \
        f"requests did not complete through the roll"
    engine_done = sum(e.stats()["completed"] for e in router.engines) \
        - engines_before
    assert engine_done == n + warmups_since, (
        f"engines completed {engine_done} != {n} flood + "
        f"{warmups_since} warmup: duplicated or dropped work")
    assert all(r.attempts == 1 for r in feeder.reqs), \
        "no fault was armed that justifies a resubmission"
    return n


def main():
    n_target = int(sys.argv[1]) if len(sys.argv) > 1 else 80
    work = tempfile.mkdtemp(prefix="ff_deploy_smoke_")
    watch = os.path.join(work, "watch")
    flight = os.path.join(work, "flight")
    os.makedirs(flight)
    ff = build_model()
    registry = WeightArtifactRegistry(watch)
    rs = np.random.RandomState(0)
    system = rs.randint(1, VOCAB, (64,)).astype(np.int32)  # 8 full pages

    router = ff.make_serving_router(
        replicas=2, max_seq_len=112, decode_buckets=[32, 96], start=False)
    warm_tail = rs.randint(1, VOCAB, (3,)).astype(np.int32)
    warm_prompts = [rs.randint(1, VOCAB, (10,)).astype(np.int32),
                    np.concatenate([system, warm_tail]),
                    np.concatenate([system, warm_tail + 1])]
    router.warmup(warm_prompts, max_new_tokens=4)
    warm_compiles = [e.recompile_count for e in router.engines]
    router.start()
    deployer = RollingDeployer(router, registry, canary_windows=2)

    try:
        leg1(ff, router, registry, deployer, rs, system, warm_prompts,
             warm_compiles, n_target)
        leg2(ff, router, registry, deployer, rs, system, warm_prompts,
             flight)
        sanitize_check(router)
    finally:
        router.close()
        shutil.rmtree(work, ignore_errors=True)
    print("deploy_smoke: PASSED")


def leg1(ff, router, registry, deployer, rs, system, warm_prompts,
         warm_compiles, n_target):
    v1 = publish_bumped(ff, registry, step=1, scale=1.25)
    tree1 = ff.executor.reshard_params(registry.load_params(v1))

    base_done = sum(e.stats()["completed"] for e in router.engines)
    feeder = Feeder(router, rs, system)
    feeder.start()
    while len(feeder.reqs) < max(8, n_target // 10):  # flood is live
        time.sleep(0.01)

    t0 = time.perf_counter()
    report = deployer.deploy(v1, warmup_prompts=warm_prompts,
                             max_new_tokens=4)
    dt = time.perf_counter() - t0
    while len(feeder.reqs) < n_target:  # post-roll traffic too
        time.sleep(0.01)
    # each swapped engine's warmup drives 2 passes over the prompt set
    # (cold + hit variants) — those requests are engine-side, not router
    n = settle(router, feeder, base_done,
               warmups_since=2 * 2 * len(warm_prompts))

    assert report["state"] == "completed", report
    assert report["swapped"] == [0, 1] and report["canary"] == 0
    st = router.stats()
    assert st["fenced"] == 0, "a healthy roll must not fence anyone"
    assert feeder.max_suspended <= 1, (
        f"{feeder.max_suspended} replicas suspended at once — the fleet "
        f"dropped below N-1 capacity")
    assert [e.weight_version for e in router.engines] == [v1, v1]
    assert st["swaps_completed"] == 2 and st["rollbacks"] == 0
    assert not st["deploying"]
    assert [row["weight_version"] for row in st["per_replica"]] \
        == [v1, v1]
    assert router.health()["weight_versions"] == [v1, v1]
    for r, eng in enumerate(router.engines):
        assert eng._cache_ns(None) == (v1, None), \
            f"replica {r} trie not salted with {v1}"
        assert eng.recompile_count == warm_compiles[r], (
            f"replica {r} compiled "
            f"{eng.recompile_count - warm_compiles[r]} programs during "
            f"the roll — the swap must not retrace")
    # post-roll traffic serves the NEW weights, token-identically
    for probe in [np.concatenate(
            [system, rs.randint(1, VOCAB, (4,)).astype(np.int32)]),
            rs.randint(1, VOCAB, (9,)).astype(np.int32)]:
        got = router.run([probe], max_new_tokens=MAX_NEW,
                         timeout=600)[0]
        np.testing.assert_array_equal(
            np.asarray(got.tokens, np.int32), ref_tokens(ff, tree1, probe),
            err_msg="post-roll stream diverged from the v1 reference")
    print(f"deploy_smoke[roll]: {n} requests exactly-once through the "
          f"{dt:.1f}s roll to {v1} (canary replica "
          f"{report['canary']} held {deployer.canary_windows} windows), "
          f"0 recompiles, max {feeder.max_suspended} replica out")


def leg2(ff, router, registry, deployer, rs, system, warm_prompts,
         flight_dir):
    v1 = router.engines[0].weight_version
    v2 = publish_bumped(ff, registry, step=2, scale=1.5)
    tree1 = ff.executor.reshard_params(registry.load_params(v1))

    # arm the SLO plane: a tight TTFT ceiling over 1 s windows, bundles
    # into a fresh dir (debounce parked high so the ONLY bundle written
    # is the rollback's own synchronous dump — fault trips merge into it)
    flightrec.configure(FFConfig(
        batch_size=2, mesh_shape={"data": 1}, slo_ttft_p99_s=0.25,
        slo_window_s=1.0, flight_recorder_dir=flight_dir,
        flight_debounce_s=600.0))
    os.environ["FF_FAULT"] = "slow(600)@canary:1-400"
    faultinject.reset()

    base_done = sum(e.stats()["completed"] for e in router.engines)
    feeder = Feeder(router, rs, system)
    feeder.start()
    while len(feeder.reqs) < 8:
        time.sleep(0.01)
    try:
        report = deployer.deploy(v2, warmup_prompts=warm_prompts,
                                 max_new_tokens=4)
    finally:
        os.environ.pop("FF_FAULT", None)
        faultinject.reset()
    # only the canary's warmup ran engine-side (2 passes); the rollback
    # swap rebaselines without re-warming
    n = settle(router, feeder, base_done,
               warmups_since=2 * len(warm_prompts))

    assert report["state"] == "rolled_back", report
    assert report["breach"] is not None, \
        "rollback without a recorded canary breach"
    assert report["breach"]["slo"] == "ttft_p99", report["breach"]
    assert str(report["breach"]["replica"]) == str(report["canary"])
    assert report["rollback_s"] > 0
    assert [e.weight_version for e in router.engines] == [v1, v1], \
        "the fleet must end back on the prior version"
    st = router.stats()
    assert st["rollbacks"] == 1 and st["fenced"] == 0
    # exactly one manifest-intact bundle, naming the breached SLO
    bundles = [os.path.join(flight_dir, d)
               for d in os.listdir(flight_dir)]
    assert len(bundles) == 1, f"expected exactly 1 bundle: {bundles}"
    assert report["bundle"] == bundles[0]
    flightrec.verify_bundle(bundles[0])
    trigger = json.load(open(os.path.join(bundles[0], "trigger.json")))
    blob = json.dumps(trigger)
    assert "canary_rollback" in blob and "ttft_p99" in blob, \
        "the bundle's trigger must name the breached SLO"
    # rolled-back fleet serves the PRIOR weights, token-identically
    probe = np.concatenate(
        [system, rs.randint(1, VOCAB, (5,)).astype(np.int32)])
    got = router.run([probe], max_new_tokens=MAX_NEW, timeout=600)[0]
    np.testing.assert_array_equal(
        np.asarray(got.tokens, np.int32), ref_tokens(ff, tree1, probe),
        err_msg="post-rollback stream diverged from the v1 reference")
    print(f"deploy_smoke[rollback]: canary breached "
          f"{report['breach']['slo']} "
          f"({report['breach']['value']:.3f}s vs "
          f"{report['breach']['bound']:.3f}s), fleet back on {v1} in "
          f"{report['rollback_s']:.2f}s, {n} requests exactly-once, "
          f"bundle {os.path.basename(bundles[0])} intact")


def sanitize_check(router):
    if not os.environ.get("FF_SANITIZE"):
        return
    from flexflow_tpu.runtime import locks

    assert locks.mode() != "off", "FF_SANITIZE set but sanitizer off"
    assert locks.violations() == [], (
        "lock-order violations under FF_SANITIZE:\n"
        + "\n".join(f"{v['outer']} -> {v['inner']}\n{v['inner_stack']}"
                    for v in locks.violations()))
    # the injected canary stall is the ONLY tolerated warm-window delay;
    # it must never have manifested as a retrace
    assert locks.retrace_log() == [], (
        "post-warmup retraces under FF_SANITIZE:\n"
        + "\n".join(f"{r['program']} {r['signature']}\n{r['stack']}"
                    for r in locks.retrace_log()))
    retr = [e.stats()["sanitizer_retraces"] for e in router.engines]
    assert sum(retr) == 0, f"per-engine sentinel hits: {retr}"
    print(f"deploy_smoke[sanitize]: zero violations, zero retraces "
          f"across both legs")


if __name__ == "__main__":
    main()
