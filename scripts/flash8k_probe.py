"""Hardware proof for the round-3 streaming flash kernels (VERDICT r2 #2):
compile + run forward and backward at seq 8192 — 2x the old FLASH_MAX_SEQ
cap — on the real chip, and report ms/iter.

The kernels stream opposing-side K/V tiles through the innermost grid
axis with O(block^2) VMEM scratch (ops/pallas_kernels.py), so sequence
length no longer bounds VMEM; this script is the on-chip leg of the
interpret-mode grad-exactness tests in tests/test_longcontext_dense.py.
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from flexflow_tpu.ops.pallas_kernels import flash_attention  # noqa: E402

rs = np.random.RandomState(0)
b, s, h, d = 1, 8192, 4, 128
q = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
k = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)
v = jnp.asarray(rs.randn(b, s, h, d), jnp.bfloat16)

f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 0.088))
# scalar fetch = real sync: block_until_ready is advisory through the
# device tunnel (same finding as bench.py's timed loop); warmed OUTSIDE
# the timed window so its compile doesn't pollute the ms/iter
sync = jax.jit(lambda a: a.astype(jnp.float32).sum())
o = f(q, k, v)
float(sync(o))
t0 = time.perf_counter()
for _ in range(10):
    o = f(q, k, v)
float(sync(o))
print("seq8192 fwd ok", (time.perf_counter() - t0) / 10 * 1e3, "ms/iter")

g = jax.jit(jax.grad(
    lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, True, 0.088).astype(jnp.float32)),
    argnums=(0, 1, 2)))
gq, gk, gv = g(q, k, v)
float(sync(gq))  # warm the bwd program AND the sync fetch
t0 = time.perf_counter()
for _ in range(5):
    gq, gk, gv = g(q, k, v)
float(sync(gq))  # scalar fetch = real sync (see above)
print("seq8192 bwd ok", (time.perf_counter() - t0) / 5 * 1e3, "ms/iter")
