#!/bin/bash
# Tunnel watcher (round 3): the axon TPU tunnel flaps. This watcher
# probes with long patience and, the moment the tunnel answers, runs the
# remaining hardware-blocked work in strict priority order (one jax
# process at a time). Each step is independent; a tunnel drop mid-step
# only loses that step. Steps already completed in earlier TPU sessions
# (bench tiers, flash8k proof, MFU ablation+probe sweep) are not re-run.
#
# Detach with: nohup bash scripts/tpu_watcher.sh >/tmp/watcher.log 2>&1 &
OUT=/tmp/tpu_queue
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
STAMP() { date -u +"%H:%M:%S"; }

# hard deadline (epoch seconds): stop probing/starting steps after this,
# so a late tunnel return can't leave a long measure run holding the
# chip when the round-end driver bench needs it. Override: FF_WATCH_UNTIL.
UNTIL="${FF_WATCH_UNTIL:-$(date -u -d '14:00' +%s 2>/dev/null || echo 0)}"

while true; do
  if [ "$UNTIL" -gt 0 ] && [ "$(date +%s)" -ge "$UNTIL" ]; then
    echo "[$(STAMP)] deadline reached; exiting so the driver owns the chip"
    break
  fi
  echo "[$(STAMP)] probe"
  if timeout 200 python -c "import jax; assert jax.devices()[0].platform=='tpu'" \
      > /dev/null 2>&1; then
    echo "[$(STAMP)] TUNNEL UP - running work queue"
    # a step only starts with its own timeout of headroom to the deadline
    HEADROOM() { [ "$UNTIL" -le 0 ] \
        || [ $(( $(date +%s) + $1 )) -lt "$UNTIL" ]; }

    # 1. ResNet-50 measure tier (VERDICT #3 arbitration — the one
    #    remaining north-star gap)
    HEADROOM 2400 || { echo "[$(STAMP)] skip resnet (deadline)"; break; }
    echo "[$(STAMP)] step resnet"
    timeout 2400 python scripts/northstar_search.py --workload resnet50 \
        --costs measure --budget 40000 \
        > "$OUT/resnet_measure.json" 2> "$OUT/resnet_measure.err"
    rc=$?
    echo "[$(STAMP)] resnet rc=$rc: $(tail -c 300 "$OUT/resnet_measure.json")"

    # 2. KV-cache decode throughput (round-3 generation subsystem)
    HEADROOM 1200 || { echo "[$(STAMP)] skip decode (deadline)"; break; }
    echo "[$(STAMP)] step decode"
    timeout 1200 python scripts/decode_probe.py \
        > "$OUT/decode.json" 2> "$OUT/decode.err"
    rc=$?
    echo "[$(STAMP)] decode rc=$rc: $(cat "$OUT/decode.json")"

    # 2b. full staged bench: re-proves all tiers through the compile
    #     cache and measures the new xxl_scan (hidden 4096) tail tier
    HEADROOM 1560 || { echo "[$(STAMP)] skip bench (deadline)"; break; }
    echo "[$(STAMP)] step bench"
    FF_BENCH_BUDGET=1500 timeout 1560 python bench.py \
        > "$OUT/bench3.json" 2> "$OUT/bench3.err"
    rc=$?
    echo "[$(STAMP)] bench rc=$rc: $(tail -c 400 "$OUT/bench3.json")"

    # 3. whole-program strategy validation, chip leg (VERDICT #5)
    HEADROOM 900 || { echo "[$(STAMP)] skip validate (deadline)"; break; }
    echo "[$(STAMP)] step validate"
    timeout 900 python scripts/validate_strategies.py --budget 2000 --steps 10 \
        > "$OUT/validate.json" 2> "$OUT/validate.err"
    rc=$?
    echo "[$(STAMP)] validate rc=$rc"

    echo "[$(STAMP)] QUEUE COMPLETE"
    break
  fi
  echo "[$(STAMP)] tunnel down; sleeping 150s"
  sleep 150
done
