#!/bin/bash
# Tunnel watcher (round 3): the axon TPU tunnel flaps — it was up for a
# ~5-minute window (22:11-22:16 UTC) in which the first-ever TPU bench
# tiers landed, then dropped mid-compile. This watcher probes with long
# patience and, the moment the tunnel answers, runs the remaining
# hardware-blocked work in strict priority order (shortest/most valuable
# first, one jax process at a time). Each step is independent; a tunnel
# drop mid-step only loses that step.
#
# Detach with: nohup bash scripts/tpu_watcher.sh >/tmp/watcher.log 2>&1 &
OUT=/tmp/tpu_queue
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
STAMP() { date -u +"%H:%M:%S"; }

while true; do
  echo "[$(STAMP)] probe"
  if timeout 200 python -c "import jax; assert jax.devices()[0].platform=='tpu'" \
      > /dev/null 2>&1; then
    echo "[$(STAMP)] TUNNEL UP - running work queue"

    # 1. headline bench: a fresh full run (resume-across-children happens
    #    INSIDE one bench.py invocation; this rerun re-times tiny/mid too,
    #    cheaply via the persistent XLA cache — the driver's round-end run
    #    needs all tiers from one invocation anyway)
    echo "[$(STAMP)] step bench"
    FF_BENCH_BUDGET=1400 timeout 1460 python bench.py \
        > "$OUT/bench2.json" 2> "$OUT/bench2.err"
    rc=$?
    echo "[$(STAMP)] bench rc=$rc: $(cat "$OUT/bench2.json")"

    # 2. flash streaming kernels at 8k on hardware (VERDICT #2 proof)
    echo "[$(STAMP)] step flash8k"
    timeout 700 python scripts/flash8k_probe.py \
        > "$OUT/flash8k.log" 2>&1
    rc=$?
    echo "[$(STAMP)] flash8k rc=$rc: $(tail -2 "$OUT/flash8k.log")"

    # 3. MFU-lever ablation rows (VERDICT #4 table)
    echo "[$(STAMP)] step ablation"
    bash scripts/mfu_ablation.sh "$OUT/ablation" >> "$OUT/ablation.log" 2>&1
    echo "[$(STAMP)] ablation done"

    # 4. whole-program strategy validation on chip (VERDICT #5 chip leg)
    echo "[$(STAMP)] step validate"
    timeout 900 python scripts/validate_strategies.py --budget 2000 --steps 10 \
        > "$OUT/validate.json" 2> "$OUT/validate.err"
    rc=$?
    echo "[$(STAMP)] validate rc=$rc"

    # 5. ResNet-50 measure tier (VERDICT #3 arbitration; longest last)
    echo "[$(STAMP)] step resnet"
    timeout 1800 python scripts/northstar_search.py --workload resnet50 \
        --costs measure --budget 40000 \
        > "$OUT/resnet_measure.json" 2> "$OUT/resnet_measure.err"
    rc=$?
    echo "[$(STAMP)] resnet rc=$rc"

    echo "[$(STAMP)] QUEUE COMPLETE"
    break
  fi
  echo "[$(STAMP)] tunnel down; sleeping 150s"
  sleep 150
done
