#!/bin/bash
# Tunnel watcher (round 4): the axon TPU tunnel flaps, and — round-3
# postmortem — can be HALF-OPEN: jax.devices() answers but the remote
# compile service refuses connections, so a shallow probe green-lights a
# queue step that then burns its whole timeout compiling nothing. The
# round-4 probe therefore compiles AND runs a jitted op end to end.
#
# Steps are independent, retried on the next tunnel-up until their done
# marker exists (output file non-empty + rc recorded 0), and strictly
# serialized (one jax process at a time — a second wedges the tunnel).
# Priority order = VERDICT round-3 "next round" order: the driver-board
# bench (machine-written history) outranks everything.
#
# Detach with: nohup bash scripts/tpu_watcher.sh >/tmp/watcher.log 2>&1 &
OUT="${FF_WATCH_OUT:-/tmp/tpu_queue_r5}"
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
STAMP() { date -u +"%H:%M:%S"; }

# hard deadline (epoch secs): stop starting steps after this so a late
# tunnel return can't leave a long measure run holding the chip when the
# round-end driver bench needs it. Default 2026-08-01 15:30 UTC.
UNTIL="${FF_WATCH_UNTIL:-1785598200}"

HEADROOM() { [ "$UNTIL" -le 0 ] || [ $(( $(date +%s) + $1 )) -lt "$UNTIL" ]; }

# run_step <name> <timeout> <done-predicate> <cmd...>: skip if done-marker
# exists or no headroom; mark done only on rc=0 + non-empty output + the
# step's own success predicate (an eval'd shell expr — rc=0 alone is NOT
# proof of a TPU result: bench.py's CPU fallback and mfu_ablation.sh's
# quarantine path both exit 0 by design). PENDING counts steps still
# lacking a marker after this pass.
PENDING=0
DLSKIP=0
run_step() {
  local name=$1 tmo=$2 pred=$3; shift 3
  [ -f "$OUT/$name.done" ] && return 0
  if ! HEADROOM "$tmo"; then
    echo "[$(STAMP)] skip $name (deadline)"
    PENDING=$((PENDING + 1)); DLSKIP=$((DLSKIP + 1))
    return 1
  fi
  echo "[$(STAMP)] step $name"
  timeout "$tmo" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  local rc=$?
  echo "[$(STAMP)] $name rc=$rc: $(tail -c 300 "$OUT/$name.json")"
  if [ "$rc" -eq 0 ] && [ -s "$OUT/$name.json" ] && eval "$pred"; then
    touch "$OUT/$name.done"
  else
    echo "[$(STAMP)] $name NOT done (pred/rc failed); will retry next pass"
    PENDING=$((PENDING + 1))
  fi
  return 0
}

while true; do
  if [ "$UNTIL" -gt 0 ] && [ "$(date +%s)" -ge "$UNTIL" ]; then
    echo "[$(STAMP)] deadline reached; exiting so the driver owns the chip"
    break
  fi
  echo "[$(STAMP)] probe"
  # deep probe: backend init AND a remote compile+execute round trip —
  # catches the half-open state that wasted the round-3 resnet window
  if timeout 240 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu'
assert float(jax.jit(lambda x: x * 2 + 1)(jnp.float32(3))) == 7.0
" > /dev/null 2>&1; then
    echo "[$(STAMP)] TUNNEL UP (compile verified) - running work queue"
    PENDING=0
    DLSKIP=0

    # 1. driver-board bench: staged tiers, machine-written history rows
    #    (VERDICT #1). Done only when a real TPU tier reached the board —
    #    the CPU fallback also exits 0 and must be retried.
    FF_BENCH_BUDGET=1500 run_step bench 1560 \
        'grep -q "\"backend\": \"tpu\"" "$OUT/bench.json"' python bench.py

    # 2. ResNet-50 + InceptionV3 measure-tier arbitration (VERDICT #2).
    #    A half-open tunnel degrades measure->analytic fallback (skips
    #    logged with a transport error) — retry those; a single op that
    #    fails measurement for a NON-tunnel reason still counts as done.
    run_step resnet_measure 2400 \
        '! grep -qE "UNAVAILABLE|Connection (Failed|refused)" "$OUT/resnet_measure.err"' \
        python scripts/northstar_search.py \
        --workload resnet50 --costs measure --budget 40000
    run_step inception_measure 2400 \
        '! grep -qE "UNAVAILABLE|Connection (Failed|refused)" "$OUT/inception_measure.err"' \
        python scripts/northstar_search.py \
        --workload inception --costs measure --budget 40000

    # 3. whole-program strategy validation, chip leg (VERDICT #3) — a
    #    tunnel drop mid-queue silently lands it on CPU; that's not done.
    #    --single-chip: a 1-device attachment cannot build the 8-device
    #    candidate mesh (round-5 finding) — the chip leg is the sim/real
    #    calibration ladder; --steps 100 so the smallest config's signal
    #    resolves above the tunnel's per-call jitter
    run_step validate 1800 'grep -q "\"backend\": \"tpu\"" "$OUT/validate.json"' \
        python scripts/validate_strategies.py --single-chip --steps 100

    # 4. d=64 MFU levers on the full tier: fused optimizer update +
    #    fused-LN-at-wide-hidden arbitration (VERDICT #4). Done needs at
    #    least one non-quarantined (TPU) ablation row on disk.
    #    Done requires the specific row step 4b consumes (a tunnel drop
    #    mid-ablation quarantines individual rows; any-row-exists would
    #    mark done with the decisive row missing)
    run_step mfu_d64 1800 'test -f "$OUT"/mfu_d64/bf16_fused_opt.json' \
        bash scripts/mfu_ablation.sh "$OUT/mfu_d64"

    # 4b. if the fused-optimizer lever measured as a WIN vs the staged
    #     bench's bf16-master row, put driver-visible machine rows with
    #     the lever on the history (lever env rescopes lever tiers only)
    #     Gated on BOTH inputs being real TPU results (bench.done +
    #     mfu_d64.done); exit 2 = measured loss (record + stop), exit 1 =
    #     inputs unreadable (leave pending — retry next pass)
    if [ -f "$OUT/mfu_d64.done" ] && [ -f "$OUT/bench.done" ] \
        && [ ! -f "$OUT/fused_followup.done" ]; then
      python3 - "$OUT" <<'PYEOF'
import json, os, sys
out = sys.argv[1]
try:
    abl = json.load(open(os.path.join(out, "mfu_d64", "bf16_fused_opt.json")))
    board = json.loads(open(os.path.join(out, "bench.json")).read())
except Exception:
    sys.exit(1)
base = None
for t in board.get("all_tiers", []):
    if t.get("tier") == "full_scan_opt":
        base = t.get("mfu")
if base is None or abl.get("mfu") is None:
    sys.exit(1)
sys.exit(0 if abl["mfu"] > base else 2)
PYEOF
      gate=$?
      if [ "$gate" -eq 0 ]; then
        FF_BENCH_BUDGET=900 FF_BENCH_FUSED_OPT=1 \
        FF_BENCH_SKIP_TIERS=tiny,mid,full,full_scan \
        run_step fused_followup 960 \
            'grep -q "\"backend\": \"tpu\"" "$OUT/fused_followup.json"' \
            python bench.py
      elif [ "$gate" -eq 2 ]; then
        echo "[$(STAMP)] fused-opt measured as a loss on chip; no follow-up"
        touch "$OUT/fused_followup.done"
      else
        echo "[$(STAMP)] fused-opt gate inputs unreadable; will retry"
        PENDING=$((PENDING + 1))
      fi
    fi

    # 5. KV-cache decode throughput (carried from round 3)
    run_step decode 1200 'grep -q "\"backend\": \"tpu\"" "$OUT/decode.json"' \
        python scripts/decode_probe.py

    if [ "$PENDING" -eq 0 ]; then
      echo "[$(STAMP)] QUEUE COMPLETE"
      break
    fi
    if [ "$DLSKIP" -eq "$PENDING" ]; then
      # everything still pending lacks deadline headroom — stop probing
      # (each probe holds the tunnel) so the driver owns the chip
      echo "[$(STAMP)] all $PENDING pending steps deadline-bound; exiting"
      break
    fi
    echo "[$(STAMP)] queue pass done ($PENDING steps pending); re-probing"
  else
    echo "[$(STAMP)] tunnel down/half-open; sleeping 150s"
  fi
  sleep 150
done
