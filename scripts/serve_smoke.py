#!/usr/bin/env python
"""CI serving smoke (ci/run_ci.sh `serving` tier): 200 mixed-length
requests through the continuous-batching engine on CPU, with FF_FAULT
nan_loss injection poisoning one request mid-stream — the poisoned
request must retire as `failed` while every other request completes,
proving a bad request can never stall the batch. Also asserts the
recompile counter stays flat after bucket warmup, and ends through
ServingEngine.drain() — stop admitting, finish the in-flight slots, final
snapshot — instead of a hard stop (the graceful-shutdown half of elastic
recovery, docs/resilience.md).

Phase 2 drives the radix prefix cache under SKEWED traffic — 80% of the
requests share a 64-token system prompt (the millions-of-users shape from
ROADMAP item 1): prefix hits must fire for nearly all of them, the warm
window must stay at zero recompiles (cold prefill, hit prefill, draft-free
decode all warmed up front), and after drain() + flush_prefix_cache() the
pool must hold exactly kv_pages - 1 free pages — the page-leak check.

Phase `quant` (ci/run_ci.sh `quant` tier, run standalone as
``python scripts/serve_smoke.py [N] quant``): the SAME skewed
shared-prefix workload driven through a bf16-pool engine and an
int8-pool engine (per-page-per-head scales, in-kernel dequant,
weight-only int8) — the sharing machinery is dtype-blind, so the hit
count and the zero-recompile warm window must MATCH the bf16 run
exactly, and the quantized pool must report >= 1.8x the tokens-per-
pool-GB of the bf16 pool.

Usage: [FF_FAULT=nan_loss@serve:37] python scripts/serve_smoke.py [N] [quant]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 \
        and sys.argv[1].isdigit() else 200
    quant_only = "quant" in sys.argv[1:]
    vocab = 128
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=4,
                   kv_page_size=8)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=vocab)
    ff.compile(final_tensor=logits)

    if quant_only:
        quant_smoke(ff, np.random.RandomState(0), vocab, n_requests)
        print("serve_smoke: PASSED")
        return

    rs = np.random.RandomState(0)
    lens = [int(rs.randint(3, 25)) for _ in range(n_requests)]
    prompts = [rs.randint(1, vocab, (n,)).astype(np.int32) for n in lens]

    eng = ff.make_serving_engine(max_seq_len=64)
    # warmup via ServingEngine.warmup (one exemplar per bucket the
    # lengths can hit — 8, 16, 32; warmup's second pass covers the
    # repeat-hit variants). Warmup admissions CONSUME FF_FAULT serve
    # occurrences, so the fault index in ci/run_ci.sh must exceed
    # N_WARM — asserted below, loudly, instead of leaving the coupling
    # implicit
    warm_prompts = [rs.randint(1, vocab, (n,)).astype(np.int32)
                    for n in (8, 16, 24)]
    n_warm = eng.warmup(warm_prompts, max_new_tokens=4)["requests"]
    warm = eng.recompile_count

    t0 = time.perf_counter()
    # submit + drive by hand instead of run(): once the queue has fully
    # admitted, DRAIN the engine — the graceful-shutdown path (stop
    # admitting, finish the in-flight slots) is what a real deploy or
    # preemption uses instead of a hard stop, so the smoke proves it
    # end-to-end with real in-flight work
    # max_new_tokens spans >1 decode chunk (12 > decode_chunk=8) so slots
    # are guaranteed mid-flight when the queue empties — drain() below
    # finishes REAL in-flight work, not an already-idle engine
    reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
    while eng.health()["queued"]:
        eng.step()
    assert eng.health()["status"] == "busy"
    st = eng.drain()  # finishes the in-flight slots, final snapshot
    dt = time.perf_counter() - t0
    health = eng.health()
    assert health["status"] == "drained" and not health["admitting"], health
    assert st["drained"] and st["queued"] == 0, st
    try:
        eng.submit(prompts[0], max_new_tokens=1)
    except RuntimeError:
        pass
    else:
        raise AssertionError("draining engine must refuse new requests")

    fault = os.environ.get("FF_FAULT", "")
    failed = [r for r in reqs if r.state == "failed"]
    done = [r for r in reqs if r.state == "done"]
    print(f"serve_smoke: {len(done)} done, {len(failed)} failed of "
          f"{n_requests} in {dt:.1f}s "
          f"({st['tokens_generated'] / dt:.0f} tok/s incl. warmup tokens), "
          f"occupancy {st['occupancy']:.2f}, "
          f"recompiles after warmup {eng.recompile_count - warm}, "
          f"drained with {st['queued']} queued")

    assert len(done) + len(failed) == n_requests, "requests lost"
    assert eng.recompile_count == warm, (
        f"recompile leak: {eng.recompile_count - warm} programs built "
        f"after bucket warmup")
    if "nan_loss@serve" in fault:
        # the FF_FAULT occurrence index is 1-based over ADMITTED requests
        # (warmup included): occurrence k poisons measured request
        # k - n_warm - 1 (0-based). Guard the coupling explicitly.
        k = int(fault.split("nan_loss@serve:")[1].split(",")[0])
        assert n_warm < k <= n_warm + n_requests, (
            f"FF_FAULT serve occurrence {k} must land in the measured "
            f"batch ({n_warm} warmup admissions precede it)")
        assert len(failed) == 1, (
            f"expected exactly 1 poisoned failure under FF_FAULT={fault}, "
            f"got {len(failed)}")
        assert failed[0].error == "non-finite logits", failed[0].error
        assert failed[0].rid == k - 1, (
            f"poison landed on rid {failed[0].rid}, expected {k - 1}")
        print(f"serve_smoke: poisoned request rid={failed[0].rid} retired "
              f"as failed without stalling the batch")
    else:
        assert not failed, f"unexpected failures: {[r.rid for r in failed]}"

    prefix_smoke(ff, rs, vocab, n_requests)
    print("serve_smoke: PASSED")


def prefix_smoke(ff, rs, vocab, n_requests, kv_cache_dtype=None,
                 weight_dtype=None, tag=""):
    """Skewed shared-prefix workload: 80% of requests share a 64-token
    system prompt. Asserts prefix hits, warm-window recompile flatness,
    and zero page leaks after drain + flush. ``kv_cache_dtype`` /
    ``weight_dtype`` run the same workload on a quantized engine (the
    `quant` phase drives a bf16/int8 pair through here); returns the
    final stats snapshot so callers can compare pairs."""
    system = rs.randint(1, vocab, (64,)).astype(np.int32)
    n_skew = (n_requests * 8) // 10
    prompts = []
    for i in range(n_requests):
        if i % 5 < 4:  # interleave 80/20 so slots mix both shapes
            tail = rs.randint(1, vocab, (int(rs.randint(1, 8)),))
            prompts.append(np.concatenate([system, tail.astype(np.int32)]))
        else:
            n = int(rs.randint(3, 25))
            prompts.append(rs.randint(1, vocab, (n,)).astype(np.int32))

    # pinned buckets: background traffic -> 32, system-prompt traffic
    # (65..71 tokens) -> 96; 96 + max_new 8 fits max_seq_len 112
    eng = ff.make_serving_engine(max_seq_len=112, decode_buckets=[32, 96],
                                 kv_cache_dtype=kv_cache_dtype,
                                 weight_dtype=weight_dtype)
    # ServingEngine.warmup drives every program the workload can need:
    # cold prefill per bucket, the (bucket 96, 8 matched pages) hit
    # prefill (pass 1 publishes the system pages, the repeats hit), and
    # the decode scan — the measured window then compiles nothing.
    warm_tail = rs.randint(1, vocab, (3,)).astype(np.int32)
    eng.warmup([rs.randint(1, vocab, (10,)).astype(np.int32),
                np.concatenate([system, warm_tail]),
                np.concatenate([system, warm_tail + 1])],
               max_new_tokens=4)
    warm = eng.recompile_count
    assert eng.stats()["prefix_hits"] >= 1, "warmup hit prefill never ran"

    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    while eng.health()["queued"]:
        eng.step()
    st = eng.drain()
    dt = time.perf_counter() - t0

    done = [r for r in reqs if r.state == "done"]
    hits = st["prefix_hits"]
    label = f"prefix{('/' + tag) if tag else ''}"
    print(f"serve_smoke[{label}]: {len(done)}/{n_requests} done in "
          f"{dt:.1f}s ({st['tokens_generated'] / dt:.0f} tok/s), "
          f"prefix hits {hits}/{st['prefix_lookups']} "
          f"(saved {st['prefill_tokens_saved']} prefill tokens), "
          f"shared-peak cached {st['kv_pages_cached']} pages, "
          f"kv {st['kv_cache_dtype']} "
          f"({st['kv_bytes_per_token']} B/token), "
          f"recompiles after warmup {eng.recompile_count - warm}")
    assert len(done) == n_requests, "requests lost in the prefix phase"
    assert hits >= n_skew - 1, (
        f"only {hits} prefix hits; the {n_skew} shared-prefix requests "
        f"(minus the publisher, warmed) must all hit")
    assert eng.recompile_count == warm, (
        f"recompile leak in the prefix-cache warm window: "
        f"{eng.recompile_count - warm} programs built")
    # page-leak check: every page is free or cached; flushing the cache
    # returns the pool to exactly kv_pages - 1 free
    assert st["prefix_refs_live"] == 0, "trie refcount leak after drain"
    assert st["free_pages"] + st["kv_pages_cached"] == st["kv_pages"] - 1, (
        f"page leak: {st['free_pages']} free + {st['kv_pages_cached']} "
        f"cached != {st['kv_pages'] - 1}")
    eng.flush_prefix_cache()
    assert eng.stats()["free_pages"] == st["kv_pages"] - 1, "flush leaked"
    st["recompiles_after_warmup"] = eng.recompile_count - warm
    return st


def quant_smoke(ff, rs, vocab, n_requests):
    """The quantized-tier leg (ci/run_ci.sh `quant`): the SAME skewed
    shared-prefix workload on a bf16 pool and an int8 pool (+ int8
    weights). The sharing machinery is page-granular and dtype-blind,
    so the int8 run's hit count and warm-window recompile flatness must
    MATCH the bf16 run's exactly — and the quantized pool must report
    near-2x tokens-per-pool-GB (scales cost a sliver below 2.0)."""
    stats = {}
    for tag, kv, wd in (("bf16", "bf16", None), ("int8", "int8", "int8")):
        stats[tag] = prefix_smoke(ff, np.random.RandomState(1), vocab,
                                  n_requests, kv_cache_dtype=kv,
                                  weight_dtype=wd, tag=tag)
    b, q = stats["bf16"], stats["int8"]
    assert q["prefix_hits"] == b["prefix_hits"], (
        f"int8 hit count {q['prefix_hits']} != bf16 {b['prefix_hits']}: "
        f"quantization must not change the sharing machinery")
    assert q["prefix_lookups"] == b["prefix_lookups"]
    assert q["recompiles_after_warmup"] == 0 \
        and b["recompiles_after_warmup"] == 0, (
        f"warm-window recompiles: int8 {q['recompiles_after_warmup']}, "
        f"bf16 {b['recompiles_after_warmup']} (must both be 0)")
    ratio = q["tokens_per_pool_gb"] / b["tokens_per_pool_gb"]
    assert ratio >= 1.8, (
        f"int8 pool holds only {ratio:.3f}x the tokens/GB of bf16 "
        f"(expected ~2x minus the per-page scale sliver)")
    assert q["kv_cache_dtype"] == "int8" and q["weight_dtype"] == "int8"
    print(f"serve_smoke[quant]: int8 matches bf16 — hits "
          f"{q['prefix_hits']}=={b['prefix_hits']}, 0 warm recompiles "
          f"both, tokens/GB ratio {ratio:.3f}x")


if __name__ == "__main__":
    main()
