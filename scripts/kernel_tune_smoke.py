#!/usr/bin/env python
"""Tune-then-consume smoke for the kernel autotuner (ci/run_ci.sh
`kernels` tier).

Proves the full loop against a REAL table file on disk, across the same
module-reload boundary a fresh process would cross:

  1. sweep (block_q, block_k) for the flash forward at one shape through
     the dispatch-floor timing harness and persist the winner;
  2. drop the in-process cache (simulating a new session), re-read the
     table from disk, and assert the lookup serves the tuned blocks;
  3. run flash_attention with the table live — the consuming trace must
     resolve to the tuned pick (counted as a table HIT) and produce the
     same numbers as the static-pick baseline (block size is a schedule
     choice, not semantics).

Run under JAX_PLATFORMS=cpu the kernels execute in interpret mode: the
smoke exercises exactly the code path a TPU re-tune takes.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    tmp = tempfile.mkdtemp(prefix="ff_kernel_tune_smoke_")
    table = os.path.join(tmp, "kernel_tune.json")
    os.environ["FF_KERNEL_TUNE_TABLE"] = table

    import jax.numpy as jnp
    import numpy as np

    from flexflow_tpu.ops.pallas_kernels import (_resolve_blocks,
                                                 flash_attention_fwd_pallas)
    from flexflow_tpu.search import kernel_tune

    # 1. tune: a real measured sweep, persisted
    rec = kernel_tune.tune_flash_attention(
        256, head_dim=16, heads=2, batch=1,
        candidates=((64, 64), (128, 128), (256, 256)), iters=2,
        verbose=True)
    assert os.path.exists(table), "tuner did not write the table file"
    best = tuple(rec["blocks"])
    print(f"[smoke] tuned {best} (static {tuple(rec['static'])}, "
          f"changed={rec['changed']}) -> {table}")

    # 2. consume across a cache drop: a fresh read of the REAL file
    kernel_tune._TABLES.clear()
    kernel_tune.reset_stats()
    got = kernel_tune.lookup_blocks("flash_fwd", seq_q=256, seq_k=256,
                                    head_dim=16, dtype=jnp.float32,
                                    batch=1, heads=2, causal=True)
    assert got == best, f"disk round-trip served {got}, tuned {best}"
    assert _resolve_blocks("flash_fwd", 256, 256, 16, jnp.float32,
                           None, None, batch=1, heads=2,
                           causal=True) == best
    assert kernel_tune.stats()["hits"] >= 1, "lookup not counted as HIT"

    # 3. the consuming kernel: tuned pick == static pick numerically
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 256, 2, 16), jnp.float32)
    k = jnp.asarray(rs.randn(1, 256, 2, 16), jnp.float32)
    v = jnp.asarray(rs.randn(1, 256, 2, 16), jnp.float32)
    tuned, _ = flash_attention_fwd_pallas(q, k, v, True, 0.25,
                                          need_lse=False)
    static, _ = flash_attention_fwd_pallas(q, k, v, True, 0.25,
                                           block_q=256, block_k=256,
                                           need_lse=False)
    np.testing.assert_allclose(np.asarray(tuned), np.asarray(static),
                               rtol=2e-5, atol=2e-5)
    # dtype keying: the f32-tuned entry must MISS for a bf16 query
    assert kernel_tune.lookup_blocks(
        "flash_fwd", seq_q=256, seq_k=256, head_dim=16,
        dtype=jnp.bfloat16, batch=1, heads=2, causal=True) is None
    print("[smoke] kernel_tune tune->persist->consume: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
