#!/usr/bin/env python
"""Whole-program validation of search winners (SURVEY §7 hard-part 5 /
VERDICT r2 #5): after the MCMC, run the top candidate strategies AND pure
data parallelism as REAL short whole-program training runs on the attached
backend, and report simulated-vs-real rank agreement.

Design notes:
  * Whole-program only — the device tunnel's ~2.4 ms per-dispatch latency
    makes per-op timings meaningless (round-2 finding), but an N-step
    jitted training loop amortizes dispatch into one number.
  * The simulator side uses costs MEASURED on the same backend the real
    runs execute on (costs=measure), so both columns describe the same
    machine. On the 8-device virtual CPU mesh this validates the
    simulator's composition (do measured per-op costs + the comm model
    compose into correct whole-program rankings?); on a TPU slice it
    validates the production stack end to end.
  * Candidates: DP, the full-budget MCMC winner, and small-budget /
    different-seed runs (distinct local optima), deduplicated.

Usage:
  FLEXFLOW_FORCE_CPU_DEVICES=8 python scripts/validate_strategies.py \
      [--budget 4000] [--steps 10] [--seq 64] [--hidden 128] [--layers 2]

Single-chip leg (--single-chip): a 1-device attachment cannot run the
8-device candidate strategies for real, so ranking *strategies* is not
measurable there. What IS measurable — and is the half of the validation
the CPU mesh can never give — is calibration of the measured-cost
pipeline against the real machine: measure per-op costs on the chip,
compose them through the full simulator (same CostModel/csim path the
search uses), and compare the predicted whole-program step time against
a real jitted training run, across several model shapes. Reports
per-shape sim/real ratio and rank agreement (does the simulator order
model shapes by real cost?). Together the two legs cover SURVEY §7 hard
part 5: CPU mesh = multi-device ranking; chip = per-op measurement
fidelity on the machine that matters.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MESH = {"data": 4, "model": 2}


def build(args, strategies=None, mesh=None):
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.models.transformer import build_encoder_classifier

    batch = args.batch
    cfg = FFConfig(batch_size=batch, mesh_shape=dict(mesh or MESH), seed=5)
    if strategies:
        cfg.strategies.update(strategies)
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, batch, args.seq, args.hidden,
                                      args.layers, 4)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(batch * 2, args.seq, args.hidden)
                     .astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (batch * 2, 1)).astype(np.int32))
    return ff


def real_time_s(ff, steps: int, scan: bool = False) -> float:
    """Best-of-3 whole-program step time (fetch-synced, like bench.py).
    scan=True runs the steps as ONE lax.scan device program — the
    dispatch-free number, required on the tunneled chip where per-step
    host dispatch would otherwise dominate small models (the simulator
    prices compute, not this environment's transport latency)."""
    if scan:
        from flexflow_tpu.search.measure import _dispatch_floor

        losses, _ = ff.train_scanned(steps)  # compile + warmup
        float(losses[-1])
        floor = _dispatch_floor()  # sampled in the same drift window
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            losses, _ = ff.train_scanned(steps)
            float(losses[-1])
            best = min(best, (time.perf_counter() - t0 - floor) / steps)
        return max(best, 1e-9)
    ff._run_train_step(ff._stage_batch())  # compile + warmup
    ff._run_train_step(ff._stage_batch())
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
        float(loss)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def lint_strategy(ff, strategies, label: str,
                  mesh: dict = None) -> bool:
    """fflint gate (flexflow_tpu/analysis): statically validate a candidate
    before spending real device time on it — a broken candidate is named
    here in milliseconds instead of hanging a collective rendezvous.
    Returns False (candidate must be skipped) on error-severity findings."""
    from flexflow_tpu.analysis import analyze

    report = analyze(ff, strategies=strategies, mesh_shape=mesh or MESH)
    if report.errors():
        print(f"[validate] {label}: fflint REJECTED the candidate:")
        for v in report.errors():
            print(f"[validate]   {v}")
        return False
    if report.warnings():
        for v in report.warnings():
            print(f"[validate] {label}: {v}")
    print(f"[validate] {label}: fflint clean "
          f"({len(report.notes())} note(s))")
    return True


def kendall_tau(a, b) -> float:
    n = len(a)
    conc = disc = 0
    for i in range(n):
        for j in range(i + 1, n):
            s = (a[i] - a[j]) * (b[i] - b[j])
            conc += s > 0
            disc += s < 0
    denom = conc + disc
    return (conc - disc) / denom if denom else 1.0


# (batch, seq, hidden, layers) ladder for the single-chip calibration:
# distinct FLOP scales so rank agreement is meaningful, small enough that
# each compiles in seconds on the tunnel
CALIB_CONFIGS = [
    (16, 128, 256, 2),
    (16, 256, 512, 2),
    (16, 256, 512, 4),
    (8, 512, 1024, 4),
]
if os.environ.get("FF_VALIDATE_TINY"):  # CPU smoke of the script itself
    CALIB_CONFIGS = [(4, 16, 32, 1), (4, 32, 64, 1), (4, 32, 64, 2)]


def single_chip_calibration(args):
    import math

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import get_search_problem
    from flexflow_tpu.search.driver import data_parallel_strategy
    from flexflow_tpu.search.measure import measure_op_costs

    mesh = {"data": 1}
    rows = []
    for batch, seq, hidden, layers in CALIB_CONFIGS:
        c = argparse.Namespace(**{**vars(args), "batch": batch, "seq": seq,
                                  "hidden": hidden, "layers": layers})
        ff = build(c, mesh=mesh)
        if not rows:  # same default-DP table for every shape: lint once
            lint_strategy(ff, {}, "dp", mesh=mesh)
        print(f"[validate/chip] b{batch} s{seq} h{hidden} L{layers}: "
              f"measuring...", flush=True)
        measured = measure_op_costs(ff, mesh)
        cost = CostModel(ff, mesh, measured=measured)
        prob = get_search_problem(ff, cost, mesh)
        sim_s = prob.simulate(
            prob.choices_for(data_parallel_strategy(ff, mesh)))
        real_s = real_time_s(ff, args.steps, scan=True)
        rows.append({"batch": batch, "seq": seq, "hidden": hidden,
                     "layers": layers, "sim_ms": round(sim_s * 1e3, 3),
                     "real_ms": round(real_s * 1e3, 3),
                     "real_over_sim": round(real_s / max(sim_s, 1e-12), 3),
                     "_sim": sim_s, "_real": real_s})
        print(f"[validate/chip]   sim {rows[-1]['sim_ms']} ms, "
              f"real {rows[-1]['real_ms']} ms", flush=True)
    # stats from UNROUNDED values: 3-dp rounding can collapse a deep sim
    # undershoot to 0.0 — log(0) would discard the run, and zero-ties would
    # make kendall_tau report perfect agreement with no ordering information
    sims = [r.pop("_sim") for r in rows]
    reals = [r.pop("_real") for r in rows]
    ratios = [rl / max(s, 1e-12) for s, rl in zip(sims, reals)]
    result = {
        "mode": "single_chip_calibration",
        "rows": rows,
        "kendall_tau": round(kendall_tau(sims, reals), 3),
        # geometric stats: the simulator is a *ranker* (reference tolerance,
        # SURVEY §7 hard part 5) so spread matters more than absolute level
        "ratio_geomean": round(
            math.exp(sum(math.log(x) for x in ratios) / len(ratios)), 3),
        "ratio_spread": round(max(ratios) / min(ratios), 3),
        "backend": _backend(),
        "config": vars(args),
    }
    print(json.dumps(result), flush=True)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=4000)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--single-chip", action="store_true",
                    help="1-device calibration leg (see module docstring)")
    args = ap.parse_args()
    if args.single_chip:
        return single_chip_calibration(args)

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import get_search_problem, native_optimize
    from flexflow_tpu.search.driver import data_parallel_strategy
    from flexflow_tpu.search.measure import measure_op_costs

    ff = build(args)
    print("[validate] measuring op costs on the attached backend...",
          flush=True)
    measured = measure_op_costs(ff, MESH)
    cost = CostModel(ff, MESH, measured=measured)
    prob = get_search_problem(ff, cost, MESH)

    candidates = {"dp": data_parallel_strategy(ff, MESH)}
    for label, (budget, seed) in {
            "mcmc_full": (args.budget, 1),
            "mcmc_alt1": (max(args.budget // 20, 50), 2),
            "mcmc_alt2": (max(args.budget // 50, 20), 3)}.items():
        found = native_optimize(ff, cost, MESH, budget=budget, alpha=0.05,
                                seed=seed)
        candidates[label] = {n: pc.axis_map for n, pc in found.items()}

    # dedup identical strategies (alternates often converge)
    rows = []
    seen = {}
    for label, strat in candidates.items():
        key = tuple(prob.choices_for(strat).tolist())
        if key in seen:
            print(f"[validate] {label} duplicates {seen[key]}; skipped")
            continue
        seen[key] = label
        sim_s = prob.simulate(prob.choices_for(strat))
        pcs = {n: _to_pc(ff, n, am, MESH) for n, am in strat.items()}
        if not lint_strategy(ff, pcs, label):
            continue
        print(f"[validate] {label}: simulated {sim_s * 1e3:.3f} ms; "
              f"running {args.steps} real steps x3...", flush=True)
        ff_c = build(args, strategies=pcs)
        real_s = real_time_s(ff_c, args.steps)
        rows.append({"strategy": label, "sim_ms": round(sim_s * 1e3, 3),
                     "real_ms": round(real_s * 1e3, 3)})

    sims = [r["sim_ms"] for r in rows]
    reals = [r["real_ms"] for r in rows]
    tau = kendall_tau(sims, reals)
    sim_winner = rows[int(np.argmin(sims))]["strategy"]
    real_winner = rows[int(np.argmin(reals))]["strategy"]
    result = {
        "rows": rows,
        "kendall_tau": round(tau, 3),
        "sim_winner": sim_winner,
        "real_winner": real_winner,
        "winner_agrees": sim_winner == real_winner,
        "backend": _backend(),
        "config": vars(args),
    }
    print(json.dumps(result), flush=True)
    return 0


def _to_pc(ff, name, axis_map, mesh):
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    op = next(o for o in ff.ops if o.name == name)
    return ParallelConfig.from_axis_map(op.outputs[0].num_dims, mesh,
                                        axis_map)


def _backend():
    import jax

    return jax.default_backend()


if __name__ == "__main__":
    sys.exit(main())
