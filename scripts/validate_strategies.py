#!/usr/bin/env python
"""Whole-program validation of search winners (SURVEY §7 hard-part 5 /
VERDICT r2 #5): after the MCMC, run the top candidate strategies AND pure
data parallelism as REAL short whole-program training runs on the attached
backend, and report simulated-vs-real rank agreement.

Design notes:
  * Whole-program only — the device tunnel's ~2.4 ms per-dispatch latency
    makes per-op timings meaningless (round-2 finding), but an N-step
    jitted training loop amortizes dispatch into one number.
  * The simulator side uses costs MEASURED on the same backend the real
    runs execute on (costs=measure), so both columns describe the same
    machine. On the 8-device virtual CPU mesh this validates the
    simulator's composition (do measured per-op costs + the comm model
    compose into correct whole-program rankings?); on a TPU slice it
    validates the production stack end to end.
  * Candidates: DP, the full-budget MCMC winner, and small-budget /
    different-seed runs (distinct local optima), deduplicated.

Usage:
  FLEXFLOW_FORCE_CPU_DEVICES=8 python scripts/validate_strategies.py \
      [--budget 4000] [--steps 10] [--seq 64] [--hidden 128] [--layers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

MESH = {"data": 4, "model": 2}


def build(args, strategies=None):
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader)
    from flexflow_tpu.models.transformer import build_encoder_classifier

    batch = args.batch
    cfg = FFConfig(batch_size=batch, mesh_shape=dict(MESH), seed=5)
    if strategies:
        cfg.strategies.update(strategies)
    ff = FFModel(cfg)
    x, out = build_encoder_classifier(ff, batch, args.seq, args.hidden,
                                      args.layers, 4)
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], final_tensor=out)
    rs = np.random.RandomState(0)
    SingleDataLoader(ff, x, rs.randn(batch * 2, args.seq, args.hidden)
                     .astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (batch * 2, 1)).astype(np.int32))
    return ff


def real_time_s(ff, steps: int) -> float:
    """Best-of-3 whole-program step time (fetch-synced, like bench.py)."""
    ff._run_train_step(ff._stage_batch())  # compile + warmup
    ff._run_train_step(ff._stage_batch())
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss, _ = ff._run_train_step(ff._stage_batch())
        float(loss)
        best = min(best, (time.perf_counter() - t0) / steps)
    return best


def kendall_tau(a, b) -> float:
    n = len(a)
    conc = disc = 0
    for i in range(n):
        for j in range(i + 1, n):
            s = (a[i] - a[j]) * (b[i] - b[j])
            conc += s > 0
            disc += s < 0
    denom = conc + disc
    return (conc - disc) / denom if denom else 1.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=4000)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    args = ap.parse_args()

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import get_search_problem, native_optimize
    from flexflow_tpu.search.driver import data_parallel_strategy
    from flexflow_tpu.search.measure import measure_op_costs

    ff = build(args)
    print("[validate] measuring op costs on the attached backend...",
          flush=True)
    measured = measure_op_costs(ff, MESH)
    cost = CostModel(ff, MESH, measured=measured)
    prob = get_search_problem(ff, cost, MESH)

    candidates = {"dp": data_parallel_strategy(ff, MESH)}
    for label, (budget, seed) in {
            "mcmc_full": (args.budget, 1),
            "mcmc_alt1": (max(args.budget // 20, 50), 2),
            "mcmc_alt2": (max(args.budget // 50, 20), 3)}.items():
        found = native_optimize(ff, cost, MESH, budget=budget, alpha=0.05,
                                seed=seed)
        candidates[label] = {n: pc.axis_map for n, pc in found.items()}

    # dedup identical strategies (alternates often converge)
    rows = []
    seen = {}
    for label, strat in candidates.items():
        key = tuple(prob.choices_for(strat).tolist())
        if key in seen:
            print(f"[validate] {label} duplicates {seen[key]}; skipped")
            continue
        seen[key] = label
        sim_s = prob.simulate(prob.choices_for(strat))
        print(f"[validate] {label}: simulated {sim_s * 1e3:.3f} ms; "
              f"running {args.steps} real steps x3...", flush=True)
        ff_c = build(args, strategies={
            n: _to_pc(ff, n, am, MESH) for n, am in strat.items()})
        real_s = real_time_s(ff_c, args.steps)
        rows.append({"strategy": label, "sim_ms": round(sim_s * 1e3, 3),
                     "real_ms": round(real_s * 1e3, 3)})

    sims = [r["sim_ms"] for r in rows]
    reals = [r["real_ms"] for r in rows]
    tau = kendall_tau(sims, reals)
    sim_winner = rows[int(np.argmin(sims))]["strategy"]
    real_winner = rows[int(np.argmin(reals))]["strategy"]
    result = {
        "rows": rows,
        "kendall_tau": round(tau, 3),
        "sim_winner": sim_winner,
        "real_winner": real_winner,
        "winner_agrees": sim_winner == real_winner,
        "backend": _backend(),
        "config": vars(args),
    }
    print(json.dumps(result), flush=True)
    return 0


def _to_pc(ff, name, axis_map, mesh):
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    op = next(o for o in ff.ops if o.name == name)
    return ParallelConfig.from_axis_map(op.outputs[0].num_dims, mesh,
                                        axis_map)


def _backend():
    import jax

    return jax.default_backend()


if __name__ == "__main__":
    sys.exit(main())
