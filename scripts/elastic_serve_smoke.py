#!/usr/bin/env python
"""CI elastic-fleet smoke (ci/run_ci.sh `elastic_serve` tier): a
2-replica fleet flooded past its capacity, the SLO-driven autoscaler
growing it live, then a deadline-raced preemption drill. Proves the
ISSUE-20 acceptance end to end on CPU:

  leg 1 — breach-driven scale-out:
  * a closed-loop flood at ~2x fleet capacity breaches the
    ``queue_wait_p99`` SLO; the AutoscalePolicy holds through its
    hysteresis window, then grows the fleet to 3 via add_replica();
  * the newcomer is warmed BEFORE admission and takes real work;
    /healthz returns to ``ok`` within a bounded recovery window once
    the capacity step lands;
  * ZERO survivor recompiles: scale-out adds capacity, never a
    compile stall on the replicas already serving.

  leg 2 — preemption with exactly-once evacuation:
  * FF_FAULT ``preempt(800)@replica:<home>`` fells the shared-prefix
    home replica mid-flood: it races the 800 ms deadline to evacuate
    its queued + in-flight requests and hot prefix pages to survivors,
    then retires WITHOUT a fence;
  * every flood request completes EXACTLY ONCE (router ledger ==
    per-engine completions; zero losses burned — a later real failover
    would still fit the cap);
  * zero evacuated prefixes lost: round 2 of the shared prompt serves
    a WARM hit from a survivor;
  * exactly one manifest-intact flight-recorder bundle lands, its
    trigger naming the preemption.

Run under FF_SANITIZE=1 (the CI tier's second leg) to also assert zero
lock-order violations and zero post-warmup retraces.

Usage: python scripts/elastic_serve_smoke.py [N_min_leg2]
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402
from flexflow_tpu.runtime import faultinject, flightrec  # noqa: E402
from flexflow_tpu.runtime.autoscale import AutoscalePolicy  # noqa: E402

VOCAB = 128
MAX_NEW = 12
WINDOW_S = 0.5


def build_model(flight_dir):
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=2,
                   kv_page_size=8, slo_window_s=WINDOW_S,
                   slo_queue_wait_p99_s=0.02,
                   flight_recorder_dir=flight_dir,
                   flight_debounce_s=1.0)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=VOCAB)
    ff.compile(final_tensor=logits)
    return ff


class Feeder(threading.Thread):
    """Closed-loop skewed flood (80% share a 64-token system prompt).
    ``max_inflight`` is live-tunable: the flood runs at ~2x fleet
    capacity to force the breach, then recedes so the recovery window
    measures the capacity step, not an unbounded arrival rate."""

    def __init__(self, router, rs, system, max_inflight):
        super().__init__(daemon=True)
        self.router, self.rs, self.system = router, rs, system
        self.max_inflight = max_inflight
        self.reqs = []
        self._halt = threading.Event()

    def _prompt(self):
        if self.rs.randint(5) < 4:
            tail = self.rs.randint(
                1, VOCAB, (int(self.rs.randint(1, 8)),)).astype(np.int32)
            return np.concatenate([self.system, tail])
        return self.rs.randint(
            1, VOCAB, (int(self.rs.randint(3, 25)),)).astype(np.int32)

    def run(self):
        while not self._halt.is_set():
            if sum(1 for r in self.reqs
                   if not r.settled) >= self.max_inflight:
                time.sleep(0.004)
                continue
            self.reqs.append(self.router.submit(self._prompt(), MAX_NEW))

    def stop(self):
        self._halt.set()
        self.join(timeout=60)


def settle(router, feeder):
    feeder.stop()
    router.wait(feeder.reqs, timeout=1200)
    n = len(feeder.reqs)
    assert all(r.settled for r in feeder.reqs), "requests lost"
    assert [r.state for r in feeder.reqs] == ["done"] * n, \
        f"{sum(1 for r in feeder.reqs if r.state != 'done')} of {n} " \
        f"requests did not complete"
    return n


def leg1_scale_out(router, pol, rs, system):
    warm_compiles = [e.recompile_count for e in router.engines]
    feeder = Feeder(router, rs, system, max_inflight=32)
    feeder.start()
    while len(feeder.reqs) < 8:         # the flood is live
        time.sleep(0.01)

    # tick at the SLO window cadence: the breach must PERSIST across
    # pol.breach_windows evaluated windows before the fleet grows
    t0 = time.perf_counter()
    action = None
    while time.perf_counter() - t0 < 120:
        action = pol.tick()
        if action is not None:
            break
        time.sleep(WINDOW_S)
    breach_s = time.perf_counter() - t0
    assert action == "scale_out", (
        f"flood at 2x capacity never drove a scale-out "
        f"(policy state {pol.state()})")
    st = router.stats()
    assert st["alive"] == 3 and st["scale_outs"] == 1
    newcomer_warm = router.engines[2].stats()["completed"]
    assert newcomer_warm > 0, "the newcomer joined un-warmed"

    # recede to below the GROWN fleet's capacity: /healthz must return
    # to ok within a bounded recovery window
    feeder.max_inflight = 2
    t0 = time.perf_counter()
    status = None
    while time.perf_counter() - t0 < 120:
        status = flightrec.health_rollup()["status"]
        if status == "ok":
            break
        time.sleep(WINDOW_S)
    recover_s = time.perf_counter() - t0
    assert status == "ok", (
        f"/healthz stuck at {flightrec.health_rollup()!r} after the "
        f"capacity step")

    n = settle(router, feeder)
    assert all(r.attempts == 1 for r in feeder.reqs), \
        "no fault was armed that justifies a resubmission"
    for r in (0, 1):
        assert router.engines[r].recompile_count == warm_compiles[r], (
            f"survivor {r} compiled "
            f"{router.engines[r].recompile_count - warm_compiles[r]} "
            f"programs during scale-out")
    assert router.engines[2].stats()["completed"] > newcomer_warm, \
        "the scaled-out replica never took flood work"
    assert router.stats()["fenced"] == 0
    print(f"elastic_smoke[scale_out]: breach -> 3 replicas in "
          f"{breach_s:.1f}s, /healthz ok {recover_s:.1f}s after the "
          f"step, {n} requests exactly-once, 0 survivor recompiles")


def leg2_preempt(router, rs, system, n_target, flight_dir):
    # the preemption target is the shared prefix's affinity HOME — the
    # replica guaranteed to hold hot pages and live traffic
    probe = np.concatenate(
        [system, rs.randint(1, VOCAB, (4,)).astype(np.int32)])
    home = router.run([probe], max_new_tokens=4, timeout=600)[0].replica
    survivors = [r for r in range(3) if r != home]
    base = [e.stats()["completed"] for e in router.engines]

    feeder = Feeder(router, rs, system, max_inflight=10)
    feeder.start()
    while len(feeder.reqs) < max(8, n_target // 4):
        time.sleep(0.01)
    os.environ["FF_FAULT"] = f"preempt(800)@replica:{home}"
    faultinject.reset()
    try:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 60:
            if router.stats()["preempts"]:
                break
            time.sleep(0.02)
        while len(feeder.reqs) < n_target:   # post-preempt traffic too
            time.sleep(0.01)
        n = settle(router, feeder)
    finally:
        os.environ.pop("FF_FAULT", None)
        faultinject.reset()

    st = router.stats()
    assert st["preempts"] == 1, "the preemption drill never fired"
    assert st["fenced"] == 0, \
        "a clean preemption must not count as a replica loss"
    assert st["evac_deadline_misses"] == 0, \
        "an 800 ms deadline must cover this evacuation"
    assert st["per_replica"][home]["retired"]
    assert st["evacuated_slabs"] >= 1 and st["evacuation_bytes"] > 0, \
        "the home replica held hot prefixes: evacuation moved nothing"
    # exactly-once: router ledger == per-engine completions, and the
    # evacuation burned ZERO losses (the failover cap keeps headroom)
    done = [e.stats()["completed"] - b
            for e, b in zip(router.engines, base)]
    assert sum(done) == n, (
        f"duplicated or lost across preemption: {done} vs {n} flood")
    assert all(r.losses == 0 for r in feeder.reqs), \
        "evacuated requests must not burn the exactly-once loss cap"
    assert all(1 <= r.attempts <= 2 for r in feeder.reqs)
    assert all(r.replica != home
               for r in feeder.reqs if r.attempts == 2), \
        "an evacuated request settled on the retired replica"

    # round 2: zero evacuated prefixes lost — the shared prompt serves
    # WARM from a survivor
    hits0 = sum(router.engines[s].stats()["prefix_hits"]
                for s in survivors)
    got = router.run([probe], max_new_tokens=4, timeout=600)[0]
    assert got.state == "done" and got.replica in survivors
    hits1 = sum(router.engines[s].stats()["prefix_hits"]
                for s in survivors)
    assert hits1 > hits0, \
        "the evacuated shared prefix never served a warm survivor hit"

    # exactly one manifest-intact bundle, naming the preemption
    path = flightrec.recorder().flush()
    bundles = [os.path.join(flight_dir, d)
               for d in os.listdir(flight_dir)]
    assert len(bundles) == 1, f"expected exactly 1 bundle: {bundles}"
    assert path == bundles[0]
    flightrec.verify_bundle(bundles[0])
    trigger = json.load(open(os.path.join(bundles[0], "trigger.json")))
    blob = json.dumps(trigger)
    assert "preempt" in blob and f'"replica": {home}' in blob, \
        f"the bundle's trigger must name the preemption: {blob[:400]}"
    print(f"elastic_smoke[preempt]: replica {home} evacuated "
          f"{st['evacuated_requests']} requests + "
          f"{st['evacuated_pages']} pages "
          f"({st['evacuation_bytes']} B) inside the deadline, {n} "
          f"requests exactly-once, warm survivor hits, bundle "
          f"{os.path.basename(bundles[0])} intact")


def sanitize_check(router):
    if not os.environ.get("FF_SANITIZE"):
        return
    from flexflow_tpu.runtime import locks

    assert locks.mode() != "off", "FF_SANITIZE set but sanitizer off"
    assert locks.violations() == [], (
        "lock-order violations under FF_SANITIZE:\n"
        + "\n".join(f"{v['outer']} -> {v['inner']}\n{v['inner_stack']}"
                    for v in locks.violations()))
    assert locks.retrace_log() == [], (
        "post-warmup retraces under FF_SANITIZE:\n"
        + "\n".join(f"{r['program']} {r['signature']}\n{r['stack']}"
                    for r in locks.retrace_log()))
    retr = [e.stats()["sanitizer_retraces"] for e in router.engines]
    assert sum(retr) == 0, f"per-engine sentinel hits: {retr}"
    print("elastic_smoke[sanitize]: zero violations, zero retraces "
          "across both legs")


def main():
    n_target = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    work = tempfile.mkdtemp(prefix="ff_elastic_smoke_")
    flight = os.path.join(work, "flight")
    os.makedirs(flight)
    ff = build_model(flight)
    rs = np.random.RandomState(0)
    system = rs.randint(1, VOCAB, (64,)).astype(np.int32)  # 8 full pages

    router = ff.make_serving_router(
        replicas=2, max_seq_len=112, decode_buckets=[32, 96], start=False)
    warm_tail = rs.randint(1, VOCAB, (3,)).astype(np.int32)
    router.warmup([rs.randint(1, VOCAB, (10,)).astype(np.int32),
                   np.concatenate([system, warm_tail]),
                   np.concatenate([system, warm_tail + 1])],
                  max_new_tokens=4)
    router.start()
    pol = AutoscalePolicy(router, min_replicas=2, max_replicas=3,
                          breach_windows=2, idle_windows=10 ** 6,
                          cooldown_s=0.0, interval_s=WINDOW_S)
    try:
        leg1_scale_out(router, pol, rs, system)
        leg2_preempt(router, rs, system, n_target, flight)
        sanitize_check(router)
    finally:
        pol.close()
        router.close()
        shutil.rmtree(work, ignore_errors=True)
    print("elastic_serve_smoke: PASSED")


if __name__ == "__main__":
    main()
