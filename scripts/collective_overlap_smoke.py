#!/usr/bin/env python
"""CI collective-overlap smoke (ci/run_ci.sh `overlap` tier): the
in-graph grad-sync overlap + async checkpointing drill (ISSUE 10).

Local leg (default, single process on 2 virtual CPU devices):
  * overlap_grad_sync training is PINNED against the serial-epilogue
    path (documented tolerance — the reduce-scatter's ring ordering may
    differ from the all-reduce's by f32 ULPs) and the ZeRO-1 optimizer
    state is genuinely sharded over the data axis;
  * a supervised overlapped run with async_checkpointing is preempted
    mid-way, its ASYNC-WRITTEN checkpoint passes manifest verification,
    and the relaunch resumes BITWISE against an uninterrupted reference.

Two-process leg (`two_process` arg; ci gates it on gloo collectives):
  the same overlapped-sync training on a 2-controller 8-device gloo
  mesh — preempted via FF_FAULT=sigterm, relaunched collectively, and
  the resumed loss tail must equal the uninterrupted 2-process
  reference bitwise (multihost checkpoints stay synchronous-collective;
  the async knob degrades with a warning, which the leg asserts too).

Usage: python scripts/collective_overlap_smoke.py [two_process]
"""

import os
import re
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
WORKER = os.path.join(REPO, "tests", "overlap_sync_worker.py")


# --------------------------------------------------------------- local leg


def run_local_leg():
    from flexflow_tpu._env import force_cpu_devices

    force_cpu_devices(2)

    import numpy as np

    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer, SingleDataLoader,
                              TrainSupervisor)
    from flexflow_tpu.runtime.checkpoint import (latest_intact_step,
                                                 pending_saves,
                                                 verify_checkpoint)
    from flexflow_tpu.runtime.optimizer import Zero1Update

    def build(overlap, ckpt="", async_ck=False):
        cfg = FFConfig(batch_size=16, mesh_shape={"data": 2},
                       grad_accum_steps=2, overlap_grad_sync=overlap,
                       async_checkpointing=async_ck, checkpoint_dir=ckpt,
                       checkpoint_every=2, seed=5)
        ff = FFModel(cfg)
        x = ff.create_tensor([16, 32], name="x")
        t = ff.dense(x, 64, name="fc1")
        ff.dense(t, 8, name="out")
        ff.compile(SGDOptimizer(lr=0.1),
                   LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                   [MetricsType.METRICS_ACCURACY])
        rs = np.random.RandomState(0)
        SingleDataLoader(ff, x, rs.randn(64, 32).astype(np.float32))
        SingleDataLoader(ff, ff.label_tensor,
                         rs.randint(0, 8, (64, 1)).astype(np.int32))
        return ff

    # -- overlap numerics pinned vs the serial epilogue
    rs = np.random.RandomState(1)
    batch = {"x": rs.randn(16, 32).astype(np.float32),
             "label": rs.randint(0, 8, (16, 1)).astype(np.int32)}
    a, b = build(False), build(True)
    for op, ws in a.params.items():
        for w, v in ws.items():
            b.set_weights(op, w, np.asarray(v))
    assert isinstance(b.optimizer, Zero1Update), type(b.optimizer)
    for i in range(3):
        la, _ = a._run_train_step(batch)
        lb, _ = b._run_train_step(batch)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-5,
                                   err_msg=f"step {i}")
    assert int(np.asarray(b.opt_state["t"])) == 3
    print("collective_overlap_smoke[local]: overlap vs serial epilogue "
          "pinned over 3 steps (ZeRO-1 update active)")

    # -- async checkpoint: preempt, verify manifest, resume bitwise
    with tempfile.TemporaryDirectory() as d_ref, \
            tempfile.TemporaryDirectory() as d:
        ref = build(True, ckpt=d_ref, async_ck=True)
        sup_ref = TrainSupervisor(ref, d_ref)
        assert sup_ref.run(8) == "completed"
        ref_losses = [f"{l:.9f}" for l in sup_ref.losses]

        ff1 = build(True, ckpt=d, async_ck=True)
        sup1 = TrainSupervisor(ff1, d)
        sup1.resume()
        while ff1._step_count < 4:
            sup1.step()
            sup1.after_step()
        sup1.request_preempt()
        assert sup1.after_step()
        sup1.finalize()
        assert pending_saves(d) == 0, "finalize must quiesce the publisher"
        step = latest_intact_step(d)
        assert step == 4, step
        verify_checkpoint(d, step)  # manifest-verified async checkpoint

        ff2 = build(True, ckpt=d, async_ck=True)
        sup2 = TrainSupervisor(ff2, d)
        assert sup2.run(8) == "completed"
        got = [f"{l:.9f}" for l in sup2.losses]
        assert got == ref_losses[4:], (got, ref_losses[4:])
    print("collective_overlap_smoke[local]: async-written checkpoint "
          "manifest-verified; resume BITWISE vs uninterrupted run")


# --------------------------------------------------------- two-process leg


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device counts
    env.pop("FF_FAULT", None)
    env["JAX_PLATFORMS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra)
    return env


def _parse_marker(out: str) -> dict:
    m = re.search(r"OVERLAPSYNC pid=(\d+) status=(\w+) resumed=(\w+) "
                  r"step=(\d+) procs=(\d+) zero1=(\d) losses=(\S*)", out)
    assert m, f"no OVERLAPSYNC marker in output:\n{out[-4000:]}"
    return {"pid": int(m.group(1)), "status": m.group(2),
            "resumed": m.group(3), "step": int(m.group(4)),
            "procs": int(m.group(5)), "zero1": int(m.group(6)),
            "losses": m.group(7).split(",") if m.group(7) else []}


def _spawn_pair(ckpt, total, fault=None):
    port = _free_port()
    procs = []
    for pid in range(2):
        extra = {"FF_FAULT": fault} if fault else {}
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flexflow_tpu.launcher", WORKER,
             "--num-processes", "2", "--process-id", str(pid),
             "--coordinator", f"127.0.0.1:{port}",
             "--cpu-devices", "4", "--", ckpt, str(total)],
            env=_worker_env(**extra), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=400)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    return [_parse_marker(o) for o in outs], outs


def run_two_process_leg():
    total = 8
    # reference: uninterrupted 2-process overlapped-sync run
    ref_dir = tempfile.mkdtemp(prefix="ff_ovl_ref_")
    mks, _ = _spawn_pair(ref_dir, total)
    for mk in mks:
        assert mk["status"] == "completed" and mk["procs"] == 2, mk
        assert mk["zero1"] == 1, "ZeRO-1 update must engage on data=8"
    ref_losses = mks[0]["losses"]
    assert len(ref_losses) == total, ref_losses
    print("collective_overlap_smoke[2proc]: reference run complete "
          f"({total} steps on the 2-controller data=8 mesh)")

    # phase 1: preempted at step 4 — collective checkpoint at the boundary
    ckpt = tempfile.mkdtemp(prefix="ff_ovl_2p_")
    mks, outs = _spawn_pair(ckpt, total, fault="sigterm@step:4")
    for mk in mks:
        assert mk["status"] == "preempted" and mk["step"] == 4, mk
    assert any("single-controller only" in o for o in outs), \
        "multihost async fallback warning expected"
    print("collective_overlap_smoke[2proc]: preempted at step 4 "
          "(async knob degraded to collective sync save, as documented)")

    # phase 2: relaunch both controllers; resume must be bitwise
    mks, _ = _spawn_pair(ckpt, total)
    for mk in mks:
        assert mk["status"] == "completed" and mk["resumed"] == "4", mk
        assert mk["losses"] == ref_losses[4:], (mk["losses"],
                                                ref_losses[4:])
    print("collective_overlap_smoke[2proc]: resumed BITWISE from the "
          "overlapped-sync checkpoint — loss tail identical to the "
          "uninterrupted run")


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "two_process":
        run_two_process_leg()
    else:
        run_local_leg()
    print("collective_overlap_smoke: PASSED")


if __name__ == "__main__":
    main()
