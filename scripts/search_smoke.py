#!/usr/bin/env python
"""Search v2 smoke (ci/run_ci.sh `search` tier): persistent op-cost DB,
warm-started search, multi-objective HBM cap, calibration gauges.

Proves the ISSUE 19 loop against a REAL DB file on disk, across the same
cache-drop boundary a fresh process would cross:

  1. COLD: a search with analyzed cost tables persists one entry per op
     signature to the cost DB;
  2. WARM: drop every in-process cache (simulating a new session), re-run
     the same search — it must re-measure ZERO already-keyed ops
     (misses == 0, hits > 0) and land within the cold search's cost;
  3. DRILL: under a tight per-chip HBM cap the multi-objective search
     chooses remat/ZeRO/offload relief and its strategy lints UNDER cap,
     where the time-only objective lints over (escalated to error);
  4. CALIBRATION: predicted-vs-observed gauges (ff_csim_error_ratio et
     al.) appear in a telemetry scrape and a calib entry lands in the DB.
"""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MESH = {"data": 2, "model": 2}


def build_model():
    from flexflow_tpu import ActiMode, FFConfig, FFModel

    cfg = FFConfig(batch_size=16, mesh_shape=MESH)
    ff = FFModel(cfg)
    x = ff.create_tensor([16, 32], name="x")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name="fc2")
    ff.dense(t, 8, name="out")
    return ff


def fresh_process_sim():
    from flexflow_tpu.search import cost_db, measure, table_store

    measure._SIGNATURE_CACHE.clear()
    table_store.clear_cache()
    cost_db.reset_stats()


def main():
    tmp = tempfile.mkdtemp(prefix="ff_search_smoke_")
    db = os.path.join(tmp, "cost_db.json")

    from flexflow_tpu.analysis import analyze
    from flexflow_tpu.runtime import telemetry
    from flexflow_tpu.search import cost_db, measure
    from flexflow_tpu.search.cost_model import MEM_MODES, CostModel
    from flexflow_tpu.search.driver import (optimize_strategies,
                                            optimize_strategies_multi)
    from flexflow_tpu.search.machine import MachineModel

    ff = build_model()

    # 1. COLD: analyze + search, entries persisted
    t0 = time.perf_counter()
    measured = measure.analyze_op_costs(ff, MESH, db_path=db)
    cold = optimize_strategies(ff, budget=80, mesh_shape=MESH, seed=3,
                               measured=measured, use_native=False)
    cold_wall = time.perf_counter() - t0
    n = cost_db.entry_count(db)
    assert os.path.exists(db), "cost DB file not written"
    assert n > 0, "cold search persisted no entries"
    s = cost_db.stats()
    assert s["stores"] == n, (s, n)
    print(f"[smoke] cold: {n} entries persisted, "
          f"{len(measured)} table rows, {cold_wall * 1e3:.1f} ms")

    # 2. WARM: fresh-process sim — zero re-measures, within cold cost
    fresh_process_sim()
    t0 = time.perf_counter()
    measured_w = measure.analyze_op_costs(ff, MESH, db_path=db)
    warm = optimize_strategies(ff, budget=80, mesh_shape=MESH, seed=3,
                               measured=measured_w, use_native=False)
    warm_wall = time.perf_counter() - t0
    s = cost_db.stats()
    assert s["misses"] == 0, f"warm search re-measured: {s}"
    assert s["hits"] > 0, s
    hit_rate = s["hits"] / max(s["hits"] + s["misses"], 1)
    cost = CostModel(ff, MESH, measured=measured_w)
    t_cold = cost.iteration_time({k: pc.axis_map for k, pc in cold.items()})
    t_warm = cost.iteration_time({k: pc.axis_map for k, pc in warm.items()})
    assert t_warm <= t_cold * 1.0001, (t_warm, t_cold)
    print(f"[smoke] warm: 0 re-measures ({s['hits']} hits, hit rate "
          f"{hit_rate:.0%}), {warm_wall * 1e3:.1f} ms wall, cost "
          f"{t_warm * 1e3:.4f} ms <= cold {t_cold * 1e3:.4f} ms")

    # 3. DRILL: tight HBM cap — multi-objective goes under, time-only not
    ops = {op.name: op for op in ff.ops if op.name in cold}
    base_cost = CostModel(ff, MESH)
    peak = sum(base_cost.op_mem_bytes(ops[k], cold[k].axis_map or {})
               for k in ops)
    floor = sum(min(base_cost.op_mem_bytes(ops[k], cold[k].axis_map or {},
                                           mem_mode=mm) for mm in MEM_MODES)
                for k in ops)
    cap = (peak + floor) / 2.0
    tiny = MachineModel(hbm_bytes=cap)
    rep = analyze(ff, strategies=cold, mesh_shape=MESH, machine=tiny,
                  passes=("legality", "perf"))
    over = rep.by_code("hbm-over-capacity")
    assert over and over[0].severity == "error", \
        "time-only strategy must lint over-cap (escalated: relief existed)"
    multi = optimize_strategies_multi(ff, budget=80, mesh_shape=MESH,
                                      seed=3, hbm_cap_bytes=cap,
                                      use_native=False)
    chosen = {k: pc.mem_mode for k, pc in multi.items()
              if pc.mem_mode != "none"}
    assert chosen, "tight cap chose no relief modes"
    assert ff._search_summary["over_cap"] is False
    rep2 = analyze(ff, strategies=multi, mesh_shape=MESH, machine=tiny,
                   passes=("legality", "perf"))
    assert not rep2.by_code("hbm-over-capacity"), \
        "multi-objective strategy still lints over-cap"
    print(f"[smoke] drill: cap {cap / 1e3:.1f} KB -> relief {chosen}, "
          f"peak {ff._search_summary['peak_hbm_bytes'] / 1e3:.1f} KB "
          f"under cap (time-only: over-cap error)")

    # 4. CALIBRATION: gauges in a scrape + calib entry in the DB
    telemetry.reset()
    hist = telemetry.registry().histogram(
        "ff_train_step_seconds", "fit() per-step wall time")
    for _ in range(8):
        hist.observe(0.010)
    rec = cost_db.export_calibration(ff, path=db)
    assert rec is not None and rec["source"] == "telemetry"
    scrape = telemetry.registry().to_prometheus()
    for gauge in ("ff_csim_predicted_step_seconds",
                  "ff_csim_observed_step_seconds", "ff_csim_error_ratio"):
        assert gauge in scrape, f"{gauge} missing from scrape"
    from flexflow_tpu.search import table_store

    assert any(k.startswith("calib|")
               for k in table_store.load(db, reload=True))
    print(f"[smoke] calibration: ratio {rec['ratio']:.2f}x, ff_csim_* "
          f"gauges scraped, calib entry persisted")
    telemetry.reset()

    print("[smoke] search v2 cold->warm->drill->calibration: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
