#!/usr/bin/env python
"""CI overlap smoke (ci/run_ci.sh `overlap` tier): the host-overlap step
engine (runtime/pipeline_loader.py prefetch + dispatch-ahead fit loop)
vs the synchronous loop under a deliberately slow host loader. Asserts
the two properties the engine exists for:

  * throughput improves (the loader sleep overlaps device compute), and
  * the measured host_wait fraction drops (the hot loop stops waiting
    on input).

The ratio bar here is deliberately looser than the bench tier's 1.3x
acceptance line — CI boxes are small and noisy; the bench row is where
the headline number is recorded.

Usage: python scripts/overlap_smoke.py [loader_delay_ms]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import (ActiMode, FFConfig, FFModel, LossType,  # noqa: E402
                          MetricsType, SGDOptimizer, SingleDataLoader)


class SlowLoader(SingleDataLoader):
    delay_s = 0.0

    def next_batch(self):
        time.sleep(SlowLoader.delay_s)
        return super().next_batch()


def main():
    delay_ms = float(sys.argv[1]) if len(sys.argv) > 1 else 40.0
    batch, n_batches, epochs = 32, 8, 2
    cfg = FFConfig(batch_size=batch, mesh_shape={"data": 1},
                   device_resident_data=False, native_dataloader=False,
                   prefetch_depth=0, dispatch_ahead=4)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 256], name="x")
    t = ff.dense(x, 2048, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 2048, ActiMode.AC_MODE_RELU)
    ff.dense(t, 16, name="out")
    ff.compile(SGDOptimizer(lr=0.01),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    n = batch * n_batches
    SlowLoader(ff, x, rs.randn(n, 256).astype(np.float32))
    SingleDataLoader(ff, ff.label_tensor,
                     rs.randint(0, 16, (n, 1)).astype(np.int32))
    ff.fit(epochs=1, verbose=False)  # compile + warm (fast loader)
    SlowLoader.delay_s = delay_ms / 1e3

    def timed(prefetch_depth):
        ff.config.prefetch_depth = prefetch_depth
        best, hw = None, None
        for _ in range(3):
            t0 = time.perf_counter()
            ff.fit(epochs=epochs, verbose=False)
            dt = time.perf_counter() - t0
            if best is None or dt < best:
                best = dt
                hw = ff.last_step_breakdown["host_wait_fraction"]
        return batch * n_batches * epochs / best, hw

    sync_sps, hw_sync = timed(0)
    overlap_sps, hw_overlap = timed(3)
    ratio = overlap_sps / sync_sps
    print(f"[overlap_smoke] loader_delay={delay_ms:.0f}ms  "
          f"sync={sync_sps:.0f} samples/s (host_wait {hw_sync:.0%})  "
          f"overlap={overlap_sps:.0f} samples/s (host_wait "
          f"{hw_overlap:.0%})  speedup={ratio:.2f}x")
    assert ratio > 1.1, \
        f"overlap engine did not beat the sync loop: {ratio:.3f}x"
    assert hw_overlap < hw_sync, \
        f"host_wait fraction did not drop: {hw_sync:.3f} -> {hw_overlap:.3f}"
    print("[overlap_smoke] PASSED")


if __name__ == "__main__":
    main()
