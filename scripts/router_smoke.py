#!/usr/bin/env python
"""CI fleet-router smoke (ci/run_ci.sh `router` tier): 2 ServingEngine
replicas behind a ServingRouter, 200 requests with skewed shared
prefixes (80% share a 64-token system prompt), and FF_FAULT
``crash(<tick>)@replica:0`` felling replica 0 mid-flight. Proves the
ISSUE-8 acceptance end to end on CPU:

  * every non-expired request completes EXACTLY ONCE — none lost, none
    duplicated (router ledger == sum of per-engine completions), each
    resubmitted at most once;
  * greedy outputs stay token-identical to a solo run through the
    failover (every resubmitted request is checked, plus a sample);
  * ZERO warm recompiles on the survivor: failover traffic lands only on
    programs its warmup already built;
  * requests that expire while queued retire as "timeout" with zero
    dispatch (attempts == 0);
  * a bounded router queue (serve_max_queue) rejects excess load fast
    while accepted work completes untouched.

Usage: [FF_FAULT=crash(10)@replica:0] python scripts/router_smoke.py [N]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu._env import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models.llama import llama_lm  # noqa: E402


def build_model():
    vocab = 128
    cfg = FFConfig(batch_size=2, mesh_shape={"data": 1}, serve_slots=4,
                   kv_page_size=8)
    ff = FFModel(cfg)
    _, logits = llama_lm(ff, 2, seq_len=16, hidden=64, layers=1, heads=4,
                         kv_heads=2, vocab_size=vocab)
    ff.compile(final_tensor=logits)
    return ff, vocab


def skewed_prompts(rs, vocab, n, system):
    """80% share the system prompt (interleaved so slots mix shapes)."""
    prompts = []
    for i in range(n):
        if i % 5 < 4:
            tail = rs.randint(1, vocab, (int(rs.randint(1, 8)),))
            prompts.append(np.concatenate([system, tail.astype(np.int32)]))
        else:
            prompts.append(rs.randint(
                1, vocab, (int(rs.randint(3, 25)),)).astype(np.int32))
    return prompts


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    fault = os.environ.get("FF_FAULT", "")
    ff, vocab = build_model()
    rs = np.random.RandomState(0)
    system = rs.randint(1, vocab, (64,)).astype(np.int32)  # 8 full pages
    prompts = skewed_prompts(rs, vocab, n_requests, system)

    # pinned buckets: background -> 32, system-prompt traffic (65..71
    # tokens) -> 96; 96 + max_new 12 fits max_seq_len 112
    router = ff.make_serving_router(
        replicas=2, max_seq_len=112, decode_buckets=[32, 96], start=False)
    # warm EVERY replica over every program the workload (and its
    # failover resubmissions) can reach: cold prefill per bucket, the
    # (96, 8-matched-pages) hit prefill (the first system prompt
    # publishes, the second hits), and the decode scan. crash@replica is
    # identity-indexed, so warmup consumes nothing from the fault plan.
    warm_tail = rs.randint(1, vocab, (3,)).astype(np.int32)
    router.warmup([rs.randint(1, vocab, (10,)).astype(np.int32),
                   np.concatenate([system, warm_tail]),
                   np.concatenate([system, warm_tail + 1])],
                  max_new_tokens=4)
    for r, eng in enumerate(router.engines):
        assert eng.stats()["prefix_hits"] >= 1, \
            f"replica {r} warmup never ran the hit prefill"
    warm_compiles = [e.recompile_count for e in router.engines]
    warm_done = [e.stats()["completed"] for e in router.engines]

    t0 = time.perf_counter()
    reqs = router.run(prompts, max_new_tokens=12, timeout=1200)
    dt = time.perf_counter() - t0
    st = router.stats()

    done = [r for r in reqs if r.state == "done"]
    resubmitted = [r for r in reqs if r.attempts == 2]
    print(f"router_smoke: {len(done)}/{n_requests} done in {dt:.1f}s, "
          f"fenced {st['fenced']}, resubmitted {st['resubmitted']}, "
          f"survivor prefix hits "
          f"{router.engines[1].stats()['prefix_hits']}")

    # exactly once, nothing lost, nothing duplicated
    assert all(r.settled for r in reqs), "requests lost"
    assert len(done) == n_requests, \
        f"{n_requests - len(done)} requests did not complete"
    assert st["completed"] == n_requests
    engine_done = sum(e.stats()["completed"] - w
                      for e, w in zip(router.engines, warm_done))
    assert engine_done == n_requests, (
        f"engines completed {engine_done} != {n_requests}: a request ran "
        f"to completion twice (duplicated) or vanished (lost)")
    assert all(1 <= r.attempts <= 2 for r in reqs), \
        "a request was resubmitted more than once"

    if "crash" in fault and "@replica:0" in fault:
        assert st["fenced"] == 1, f"crash fault armed but fenced == " \
            f"{st['fenced']}"
        assert st["resubmitted"] >= 1 and resubmitted, \
            "the crash was supposed to catch work in flight"
        # the drill's trace annotation marks exactly where the fault
        # landed (runtime/telemetry.py; faultinject reports every fire)
        from flexflow_tpu.runtime import telemetry

        assert any(e["args"]["kind"] == "crash"
                   and e["args"]["site"] == "replica"
                   and e["args"]["index"] == 0
                   for e in telemetry.fault_events()), \
            "crash fired but left no fault annotation in the trace ring"
        # the survivor saw failover traffic yet compiled NOTHING new
        assert router.engines[1].recompile_count == warm_compiles[1], (
            f"survivor recompile leak: "
            f"{router.engines[1].recompile_count - warm_compiles[1]} "
            f"programs built after warmup")
        print(f"router_smoke: replica 0 crashed mid-flight "
              f"({st['per_replica'][0]['fence_reason']}); "
              f"{len(resubmitted)} requests failed over, survivor built "
              f"0 new programs")
    else:
        assert st["fenced"] == 0 and not resubmitted
        for r, eng in enumerate(router.engines):
            assert eng.recompile_count == warm_compiles[r], \
                f"replica {r} recompile leak without any fault"

    # token identity through the failover: every resubmitted request +
    # a sample of the rest against solo generate
    for r in resubmitted + done[:: max(1, len(done) // 8)]:
        solo = ff.generate(r.prompt[None, :], max_new_tokens=12)
        np.testing.assert_array_equal(
            np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:],
            err_msg=f"request {r.rid} (attempts {r.attempts}) diverged "
                    f"from its solo run")
    print(f"router_smoke: token identity held for {len(resubmitted)} "
          f"failed-over + sampled requests")

    deadline_leg(router, rs, vocab, system)
    shedding_leg(ff, rs, vocab)

    if os.environ.get("FF_SANITIZE"):
        # CI sanitize tier: the whole run above executed under the
        # order-asserting lock proxies and the armed retrace sentinel —
        # any inversion or warm-program retrace is a hard failure here
        from flexflow_tpu.runtime import locks

        assert locks.mode() != "off", "FF_SANITIZE set but sanitizer off"
        assert locks.violations() == [], (
            "lock-order violations under FF_SANITIZE:\n"
            + "\n".join(f"{v['outer']} -> {v['inner']}\n{v['inner_stack']}"
                        for v in locks.violations()))
        assert locks.retrace_log() == [], (
            "post-warmup retraces under FF_SANITIZE:\n"
            + "\n".join(f"{r['program']} {r['signature']}\n{r['stack']}"
                        for r in locks.retrace_log()))
        retr = [e.stats()["sanitizer_retraces"] for e in router.engines]
        assert sum(retr) == 0, f"per-engine sentinel hits: {retr}"
        snap = locks.lock_graph_snapshot()
        print(f"router_smoke[sanitize]: mode={snap['mode']}, "
              f"{len(snap['tracked_locks'])} tracked locks, "
              f"zero violations, zero retraces")

    print("router_smoke: PASSED")


def deadline_leg(router, rs, vocab, system):
    """Expired-while-queued requests retire as timeout with ZERO
    dispatch; unexpired siblings complete normally on the survivors."""
    st0 = router.stats()
    expired = [router.submit(
        np.concatenate([system, rs.randint(1, vocab, (2,)).astype(np.int32)]),
        8, deadline_s=0.0) for _ in range(10)]
    live = [router.submit(
        np.concatenate([system, rs.randint(1, vocab, (3,)).astype(np.int32)]),
        8, deadline_s=60.0) for _ in range(10)]
    router.wait(expired + live, timeout=600)
    assert [r.state for r in expired] == ["timeout"] * 10
    assert all(r.attempts == 0 for r in expired), \
        "an expired-in-queue request was dispatched"
    assert [r.state for r in live] == ["done"] * 10
    st = router.stats()
    assert st["timeouts"] - st0["timeouts"] == 10
    assert st["dispatched"] - st0["dispatched"] == 10, \
        "only the live requests may dispatch"
    print(f"router_smoke[deadline]: 10 expired retired undispatched, "
          f"10 live completed (fleet p99 TTFT {st['ttft_p99_ms']:.0f} ms)")


def shedding_leg(ff, rs, vocab):
    """A bounded router queue rejects excess load fast; accepted work is
    untouched and completes exactly once."""
    router = ff.make_serving_router(replicas=1, serve_slots=2,
                                    max_seq_len=32, max_queue=8,
                                    start=False)
    try:
        t0 = time.perf_counter()
        reqs = [router.submit(
            rs.randint(1, vocab, (int(rs.randint(3, 10)),)).astype(np.int32),
            4) for _ in range(40)]
        t_submit = time.perf_counter() - t0
        shed = [r for r in reqs if r.state == "rejected"]
        accepted = [r for r in reqs if r.state == "queued"]
        assert len(accepted) == 8 and len(shed) == 32, \
            f"{len(shed)} shed of 40 over a queue of 8"
        assert t_submit < 0.5, \
            f"40 submits (32 rejections) took {t_submit:.2f}s — not fast"
        snap = router.drain()
        assert [r.state for r in accepted] == ["done"] * 8
        assert snap["completed"] == 8 and snap["rejected"] == 32
        for r in accepted[::3]:
            solo = ff.generate(r.prompt[None, :], max_new_tokens=4)
            np.testing.assert_array_equal(
                np.asarray(r.tokens, np.int32), solo[0, r.prompt.size:])
        print(f"router_smoke[shed]: 32/40 rejected in "
              f"{t_submit * 1e3:.1f} ms total, 8 accepted all completed")
    finally:
        router.close()


if __name__ == "__main__":
    main()
