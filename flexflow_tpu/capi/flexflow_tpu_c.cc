/* flexflow_tpu C API implementation: embeds CPython and forwards each call
 * into the flexflow_tpu Python package (the same runtime the Python surface
 * uses — mirroring the reference where flexflow_c.cc forwards into FFModel;
 * reference: python/flexflow_c.cc).
 *
 * No numpy C API usage: C buffers become numpy arrays through
 * memoryview + np.frombuffer, keeping the build dependency-free.
 */

#include "flexflow_tpu_c.h"

#include <Python.h>

#include <cmath>
#include <cstring>
#include <string>
#include <vector>

namespace {

PyObject *g_ff = nullptr;       /* flexflow_tpu package */
PyObject *g_ffconst = nullptr;  /* flexflow_tpu.ffconst  */
PyObject *g_np = nullptr;       /* numpy */
std::string g_err;

void capture_error() {
  PyObject *t = nullptr, *v = nullptr, *tb = nullptr;
  PyErr_Fetch(&t, &v, &tb);
  PyErr_NormalizeException(&t, &v, &tb);
  g_err = "unknown error";
  if (v) {
    PyObject *s = PyObject_Str(v);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) g_err = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
}

/* Steal-nothing check: returns o, capturing the Python error when NULL.
 * On success the stale error is cleared, so fft_last_error() reflects the
 * most recent call (every API path that can fail goes through ck). */
PyObject *ck(PyObject *o) {
  if (!o)
    capture_error();
  else
    g_err.clear();
  return o;
}

PyObject *enum_from_int(const char *enum_name, long value) {
  PyObject *cls = ck(PyObject_GetAttrString(g_ffconst, enum_name));
  if (!cls) return nullptr;
  PyObject *r = ck(PyObject_CallFunction(cls, "l", value));
  Py_DECREF(cls);
  return r;
}

PyObject *int_list(const int *v, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) PyList_SET_ITEM(l, i, PyLong_FromLong(v[i]));
  return l;
}

/* call obj.<method>(args..., name=name) where args is a new-ref tuple */
PyObject *call_with_name(PyObject *obj, const char *method, PyObject *args,
                         const char *name) {
  PyObject *meth = ck(PyObject_GetAttrString(obj, method));
  if (!meth) {
    Py_DECREF(args);
    return nullptr;
  }
  PyObject *kwargs = PyDict_New();
  if (name) {
    PyObject *s = PyUnicode_FromString(name);
    PyDict_SetItemString(kwargs, "name", s);
    Py_DECREF(s);
  }
  PyObject *r = ck(PyObject_Call(meth, args, kwargs));
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  return r;
}

template <typename H>
H wrap(PyObject *o) {
  H h;
  h.impl = o;
  return h;
}

PyObject *obj(fft_config_t h) { return (PyObject *)h.impl; }
PyObject *obj(fft_model_t h) { return (PyObject *)h.impl; }
PyObject *obj(fft_tensor_t h) { return (PyObject *)h.impl; }
PyObject *obj(fft_optimizer_t h) { return (PyObject *)h.impl; }
PyObject *obj(fft_dataloader_t h) { return (PyObject *)h.impl; }

/* wrap a C buffer as a (copied) numpy array: np.frombuffer(mv, dt)
 * .reshape(shape).copy() */
PyObject *array_from_buffer(const void *data, int64_t nbytes, const char *dt,
                            PyObject *shape_list) {
  PyObject *mv = ck(PyMemoryView_FromMemory((char *)data, (Py_ssize_t)nbytes,
                                            PyBUF_READ));
  if (!mv) return nullptr;
  PyObject *flat = ck(PyObject_CallMethod(g_np, "frombuffer", "Os", mv, dt));
  Py_DECREF(mv);
  if (!flat) return nullptr;
  PyObject *shaped = ck(PyObject_CallMethod(flat, "reshape", "O", shape_list));
  Py_DECREF(flat);
  if (!shaped) return nullptr;
  PyObject *copied = ck(PyObject_CallMethod(shaped, "copy", nullptr));
  Py_DECREF(shaped);
  return copied;
}

int run_verb(fft_model_t m, const char *verb) {
  PyObject *r = ck(PyObject_CallMethod(obj(m), verb, nullptr));
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

}  // namespace

extern "C" {

int fft_init(const char *repo_root) {
  if (g_ff) return 0;
  if (!Py_IsInitialized()) Py_Initialize();
  if (repo_root) {
    PyObject *sys_path = PySys_GetObject("path"); /* borrowed */
    PyObject *p = PyUnicode_FromString(repo_root);
    PyList_Insert(sys_path, 0, p);
    Py_DECREF(p);
  }
  /* Optional platform override before any backend initializes (test rigs
   * set FFT_JAX_PLATFORMS=cpu + FFT_NUM_CPU_DEVICES=8 for a virtual mesh;
   * some environments pre-import jax so plain JAX_PLATFORMS is ignored). */
  PyRun_SimpleString(
      "import os as _os\n"
      "_plat = _os.environ.get('FFT_JAX_PLATFORMS')\n"
      "if _plat == 'cpu':\n"
      "    from flexflow_tpu._env import force_cpu_devices_from_env\n"
      "    force_cpu_devices_from_env("
      "_os.environ.get('FFT_NUM_CPU_DEVICES', '0'))\n"
      "elif _plat:\n"
      "    import jax as _jax\n"
      "    _jax.config.update('jax_platforms', _plat)\n");
  g_np = ck(PyImport_ImportModule("numpy"));
  g_ff = ck(PyImport_ImportModule("flexflow_tpu"));
  g_ffconst = ck(PyImport_ImportModule("flexflow_tpu.ffconst"));
  return (g_ff && g_ffconst && g_np) ? 0 : -1;
}

void fft_finalize(void) {
  Py_XDECREF(g_ff);
  Py_XDECREF(g_ffconst);
  Py_XDECREF(g_np);
  g_ff = g_ffconst = g_np = nullptr;
  if (Py_IsInitialized()) Py_Finalize();
}

const char *fft_last_error(void) { return g_err.c_str(); }

/* --------------------------------------------------------------- FFConfig */

fft_config_t fft_config_create(int batch_size, int epochs,
                               const char **mesh_axes, const int *mesh_sizes,
                               int n_mesh) {
  PyObject *cls = ck(PyObject_GetAttrString(g_ff, "FFConfig"));
  if (!cls) return wrap<fft_config_t>(nullptr);
  PyObject *kwargs = Py_BuildValue("{s:i,s:i}", "batch_size", batch_size,
                                   "epochs", epochs);
  if (n_mesh > 0) {
    PyObject *mesh = PyDict_New();
    for (int i = 0; i < n_mesh; ++i) {
      PyObject *sz = PyLong_FromLong(mesh_sizes[i]);
      PyDict_SetItemString(mesh, mesh_axes[i], sz);
      Py_DECREF(sz);
    }
    PyDict_SetItemString(kwargs, "mesh_shape", mesh);
    Py_DECREF(mesh);
  }
  PyObject *args = PyTuple_New(0);
  PyObject *cfg = ck(PyObject_Call(cls, args, kwargs));
  Py_DECREF(cls);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  return wrap<fft_config_t>(cfg);
}

void fft_config_destroy(fft_config_t h) { Py_XDECREF(obj(h)); }

static long get_int_attr(PyObject *o, const char *attr, long dflt) {
  PyObject *a = PyObject_GetAttrString(o, attr);
  if (!a) {
    PyErr_Clear();
    return dflt;
  }
  long v = PyLong_AsLong(a);
  Py_DECREF(a);
  return v;
}

int fft_config_get_batch_size(fft_config_t h) {
  return (int)get_int_attr(obj(h), "batch_size", -1);
}

int fft_config_get_epochs(fft_config_t h) {
  return (int)get_int_attr(obj(h), "epochs", -1);
}

int fft_config_get_num_devices(fft_config_t h) {
  (void)h;
  PyObject *jax = ck(PyImport_ImportModule("jax"));
  if (!jax) return -1;
  PyObject *n = ck(PyObject_CallMethod(jax, "device_count", nullptr));
  Py_DECREF(jax);
  if (!n) return -1;
  int v = (int)PyLong_AsLong(n);
  Py_DECREF(n);
  return v;
}

void fft_config_set_search_budget(fft_config_t h, int budget) {
  PyObject *v = PyLong_FromLong(budget);
  PyObject_SetAttrString(obj(h), "search_budget", v);
  Py_DECREF(v);
}

void fft_config_set_import_strategy_file(fft_config_t h, const char *path) {
  PyObject *v = PyUnicode_FromString(path);
  PyObject_SetAttrString(obj(h), "import_strategy_file", v);
  Py_DECREF(v);
}

void fft_config_set_export_strategy_file(fft_config_t h, const char *path) {
  PyObject *v = PyUnicode_FromString(path);
  PyObject_SetAttrString(obj(h), "export_strategy_file", v);
  Py_DECREF(v);
}

/* ---------------------------------------------------------------- FFModel */

fft_model_t fft_model_create(fft_config_t cfg) {
  PyObject *cls = ck(PyObject_GetAttrString(g_ff, "FFModel"));
  if (!cls) return wrap<fft_model_t>(nullptr);
  PyObject *m = ck(PyObject_CallFunction(cls, "O", obj(cfg)));
  Py_DECREF(cls);
  return wrap<fft_model_t>(m);
}

void fft_model_destroy(fft_model_t h) { Py_XDECREF(obj(h)); }

fft_tensor_t fft_model_create_tensor(fft_model_t m, const int *dims,
                                     int ndims, fft_data_type dtype,
                                     const char *name) {
  PyObject *dt = enum_from_int("DataType", dtype);
  if (!dt) return wrap<fft_tensor_t>(nullptr);
  PyObject *dl = int_list(dims, ndims);
  PyObject *meth = ck(PyObject_GetAttrString(obj(m), "create_tensor"));
  if (!meth) {
    Py_DECREF(dt);
    Py_DECREF(dl);
    return wrap<fft_tensor_t>(nullptr);
  }
  PyObject *args = Py_BuildValue("(O)", dl);
  PyObject *kwargs = Py_BuildValue("{s:O,s:s}", "dtype", dt, "name", name);
  PyObject *t = ck(PyObject_Call(meth, args, kwargs));
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(dt);
  Py_DECREF(dl);
  return wrap<fft_tensor_t>(t);
}

fft_tensor_t fft_model_add_dense(fft_model_t m, fft_tensor_t in, int out_dim,
                                 fft_acti_mode act, int use_bias,
                                 const char *name) {
  PyObject *a = enum_from_int("ActiMode", act);
  if (!a) return wrap<fft_tensor_t>(nullptr);
  PyObject *args = Py_BuildValue("(OiOO)", obj(in), out_dim, a,
                                 use_bias ? Py_True : Py_False);
  Py_DECREF(a);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "dense", args, name));
}

fft_tensor_t fft_model_add_conv2d(fft_model_t m, fft_tensor_t in,
                                  int out_channels, int kh, int kw, int sh,
                                  int sw, int ph, int pw, fft_acti_mode act,
                                  int groups, int use_bias,
                                  const char *name) {
  PyObject *a = enum_from_int("ActiMode", act);
  if (!a) return wrap<fft_tensor_t>(nullptr);
  PyObject *args =
      Py_BuildValue("(OiiiiiiiOiO)", obj(in), out_channels, kh, kw, sh, sw,
                    ph, pw, a, groups, use_bias ? Py_True : Py_False);
  Py_DECREF(a);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "conv2d", args, name));
}

fft_tensor_t fft_model_add_pool2d(fft_model_t m, fft_tensor_t in, int kh,
                                  int kw, int sh, int sw, int ph, int pw,
                                  fft_pool_type type, const char *name) {
  PyObject *p = enum_from_int("PoolType", type);
  if (!p) return wrap<fft_tensor_t>(nullptr);
  PyObject *args =
      Py_BuildValue("(OiiiiiiO)", obj(in), kh, kw, sh, sw, ph, pw, p);
  Py_DECREF(p);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "pool2d", args, name));
}

fft_tensor_t fft_model_add_embedding(fft_model_t m, fft_tensor_t in,
                                     int num_entries, int out_dim,
                                     fft_aggr_mode aggr, const char *name) {
  PyObject *a = enum_from_int("AggrMode", aggr);
  if (!a) return wrap<fft_tensor_t>(nullptr);
  PyObject *args =
      Py_BuildValue("(OiiO)", obj(in), num_entries, out_dim, a);
  Py_DECREF(a);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "embedding", args, name));
}

fft_tensor_t fft_model_add_flat(fft_model_t m, fft_tensor_t in,
                                const char *name) {
  PyObject *args = Py_BuildValue("(O)", obj(in));
  return wrap<fft_tensor_t>(call_with_name(obj(m), "flat", args, name));
}

fft_tensor_t fft_model_add_softmax(fft_model_t m, fft_tensor_t in, int axis,
                                   const char *name) {
  PyObject *args = Py_BuildValue("(Oi)", obj(in), axis);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "softmax", args, name));
}

fft_tensor_t fft_model_add_batch_norm(fft_model_t m, fft_tensor_t in,
                                      int relu, const char *name) {
  PyObject *args =
      Py_BuildValue("(OO)", obj(in), relu ? Py_True : Py_False);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "batch_norm", args, name));
}

fft_tensor_t fft_model_add_concat(fft_model_t m, const fft_tensor_t *ins,
                                  int n, int axis, const char *name) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    Py_INCREF(obj(ins[i]));
    PyList_SET_ITEM(l, i, obj(ins[i]));
  }
  PyObject *args = Py_BuildValue("(Oi)", l, axis);
  Py_DECREF(l);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "concat", args, name));
}

fft_tensor_t fft_model_add_dropout(fft_model_t m, fft_tensor_t in, float rate,
                                   const char *name) {
  PyObject *args = Py_BuildValue("(Of)", obj(in), rate);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "dropout", args, name));
}

fft_tensor_t fft_model_add_multihead_attention(fft_model_t m, fft_tensor_t q,
                                               fft_tensor_t k, fft_tensor_t v,
                                               int embed_dim, int num_heads,
                                               int causal, const char *name) {
  PyObject *meth =
      ck(PyObject_GetAttrString(obj(m), "multihead_attention"));
  if (!meth) return wrap<fft_tensor_t>(nullptr);
  PyObject *args =
      Py_BuildValue("(OOOii)", obj(q), obj(k), obj(v), embed_dim, num_heads);
  PyObject *kwargs = Py_BuildValue("{s:O,s:s}", "causal",
                                   causal ? Py_True : Py_False, "name", name);
  PyObject *r = ck(PyObject_Call(meth, args, kwargs));
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  return wrap<fft_tensor_t>(r);
}

fft_tensor_t fft_model_add_add(fft_model_t m, fft_tensor_t a, fft_tensor_t b,
                               const char *name) {
  PyObject *args = Py_BuildValue("(OO)", obj(a), obj(b));
  return wrap<fft_tensor_t>(call_with_name(obj(m), "add", args, name));
}

fft_tensor_t fft_model_add_multiply(fft_model_t m, fft_tensor_t a,
                                    fft_tensor_t b, const char *name) {
  PyObject *args = Py_BuildValue("(OO)", obj(a), obj(b));
  return wrap<fft_tensor_t>(call_with_name(obj(m), "multiply", args, name));
}

fft_tensor_t fft_model_add_relu(fft_model_t m, fft_tensor_t in,
                                const char *name) {
  PyObject *args = Py_BuildValue("(O)", obj(in));
  return wrap<fft_tensor_t>(call_with_name(obj(m), "relu", args, name));
}

fft_tensor_t fft_model_add_reshape(fft_model_t m, fft_tensor_t in,
                                   const int *shape, int ndims,
                                   const char *name) {
  PyObject *l = int_list(shape, ndims);
  PyObject *args = Py_BuildValue("(OO)", obj(in), l);
  Py_DECREF(l);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "reshape", args, name));
}

fft_tensor_t fft_model_add_transpose(fft_model_t m, fft_tensor_t in,
                                     const int *perm, int ndims,
                                     const char *name) {
  PyObject *l = int_list(perm, ndims);
  PyObject *args = Py_BuildValue("(OO)", obj(in), l);
  Py_DECREF(l);
  return wrap<fft_tensor_t>(call_with_name(obj(m), "transpose", args, name));
}

int fft_model_compile(fft_model_t m, fft_optimizer_t opt, fft_loss_type loss,
                      const fft_metrics_type *metrics, int n_metrics,
                      fft_tensor_t final) {
  PyObject *lt = enum_from_int("LossType", loss);
  if (!lt) return -1;
  PyObject *ml = PyList_New(n_metrics);
  for (int i = 0; i < n_metrics; ++i) {
    PyObject *mt = enum_from_int("MetricsType", metrics[i]);
    if (!mt) {
      Py_DECREF(lt);
      Py_DECREF(ml);
      return -1;
    }
    PyList_SET_ITEM(ml, i, mt);
  }
  PyObject *meth = ck(PyObject_GetAttrString(obj(m), "compile"));
  if (!meth) {
    Py_DECREF(lt);
    Py_DECREF(ml);
    return -1;
  }
  PyObject *args = Py_BuildValue("(OOO)", obj(opt), lt, ml);
  PyObject *kwargs = PyDict_New();
  if (final.impl)
    PyDict_SetItemString(kwargs, "final_tensor", obj(final));
  PyObject *r = ck(PyObject_Call(meth, args, kwargs));
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(lt);
  Py_DECREF(ml);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int fft_model_init_layers(fft_model_t m) { return run_verb(m, "init_layers"); }

fft_tensor_t fft_model_get_label_tensor(fft_model_t m) {
  PyObject *t = ck(PyObject_GetAttrString(obj(m), "label_tensor"));
  return wrap<fft_tensor_t>(t);
}

int fft_model_forward(fft_model_t m) { return run_verb(m, "forward"); }
int fft_model_zero_gradients(fft_model_t m) {
  return run_verb(m, "zero_gradients");
}
int fft_model_backward(fft_model_t m) { return run_verb(m, "backward"); }
int fft_model_update(fft_model_t m) { return run_verb(m, "update"); }
int fft_model_next_batch(fft_model_t m) {
  return run_verb(m, "next_batch_all");
}

int fft_model_fit(fft_model_t m, int epochs) {
  PyObject *meth = ck(PyObject_GetAttrString(obj(m), "fit"));
  if (!meth) return -1;
  PyObject *args = PyTuple_New(0);
  PyObject *kwargs = PyDict_New();
  if (epochs > 0) {
    PyObject *e = PyLong_FromLong(epochs);
    PyDict_SetItemString(kwargs, "epochs", e);
    Py_DECREF(e);
  }
  PyObject *r = ck(PyObject_Call(meth, args, kwargs));
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

float fft_model_get_last_loss(fft_model_t m) {
  PyObject *l = PyObject_GetAttrString(obj(m), "_last_loss");
  if (!l) {
    PyErr_Clear();
    return NAN;
  }
  PyObject *f = ck(PyNumber_Float(l));
  Py_DECREF(l);
  if (!f) return NAN;
  float v = (float)PyFloat_AsDouble(f);
  Py_DECREF(f);
  return v;
}

int fft_model_get_weights(fft_model_t m, const char *op_name,
                          const char *weight_name, float *buf, int64_t n) {
  PyObject *w = ck(PyObject_CallMethod(obj(m), "get_weights", "ss", op_name,
                                       weight_name));
  if (!w) return -1;
  PyObject *dt = PyUnicode_FromString("float32");
  PyObject *cont = ck(PyObject_CallMethod(g_np, "ascontiguousarray", "OO", w,
                                          dt));
  Py_DECREF(w);
  Py_DECREF(dt);
  if (!cont) return -1;
  Py_buffer view;
  if (PyObject_GetBuffer(cont, &view, PyBUF_CONTIG_RO) != 0) {
    capture_error();
    Py_DECREF(cont);
    return -1;
  }
  int64_t count = (int64_t)(view.len / sizeof(float));
  if (count != n) {
    g_err = "get_weights: size mismatch";
    PyBuffer_Release(&view);
    Py_DECREF(cont);
    return -1;
  }
  std::memcpy(buf, view.buf, (size_t)view.len);
  PyBuffer_Release(&view);
  Py_DECREF(cont);
  return 0;
}

int fft_model_set_weights(fft_model_t m, const char *op_name,
                          const char *weight_name, const float *buf,
                          int64_t n) {
  /* target shape from the live (device) param — no host copy needed */
  PyObject *params = ck(PyObject_GetAttrString(obj(m), "params"));
  if (!params) return -1;
  PyObject *group = ck(PyMapping_GetItemString(params, op_name));
  Py_DECREF(params);
  if (!group) return -1;
  PyObject *cur = ck(PyMapping_GetItemString(group, weight_name));
  Py_DECREF(group);
  if (!cur) return -1;
  PyObject *shape = ck(PyObject_GetAttrString(cur, "shape"));
  Py_DECREF(cur);
  if (!shape) return -1;
  PyObject *arr =
      array_from_buffer(buf, n * (int64_t)sizeof(float), "float32", shape);
  Py_DECREF(shape);
  if (!arr) return -1;
  PyObject *r = ck(PyObject_CallMethod(obj(m), "set_weights", "ssO", op_name,
                                       weight_name, arr));
  Py_DECREF(arr);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

/* ----------------------------------------------------------------- Tensor */

int fft_tensor_get_ndims(fft_tensor_t t) {
  PyObject *d = ck(PyObject_GetAttrString(obj(t), "dims"));
  if (!d) return -1;
  int n = (int)PySequence_Length(d);
  Py_DECREF(d);
  return n;
}

void fft_tensor_get_dims(fft_tensor_t t, int *dims) {
  PyObject *d = ck(PyObject_GetAttrString(obj(t), "dims"));
  if (!d) return;
  Py_ssize_t n = PySequence_Length(d);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *x = PySequence_GetItem(d, i);
    dims[i] = (int)PyLong_AsLong(x);
    Py_DECREF(x);
  }
  Py_DECREF(d);
}

void fft_tensor_destroy(fft_tensor_t t) { Py_XDECREF(obj(t)); }

/* ------------------------------------------------------------- Optimizers */

fft_optimizer_t fft_sgd_optimizer_create(double lr, double momentum,
                                         int nesterov, double weight_decay) {
  PyObject *cls = ck(PyObject_GetAttrString(g_ff, "SGDOptimizer"));
  if (!cls) return wrap<fft_optimizer_t>(nullptr);
  PyObject *args = PyTuple_New(0);
  PyObject *kwargs = Py_BuildValue(
      "{s:d,s:d,s:O,s:d}", "lr", lr, "momentum", momentum, "nesterov",
      nesterov ? Py_True : Py_False, "weight_decay", weight_decay);
  PyObject *o = ck(PyObject_Call(cls, args, kwargs));
  Py_DECREF(cls);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  return wrap<fft_optimizer_t>(o);
}

fft_optimizer_t fft_adam_optimizer_create(double lr, double beta1,
                                          double beta2, double weight_decay,
                                          double epsilon) {
  PyObject *cls = ck(PyObject_GetAttrString(g_ff, "AdamOptimizer"));
  if (!cls) return wrap<fft_optimizer_t>(nullptr);
  PyObject *args = PyTuple_New(0);
  PyObject *kwargs = Py_BuildValue(
      "{s:d,s:d,s:d,s:d,s:d}", "alpha", lr, "beta1", beta1, "beta2", beta2,
      "weight_decay", weight_decay, "epsilon", epsilon);
  PyObject *o = ck(PyObject_Call(cls, args, kwargs));
  Py_DECREF(cls);
  Py_DECREF(args);
  Py_DECREF(kwargs);
  return wrap<fft_optimizer_t>(o);
}

void fft_optimizer_destroy(fft_optimizer_t h) { Py_XDECREF(obj(h)); }

/* ------------------------------------------------------------- DataLoader */

fft_dataloader_t fft_single_dataloader_create(fft_model_t m, fft_tensor_t t,
                                              const void *data,
                                              int64_t num_samples) {
  /* element shape = tensor.dims[1:]; dtype from tensor.dtype */
  int nd = fft_tensor_get_ndims(t);
  if (nd < 1) return wrap<fft_dataloader_t>(nullptr);
  std::vector<int> dims(nd);
  fft_tensor_get_dims(t, dims.data());
  int64_t per_sample = 1;
  for (int i = 1; i < nd; ++i) per_sample *= dims[i];

  PyObject *dtype_obj = ck(PyObject_GetAttrString(obj(t), "dtype"));
  if (!dtype_obj) return wrap<fft_dataloader_t>(nullptr);
  PyObject *dname = ck(PyObject_GetAttrString(dtype_obj, "name"));
  Py_DECREF(dtype_obj);
  if (!dname) return wrap<fft_dataloader_t>(nullptr);
  const char *dn = PyUnicode_AsUTF8(dname);
  const char *npdt = nullptr;
  int64_t esize = 0;
  if (dn && std::strcmp(dn, "DT_FLOAT") == 0) {
    npdt = "float32";
    esize = 4;
  } else if (dn && std::strcmp(dn, "DT_INT64") == 0) {
    npdt = "int64";
    esize = 8;
  } else if (dn && std::strcmp(dn, "DT_INT32") == 0) {
    npdt = "int32";
    esize = 4;
  } else if (dn && std::strcmp(dn, "DT_DOUBLE") == 0) {
    npdt = "float64";
    esize = 8;
  }
  if (!npdt) {
    g_err = std::string("single_dataloader: unsupported tensor dtype ") +
            (dn ? dn : "?") +
            " for raw-buffer attach (use float32/float64/int32/int64)";
    Py_DECREF(dname);
    return wrap<fft_dataloader_t>(nullptr);
  }
  Py_DECREF(dname);

  PyObject *shape = PyList_New(nd);
  PyList_SET_ITEM(shape, 0, PyLong_FromLongLong(num_samples));
  for (int i = 1; i < nd; ++i)
    PyList_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
  PyObject *arr = array_from_buffer(data, num_samples * per_sample * esize,
                                    npdt, shape);
  Py_DECREF(shape);
  if (!arr) return wrap<fft_dataloader_t>(nullptr);

  PyObject *cls = ck(PyObject_GetAttrString(g_ff, "SingleDataLoader"));
  if (!cls) {
    Py_DECREF(arr);
    return wrap<fft_dataloader_t>(nullptr);
  }
  PyObject *dl = ck(PyObject_CallFunction(cls, "OOO", obj(m), obj(t), arr));
  Py_DECREF(cls);
  Py_DECREF(arr);
  return wrap<fft_dataloader_t>(dl);
}

void fft_dataloader_destroy(fft_dataloader_t h) { Py_XDECREF(obj(h)); }

int fft_dataloader_num_batches(fft_dataloader_t h) {
  return (int)get_int_attr(obj(h), "num_batches", -1);
}

}  /* extern "C" */
