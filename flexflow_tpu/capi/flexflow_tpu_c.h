/* flexflow_tpu C API.
 *
 * A flat C shim over the flexflow_tpu Python framework, serving the same
 * role as the reference's C API (reference: python/flexflow_c.h:27-851 —
 * opaque handle types over FFConfig/FFModel/Tensor/optimizers/dataloaders)
 * so native C/C++ applications can build, train and evaluate models.
 *
 * Implementation embeds CPython (the compute path is JAX/XLA either way;
 * the reference's C API equally just forwards into the same runtime its
 * Python bindings use). Handles are reference-counted Python objects.
 *
 * Thread model: all calls must come from one thread (the embedded
 * interpreter owns the GIL for the duration of each call).
 */

#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define FFT_OPAQUE(T) typedef struct T { void *impl; } T

FFT_OPAQUE(fft_config_t);
FFT_OPAQUE(fft_model_t);
FFT_OPAQUE(fft_tensor_t);
FFT_OPAQUE(fft_optimizer_t);
FFT_OPAQUE(fft_dataloader_t);

/* enums mirror flexflow_tpu.ffconst (reference include/ffconst.h) */
typedef enum fft_acti_mode {
  FFT_AC_MODE_NONE = 10,
  FFT_AC_MODE_RELU = 11,
  FFT_AC_MODE_SIGMOID = 12,
  FFT_AC_MODE_TANH = 13,
  FFT_AC_MODE_GELU = 14,
} fft_acti_mode;

typedef enum fft_pool_type {
  FFT_POOL_MAX = 30,
  FFT_POOL_AVG = 31,
} fft_pool_type;

typedef enum fft_aggr_mode {
  FFT_AGGR_MODE_NONE = 20,
  FFT_AGGR_MODE_SUM = 21,
  FFT_AGGR_MODE_AVG = 22,
} fft_aggr_mode;

typedef enum fft_loss_type {
  FFT_LOSS_CATEGORICAL_CROSSENTROPY = 50,
  FFT_LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51,
  FFT_LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52,
  FFT_LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53,
} fft_loss_type;

typedef enum fft_metrics_type {
  FFT_METRICS_ACCURACY = 1001,
  FFT_METRICS_CATEGORICAL_CROSSENTROPY = 1002,
  FFT_METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004,
  FFT_METRICS_MEAN_SQUARED_ERROR = 1008,
  FFT_METRICS_ROOT_MEAN_SQUARED_ERROR = 1016,
  FFT_METRICS_MEAN_ABSOLUTE_ERROR = 1032,
} fft_metrics_type;

typedef enum fft_data_type {
  FFT_DT_FLOAT = 40,
  FFT_DT_DOUBLE = 41,
  FFT_DT_INT32 = 42,
  FFT_DT_INT64 = 43,
  FFT_DT_BOOLEAN = 44,
  FFT_DT_HALF = 45,
  FFT_DT_BFLOAT16 = 46,
} fft_data_type;

/* ---------------------------------------------------------------- runtime */

/* Initialize the embedded interpreter + import flexflow_tpu.  repo_root may
 * be NULL if flexflow_tpu is importable from the default sys.path.
 * Returns 0 on success. Call once before anything else. */
int fft_init(const char *repo_root);

/* Finalize the interpreter. No fft_* call is valid afterwards. */
void fft_finalize(void);

/* Last error message ("" if none). Valid until the next fft_* call. */
const char *fft_last_error(void);

/* --------------------------------------------------------------- FFConfig */

/* mesh_axes/mesh_sizes: named device-mesh axes, e.g. {"data","model"},{4,2}.
 * Pass n_mesh=0 for single-axis {"data": num_devices}. */
fft_config_t fft_config_create(int batch_size, int epochs,
                               const char **mesh_axes, const int *mesh_sizes,
                               int n_mesh);
void fft_config_destroy(fft_config_t h);
int fft_config_get_batch_size(fft_config_t h);
int fft_config_get_epochs(fft_config_t h);
int fft_config_get_num_devices(fft_config_t h);
/* MCMC strategy search knobs (reference --budget / --import / --export) */
void fft_config_set_search_budget(fft_config_t h, int budget);
void fft_config_set_import_strategy_file(fft_config_t h, const char *path);
void fft_config_set_export_strategy_file(fft_config_t h, const char *path);

/* ---------------------------------------------------------------- FFModel */

fft_model_t fft_model_create(fft_config_t cfg);
void fft_model_destroy(fft_model_t h);

fft_tensor_t fft_model_create_tensor(fft_model_t m, const int *dims,
                                     int ndims, fft_data_type dtype,
                                     const char *name);

/* layer factories (reference flexflow_model_add_*) */
fft_tensor_t fft_model_add_dense(fft_model_t m, fft_tensor_t in, int out_dim,
                                 fft_acti_mode act, int use_bias,
                                 const char *name);
fft_tensor_t fft_model_add_conv2d(fft_model_t m, fft_tensor_t in,
                                  int out_channels, int kh, int kw, int sh,
                                  int sw, int ph, int pw, fft_acti_mode act,
                                  int groups, int use_bias, const char *name);
fft_tensor_t fft_model_add_pool2d(fft_model_t m, fft_tensor_t in, int kh,
                                  int kw, int sh, int sw, int ph, int pw,
                                  fft_pool_type type, const char *name);
fft_tensor_t fft_model_add_embedding(fft_model_t m, fft_tensor_t in,
                                     int num_entries, int out_dim,
                                     fft_aggr_mode aggr, const char *name);
fft_tensor_t fft_model_add_flat(fft_model_t m, fft_tensor_t in,
                                const char *name);
fft_tensor_t fft_model_add_softmax(fft_model_t m, fft_tensor_t in, int axis,
                                   const char *name);
fft_tensor_t fft_model_add_batch_norm(fft_model_t m, fft_tensor_t in,
                                      int relu, const char *name);
fft_tensor_t fft_model_add_concat(fft_model_t m, const fft_tensor_t *ins,
                                  int n, int axis, const char *name);
fft_tensor_t fft_model_add_dropout(fft_model_t m, fft_tensor_t in, float rate,
                                   const char *name);
fft_tensor_t fft_model_add_multihead_attention(fft_model_t m, fft_tensor_t q,
                                               fft_tensor_t k, fft_tensor_t v,
                                               int embed_dim, int num_heads,
                                               int causal, const char *name);
fft_tensor_t fft_model_add_add(fft_model_t m, fft_tensor_t a, fft_tensor_t b,
                               const char *name);
fft_tensor_t fft_model_add_multiply(fft_model_t m, fft_tensor_t a,
                                    fft_tensor_t b, const char *name);
fft_tensor_t fft_model_add_relu(fft_model_t m, fft_tensor_t in,
                                const char *name);
fft_tensor_t fft_model_add_reshape(fft_model_t m, fft_tensor_t in,
                                   const int *shape, int ndims,
                                   const char *name);
fft_tensor_t fft_model_add_transpose(fft_model_t m, fft_tensor_t in,
                                     const int *perm, int ndims,
                                     const char *name);

/* compile: resolves strategies (runs the MCMC search when budget>0), builds
 * the mesh, initializes sharded params. final may be a NULL-impl handle to
 * use the last op's output. */
int fft_model_compile(fft_model_t m, fft_optimizer_t opt, fft_loss_type loss,
                      const fft_metrics_type *metrics, int n_metrics,
                      fft_tensor_t final);

int fft_model_init_layers(fft_model_t m);
fft_tensor_t fft_model_get_label_tensor(fft_model_t m);

/* train verbs (reference: forward/zero_gradients/backward/update are fused
 * into one XLA step here; the verbs are kept for API parity) */
int fft_model_forward(fft_model_t m);
int fft_model_zero_gradients(fft_model_t m);
int fft_model_backward(fft_model_t m);
int fft_model_update(fft_model_t m);
int fft_model_next_batch(fft_model_t m);

/* full training loop with throughput print; returns 0 on success */
int fft_model_fit(fft_model_t m, int epochs);

/* loss of the most recent step (NaN before any step) */
float fft_model_get_last_loss(fft_model_t m);

/* weights IO (reference Parameter::set_weights/get_weights).
 * buf is row-major float32 of the parameter's full (unsharded) shape. */
int fft_model_get_weights(fft_model_t m, const char *op_name,
                          const char *weight_name, float *buf, int64_t n);
int fft_model_set_weights(fft_model_t m, const char *op_name,
                          const char *weight_name, const float *buf,
                          int64_t n);

/* ----------------------------------------------------------------- Tensor */

int fft_tensor_get_ndims(fft_tensor_t t);
void fft_tensor_get_dims(fft_tensor_t t, int *dims);
void fft_tensor_destroy(fft_tensor_t t);

/* ------------------------------------------------------------- Optimizers */

fft_optimizer_t fft_sgd_optimizer_create(double lr, double momentum,
                                         int nesterov, double weight_decay);
fft_optimizer_t fft_adam_optimizer_create(double lr, double beta1,
                                          double beta2, double weight_decay,
                                          double epsilon);
void fft_optimizer_destroy(fft_optimizer_t h);

/* ------------------------------------------------------------- DataLoader */

/* Full dataset resident, next_batch slices per shard (reference
 * SingleDataLoader, python/flexflow_dataloader.cc). data is row-major
 * float32 (or int32 when the tensor dtype is int) of shape
 * [num_samples, tensor.dims[1:]...]. */
fft_dataloader_t fft_single_dataloader_create(fft_model_t m, fft_tensor_t t,
                                              const void *data,
                                              int64_t num_samples);
void fft_dataloader_destroy(fft_dataloader_t h);
int fft_dataloader_num_batches(fft_dataloader_t h);

#undef FFT_OPAQUE

#ifdef __cplusplus
}
#endif

#endif /* FLEXFLOW_TPU_C_H */
