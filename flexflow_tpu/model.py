"""FFModel: the user-facing graph builder + training driver.

API parity with the reference's FFModel (include/model.h:250-483; Python
surface python/flexflow/core/flexflow_cbinding.py): layer factory methods
append ops to a graph; `compile` resolves strategies (running the MCMC search
when budget > 0), builds the mesh, and initializes sharded params; the
training verbs (forward/zero_gradients/backward/update) and `fit` drive
jitted GSPMD steps.

Execution model difference from the reference: instead of per-op Legion index
launches scheduled by a mapper (§3.1 of SURVEY.md), the whole step is one XLA
program; strategies become sharding constraints inside it.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import (ActiMode, AggrMode, CompMode, DataType,
                                  LossType, MetricsType, OperatorType, PoolType)
from flexflow_tpu.ops.attention import MultiHeadAttention
from flexflow_tpu.ops.base import InputOp, Op
from flexflow_tpu.ops.conv import BatchNorm, Conv2D, Flat, Pool2D
from flexflow_tpu.ops.dense import BatchMatmul, Embedding, Linear
from flexflow_tpu.ops.elementwise import Cast, ElementBinary, ElementUnary, Mean
from flexflow_tpu.ops.norm import (AddLayerNorm, Dropout, LayerNorm, RMSNorm,
                                   Softmax)
from flexflow_tpu.ops.tensor_ops import (Concat, Gather, Pad, Reshape, Reverse,
                                         Split, TopK, Transpose)
from flexflow_tpu.parallel.mesh import make_mesh
from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)
from flexflow_tpu.runtime.executor import GraphExecutor
from flexflow_tpu.runtime.loss import loss_type_from_name
from flexflow_tpu.runtime.metrics import PerfMetrics, metrics_from_names
from flexflow_tpu.tensor import Tensor

# process-wide model ids: the HBM ledger's per-instance source name
# (two FFModels in one process must not overwrite each other's rows)
_MODEL_IDS = iter(range(1 << 30))


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        self.ops: List[Op] = []
        self._op_counters: Dict[str, int] = {}
        self._dataloaders: List = []
        self.mesh = None
        self.executor: Optional[GraphExecutor] = None
        # bumped on every params replacement/mutation (property setter +
        # set_weights); consumers that derive from params (the int8 decode
        # cache) key their caches on it
        self._params_version = 0
        self.params = None
        self.opt_state = None
        self.bn_state = None
        self.optimizer = None
        self.loss_type: Optional[LossType] = None
        self.metric_types: List[MetricsType] = []
        self.label_tensor: Optional[Tensor] = None
        self.comp_mode = CompMode.COMP_MODE_TRAINING
        self._rng = jax.random.PRNGKey(self.config.seed)
        self._step_count = 0
        self._train_step = None
        self._train_scan = None
        # divergence-guarded step + its device-resident guard carry
        # (runtime/resilience.py; built in compile() when
        # config.on_nonfinite != "none")
        self._guarded_step = None
        self._guard_state = None
        self._eval_step = None
        self._predict_fn = None
        self._generators = {}
        # (dst_op, dst_weight) -> (src_op, src_weight, transform); see
        # tie_weights()
        self._tied = {}
        self._current_batch: Dict[str, np.ndarray] = {}
        self._aux_tensors: List[Tensor] = []  # scalar losses (MoE balance)
        self._cached_backward = None
        self._perf = PerfMetrics()
        # host-overlap step engine (runtime/pipeline_loader.py): the live
        # prefetch pipeline while fit() runs one (the supervisor reads
        # checkpoint cursors through it), and the last fit's per-step
        # host_wait/h2d/dispatch/device breakdown
        self._pipeline = None
        self.last_step_breakdown: Optional[Dict[str, float]] = None
        # fflint's per-chip HBM footprint estimate, stashed by compile's
        # lint pass for the flight recorder's accounting cross-check;
        # the ledger row name is per-instance so two models in one
        # process keep distinct rows
        self._lint_hbm_estimate: Optional[float] = None
        self._hbm_name = f"model-{next(_MODEL_IDS)}"
        # identity of the ledger this model registered on: a
        # flightrec.reset() swaps the singleton, so a plain once-flag
        # would permanently drop this model's row from later scrapes
        self._hbm_registered_on = None

    @property
    def params(self):
        return self._params

    @params.setter
    def params(self, value):
        self._params = value
        self._params_version += 1

    # ------------------------------------------------------------------ graph

    def _name(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        n = self._op_counters.get(kind, 0)
        self._op_counters[kind] = n + 1
        return f"{kind}_{n}" if n else kind

    def _add(self, op: Op) -> Union[Tensor, List[Tensor]]:
        assert self.get_op_by_name(op.name) is None, \
            f"duplicate op name {op.name!r} (params/strategies key by name)"
        self.ops.append(op)
        return op.outputs[0] if len(op.outputs) == 1 else op.outputs

    def create_tensor(self, dims: Sequence[int],
                      dtype: DataType = DataType.DT_FLOAT,
                      name: Optional[str] = None,
                      create_grad: bool = True) -> Tensor:
        if not isinstance(dtype, DataType):
            # the classic misuse is passing the NAME positionally where dtype
            # goes — without this check the string rides the graph and
            # surfaces as a KeyError deep inside measurement/serialization
            raise TypeError(
                f"create_tensor dtype must be a DataType enum, got "
                f"{dtype!r} — did you mean name={dtype!r}?")
        op = InputOp(self, self._name("input", name), tuple(dims), dtype)
        op.finalize()
        assert self.get_op_by_name(op.name) is None, \
            f"duplicate input name {op.name!r} (batch dicts key by name)"
        self.ops.append(op)
        return op.outputs[0]

    # layer factories (reference: flexflow_c.h flexflow_model_add_*)

    def dense(self, input: Tensor, out_dim: int,
              activation: ActiMode = ActiMode.AC_MODE_NONE,
              use_bias: bool = True, name: Optional[str] = None, **kw) -> Tensor:
        return self._add(Linear(self, self._name("dense", name), [input],
                                out_dim, activation, use_bias))

    def conv2d(self, input: Tensor, out_channels: int, kernel_h: int,
               kernel_w: int, stride_h: int, stride_w: int, padding_h: int,
               padding_w: int, activation: ActiMode = ActiMode.AC_MODE_NONE,
               groups: int = 1, use_bias: bool = True,
               name: Optional[str] = None, **kw) -> Tensor:
        return self._add(Conv2D(self, self._name("conv2d", name), [input],
                                out_channels, kernel_h, kernel_w, stride_h,
                                stride_w, padding_h, padding_w, activation,
                                groups, use_bias))

    def pool2d(self, input: Tensor, kernel_h: int, kernel_w: int,
               stride_h: int, stride_w: int, padding_h: int, padding_w: int,
               pool_type: PoolType = PoolType.POOL_MAX,
               activation: ActiMode = ActiMode.AC_MODE_NONE,
               name: Optional[str] = None) -> Tensor:
        return self._add(Pool2D(self, self._name("pool2d", name), [input],
                                kernel_h, kernel_w, stride_h, stride_w,
                                padding_h, padding_w, pool_type, activation))

    def embedding(self, input: Tensor, num_entries: int, out_dim: int,
                  aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
                  name: Optional[str] = None, **kw) -> Tensor:
        return self._add(Embedding(self, self._name("embedding", name), [input],
                                   num_entries, out_dim, aggr))

    def batch_norm(self, input: Tensor, relu: bool = True,
                   name: Optional[str] = None) -> Tensor:
        return self._add(BatchNorm(self, self._name("batch_norm", name),
                                   [input], relu))

    def layer_norm(self, input: Tensor, eps: float = 1e-5,
                   elementwise_affine: bool = True,
                   name: Optional[str] = None) -> Tensor:
        return self._add(LayerNorm(self, self._name("layer_norm", name),
                                   [input], eps, elementwise_affine))

    def add_layer_norm(self, input: Tensor, residual: Tensor,
                       eps: float = 1e-5,
                       name: Optional[str] = None) -> List[Tensor]:
        """Fused (input + residual, LN(input + residual)); returns
        [sum, normed]."""
        out = self._add(AddLayerNorm(self, self._name("add_ln", name),
                                     [input, residual], eps))
        return out if isinstance(out, list) else [out]

    def rms_norm(self, input: Tensor, eps: float = 1e-6,
                 name: Optional[str] = None) -> Tensor:
        return self._add(RMSNorm(self, self._name("rms_norm", name), [input], eps))

    def lstm(self, input: Tensor, hidden_size: int,
             return_sequences: bool = True, name: Optional[str] = None) -> Tensor:
        from flexflow_tpu.ops.recurrent import LSTM

        return self._add(LSTM(self, self._name("lstm", name), [input],
                              hidden_size, return_sequences))

    def gru(self, input: Tensor, hidden_size: int,
            return_sequences: bool = True, name: Optional[str] = None) -> Tensor:
        from flexflow_tpu.ops.recurrent import GRU

        return self._add(GRU(self, self._name("gru", name), [input],
                             hidden_size, return_sequences))

    def moe(self, input: Tensor, num_experts: int, hidden_dim: int,
            k: int = 2, capacity_factor: float = 1.25,
            dispatch: str = "auto", name: Optional[str] = None) -> Tensor:
        """Mixture-of-experts FFN (net-new vs reference; expert-parallel over
        the 'expert' mesh axis). Returns the main output; the load-balancing
        aux loss is folded into the training loss automatically. dispatch:
        "auto" (dense einsums when experts are mesh-sharded, else sort-based)
        | "dense" | "sort"."""
        from flexflow_tpu.ops.moe import MoE

        op = MoE(self, self._name("moe", name), [input], num_experts,
                 hidden_dim, k, capacity_factor, dispatch=dispatch)
        outs = self._add(op)
        self._aux_tensors.append(outs[1])
        return outs[0]

    def batch_matmul(self, a: Tensor, b: Tensor,
                     name: Optional[str] = None) -> Tensor:
        return self._add(BatchMatmul(self, self._name("batch_matmul", name), [a, b]))

    def flat(self, input: Tensor, name: Optional[str] = None) -> Tensor:
        return self._add(Flat(self, self._name("flat", name), [input]))

    def softmax(self, input: Tensor, axis: int = -1,
                name: Optional[str] = None) -> Tensor:
        return self._add(Softmax(self, self._name("softmax", name), [input], axis))

    def dropout(self, input: Tensor, rate: float, seed: int = 0,
                name: Optional[str] = None) -> Tensor:
        return self._add(Dropout(self, self._name("dropout", name), [input],
                                 rate, seed))

    def multihead_attention(self, query: Tensor, key: Tensor, value: Tensor,
                            embed_dim: int, num_heads: int, kdim: int = 0,
                            vdim: int = 0, dropout: float = 0.0,
                            bias: bool = True, add_bias_kv: bool = False,
                            add_zero_attn: bool = False, causal: bool = False,
                            num_kv_heads: int = 0, rope: bool = False,
                            rope_theta: float = 10000.0,
                            name: Optional[str] = None, **kw) -> Tensor:
        return self._add(MultiHeadAttention(
            self, self._name("multihead_attention", name), [query, key, value],
            embed_dim, num_heads, kdim, vdim, dropout, bias, add_bias_kv,
            add_zero_attn, causal, num_kv_heads=num_kv_heads, rope=rope,
            rope_theta=rope_theta))

    def transformer_pipeline_stack(self, input: Tensor, num_layers: int,
                                   num_heads: int, ffn_mult: int = 4,
                                   causal: bool = False,
                                   num_microbatches: Optional[int] = None,
                                   name: Optional[str] = None) -> Tensor:
        """L identical transformer blocks with stacked weights; under a
        'pipe' mesh axis the stack runs as a GPipe ring (graph-level pipeline
        parallelism — the reference's NMT chunked-timestep scheme, rnn.h:21-63,
        re-designed for TPU as layer stacking; see ops/pipelined.py)."""
        from flexflow_tpu.ops.pipelined import TransformerPipelineStack

        return self._add(TransformerPipelineStack(
            self, self._name("transformer_pipeline_stack", name), [input],
            num_layers, num_heads, ffn_mult, causal, num_microbatches))

    def reshape(self, input: Tensor, shape: Sequence[int],
                name: Optional[str] = None) -> Tensor:
        return self._add(Reshape(self, self._name("reshape", name), [input], shape))

    def transpose(self, input: Tensor, perm: Sequence[int],
                  name: Optional[str] = None) -> Tensor:
        return self._add(Transpose(self, self._name("transpose", name), [input], perm))

    def reverse(self, input: Tensor, axis: int,
                name: Optional[str] = None) -> Tensor:
        return self._add(Reverse(self, self._name("reverse", name), [input], axis))

    def concat(self, tensors: Sequence[Tensor], axis: int,
               name: Optional[str] = None) -> Tensor:
        return self._add(Concat(self, self._name("concat", name), list(tensors), axis))

    def split(self, input: Tensor, sizes: Union[int, Sequence[int]], axis: int,
              name: Optional[str] = None) -> List[Tensor]:
        if isinstance(sizes, int):
            n = sizes
            d = input.dims[axis]
            assert d % n == 0
            sizes = [d // n] * n
        out = self._add(Split(self, self._name("split", name), [input],
                              sizes, axis))
        return out if isinstance(out, list) else [out]

    def topk(self, input: Tensor, k: int, sorted: bool = True,
             name: Optional[str] = None) -> List[Tensor]:
        out = self._add(TopK(self, self._name("topk", name), [input], k, sorted))
        return out if isinstance(out, list) else [out]

    def gather(self, input: Tensor, index: Tensor, axis: int,
               name: Optional[str] = None) -> Tensor:
        return self._add(Gather(self, self._name("gather", name),
                                [input, index], axis))

    def cast(self, input: Tensor, dtype: DataType,
             name: Optional[str] = None) -> Tensor:
        return self._add(Cast(self, self._name("cast", name), [input], dtype))

    def pad(self, input: Tensor, pads, value: float = 0.0,
            name: Optional[str] = None) -> Tensor:
        return self._add(Pad(self, self._name("pad", name), [input], pads, value))

    def mean(self, input: Tensor, dims: Sequence[int], keepdims: bool = False,
             name: Optional[str] = None) -> Tensor:
        return self._add(Mean(self, self._name("mean", name), [input], dims, keepdims))

    # elementwise unary/binary

    def _unary(self, op_type: OperatorType, x: Tensor, name=None,
               scalar=None) -> Tensor:
        kind = op_type.name[3:].lower()
        return self._add(ElementUnary(self, self._name(kind, name), [x],
                                      op_type, scalar))

    def _binary(self, op_type: OperatorType, a: Tensor, b: Tensor, name=None) -> Tensor:
        kind = op_type.name[3:].lower()
        return self._add(ElementBinary(self, self._name(kind, name), [a, b], op_type))

    def exp(self, x, name=None):
        return self._unary(OperatorType.OP_EXP, x, name)

    def sin(self, x, name=None):
        return self._unary(OperatorType.OP_SIN, x, name)

    def cos(self, x, name=None):
        return self._unary(OperatorType.OP_COS, x, name)

    def relu(self, x, name=None):
        return self._unary(OperatorType.OP_RELU, x, name)

    def sigmoid(self, x, name=None):
        return self._unary(OperatorType.OP_SIGMOID, x, name)

    def tanh(self, x, name=None):
        return self._unary(OperatorType.OP_TANH, x, name)

    def elu(self, x, name=None):
        return self._unary(OperatorType.OP_ELU, x, name)

    def gelu(self, x, name=None):
        return self._unary(OperatorType.OP_GELU, x, name)

    def identity(self, x, name=None):
        return self._unary(OperatorType.OP_IDENTITY, x, name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(OperatorType.OP_POW, x, name, scalar=exponent)

    def rsqrt(self, x, name=None):
        return self._unary(OperatorType.OP_RSQRT, x, name)

    def scalar_multiply(self, x, scalar: float, name=None):
        return self._unary(OperatorType.OP_SCALAR_MULTIPLY, x, name, scalar=scalar)

    def add(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_ADD, a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_SUB, a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_MUL, a, b, name)

    def divide(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_DIV, a, b, name)

    def max(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_MAX, a, b, name)

    def min(self, a, b, name=None):
        return self._binary(OperatorType.OP_EW_MIN, a, b, name)

    # -------------------------------------------------------------- compile

    def tie_weights(self, dst_op: str, dst_weight: str, src_op: str,
                    src_weight: str, transform: str = "same"):
        """Share one stored weight between two ops (reference parity: the
        NMT subsystem's SharedVariable, nmt/rnn.h:37-51, one logical
        weight behind many timestep ops; modern use: tied embedding /
        lm_head). The destination op stops owning storage — its weight is
        resolved from the source at trace time (transform: "same" |
        "transpose"), so gradients from both ops accumulate into the one
        array through autodiff. Call after building both ops, before
        compile()."""
        if transform not in ("same", "transpose"):
            raise ValueError(f"transform must be 'same' or 'transpose', "
                             f"got {transform!r}")
        if getattr(self, "executor", None) is not None:
            raise ValueError(
                "tie_weights must be called before compile(): params and "
                "the jitted step are already built, so a late tie would "
                "be silently ignored by traced programs")
        s, d = self.get_op_by_name(src_op), self.get_op_by_name(dst_op)
        for nm, op in ((src_op, s), (dst_op, d)):
            if op is None:
                raise ValueError(f"tie_weights: no op named {nm!r}")
        specs_s = {w.name: w for w in s.weight_specs()}
        specs_d = {w.name: w for w in d.weight_specs()}
        if src_weight not in specs_s:
            raise ValueError(f"tie_weights: {src_op!r} has no weight "
                             f"{src_weight!r} (has {list(specs_s)})")
        if dst_weight not in specs_d:
            raise ValueError(f"tie_weights: {dst_op!r} has no weight "
                             f"{dst_weight!r} (has {list(specs_d)})")
        shape_s = tuple(specs_s[src_weight].shape)
        if transform == "transpose":
            shape_s = shape_s[::-1]
        if tuple(specs_d[dst_weight].shape) != shape_s:
            raise ValueError(
                f"tie_weights: shape mismatch — {dst_op}.{dst_weight} is "
                f"{tuple(specs_d[dst_weight].shape)} but {src_op}."
                f"{src_weight} {transform} gives {shape_s}")
        if (src_op, src_weight) in self._tied:
            raise ValueError(
                f"tie_weights: source {src_op}.{src_weight} is itself tied "
                f"— chain ties to the original storage instead")
        if (dst_op, dst_weight) in self._tied:
            prev = self._tied[(dst_op, dst_weight)]
            raise ValueError(
                f"tie_weights: {dst_op}.{dst_weight} is already tied to "
                f"{prev[0]}.{prev[1]}")
        if any(src == (dst_op, dst_weight)
               for src in ((v[0], v[1]) for v in self._tied.values())):
            raise ValueError(
                f"tie_weights: {dst_op}.{dst_weight} is the SOURCE of an "
                f"existing tie; it must keep its storage — reverse the tie "
                f"or chain the other ops to the same source")
        self._tied[(dst_op, dst_weight)] = (src_op, src_weight, transform)

    def get_op_by_name(self, name: str) -> Optional[Op]:
        for op in self.ops:
            if op.name == name:
                return op
        return None

    def compile(self, optimizer=None,
                loss_type: Union[LossType, str] = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                metrics: Sequence = (MetricsType.METRICS_ACCURACY,),
                comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
                final_tensor: Optional[Tensor] = None):
        """Resolve strategies -> build mesh -> init sharded params.

        Reference: FFModel::compile (model.cc:1481-1646): optional strategy
        search, per-op create_output_and_partition/create_weights, fusion,
        label tensor, optimizer init.
        """
        cfg = self.config
        self.optimizer = optimizer
        self.loss_type = loss_type_from_name(loss_type)
        self.metric_types = metrics_from_names(metrics)
        self.comp_mode = comp_mode
        # strategy import must land BEFORE the elastic hook: on a relaunch
        # with the original flags the imported file describes the OLD
        # topology, and the hook's mesh-refit re-derivation has to win over
        # it, not be clobbered by it
        if cfg.import_strategy_file:
            cfg.strategies.update(
                load_strategies_from_file(cfg.import_strategy_file))
        # elastic recovery (runtime/elastic.py): with a checkpoint_dir set,
        # compare the newest intact checkpoint's recorded topology against
        # what this process actually has BEFORE the mesh is built — a
        # restart on fewer devices refits the mesh (csim-ranked), re-derives
        # the saved strategy, and preserves the global batch via grad-accum,
        # per cfg.on_topology_change; the later restore then re-shards the
        # saved params onto whatever mesh this compile produces
        self._elastic = None
        if cfg.checkpoint_dir:
            from flexflow_tpu.runtime.elastic import apply_elastic_policy

            self._elastic = apply_elastic_policy(self)
        if cfg.compilation_cache_dir:
            # persistent compilation cache: must be on BEFORE the first
            # trace so the train/serve programs are covered; repeated runs
            # then load executables instead of recompiling
            from flexflow_tpu._env import (compilation_cache_entries,
                                           enable_compilation_cache)
            from flexflow_tpu.logger import fflogger

            if enable_compilation_cache(cfg.compilation_cache_dir):
                fflogger.info(
                    "persistent compilation cache: %s (%d entries)",
                    cfg.compilation_cache_dir,
                    compilation_cache_entries(cfg.compilation_cache_dir))
        self.mesh = make_mesh(cfg.mesh_shape)

        if cfg.search_budget > 0:
            from flexflow_tpu.search.driver import optimize_strategies_multi

            # persistent cost DB: already-keyed op signatures load from
            # disk instead of re-measuring/re-compiling (search/cost_db.py)
            db_path = getattr(cfg, "cost_db_path", "") or None
            measured = None
            if cfg.measure_search_costs == "analyze":
                from flexflow_tpu.search.measure import analyze_op_costs

                measured = analyze_op_costs(
                    self, cfg.mesh_shape,
                    enable_parameter_parallel=cfg.enable_parameter_parallel,
                    enable_attribute_parallel=cfg.enable_attribute_parallel,
                    verbose=cfg.profiling, db_path=db_path)
            elif cfg.measure_search_costs:
                from flexflow_tpu.search.measure import measure_op_costs

                measured = measure_op_costs(
                    self, cfg.mesh_shape,
                    cfg.enable_parameter_parallel,
                    cfg.enable_attribute_parallel,
                    verbose=cfg.profiling, db_path=db_path)
            machine = None
            if cfg.dcn_mesh_shape:
                # two-tier topology: axes listed in dcn_mesh_shape span that
                # many hosts, so their collectives are priced at the DCN tier
                from flexflow_tpu.search.machine import MachineModel

                machine = MachineModel(dcn_axes=dict(cfg.dcn_mesh_shape))
            # multi-objective: time subject to the per-chip HBM cap — when
            # the time-optimal strategy fits (the common case) the relief
            # loop is a no-op and this is exactly the old time-only search
            best = optimize_strategies_multi(self, budget=cfg.search_budget,
                                             alpha=cfg.search_alpha,
                                             machine=machine,
                                             measured=measured)
            cfg.strategies.update(best)
            if cfg.export_strategy_file:
                save_strategies_to_file(cfg.export_strategy_file, cfg.strategies)

        if cfg.strategy_lint != "off":
            # fflint (analysis/): static validation of the now-final
            # strategy table — pure graph+table checks, no tracing. A bad
            # strategy is named HERE (op + pass + rule) instead of
            # surfacing as a mesh-build/XLA error with no line back to
            # the offending axis. The schema pass (text-file round-trip,
            # a tempfile write per run) is file-facing and stays with the
            # CLI/scripts callers — compile validates the in-memory table.
            from flexflow_tpu.analysis import StrategyLintError, analyze
            from flexflow_tpu.logger import fflogger

            report = analyze(self, strategies=cfg.strategies,
                             mesh_shape=cfg.mesh_shape,
                             passes=("legality", "perf"))
            if cfg.strategy_lint == "strict" and report.errors():
                raise StrategyLintError(report)
            report.log(fflogger)
            # stash the footprint pass's per-chip HBM estimate for the
            # accounting ledger's cross-check (runtime/flightrec.py:
            # ff_hbm_lint_estimated_bytes vs the tracked byte ledger) —
            # the lint already computed it, this costs nothing
            rows = report.by_code("hbm-footprint")
            if rows and rows[0].est_bytes:
                self._lint_hbm_estimate = float(rows[0].est_bytes)

        self._final_tensor = final_tensor or self.ops[-1].outputs[0]
        # fused softmax + cross-entropy, the reference semantics: its CE
        # loss kernels consume the Softmax OUTPUT with an identity backward
        # through the softmax (loss_functions.cu grad = probs - one_hot),
        # which equals CE-from-logits. compute_loss applies log_softmax
        # itself, so a graph ending in Softmax must feed the loss its
        # logits INPUT — otherwise training runs on a double softmax with
        # flattened gradients. predict()/generate() still return the
        # softmax output.
        self._loss_tensor = self._final_tensor
        if self.loss_type in (LossType.LOSS_CATEGORICAL_CROSSENTROPY,
                              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY):
            fop = self._final_tensor.owner_op
            from flexflow_tpu.ops.norm import Softmax as _Softmax

            if isinstance(fop, _Softmax) \
                    and fop.axis in (-1, fop.outputs[0].num_dims - 1):
                self._loss_tensor = fop.inputs[0]

        if cfg.perform_fusion:
            # reference: FFModel::apply_fusion after search (model.cc:1538-1593)
            from flexflow_tpu.ops.fused import apply_fusion

            protected = [self._final_tensor, self._loss_tensor] + list(
                getattr(self, "_aux_tensors", ()))
            apply_fusion(self, protected=protected)

        # label tensor shaped like the final op's sample dims (model.cc:1615-1646)
        fdims = self._final_tensor.dims
        if self.loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            self.label_tensor = Tensor(dims=tuple(fdims[:-1]) + (1,),
                                       dtype=DataType.DT_INT32, name="label")
        else:
            self.label_tensor = Tensor(dims=fdims, dtype=DataType.DT_FLOAT,
                                       name="label")

        from flexflow_tpu.parallel.placement import (PlacementExecutor,
                                                     has_placement)

        if has_placement(cfg.strategies, self.mesh.size):
            # some op is placed on a proper device subset: lower via
            # per-group sub-mesh programs (the reference mapper's per-op
            # device_ids, mapper.cc:346-424)
            self.executor = PlacementExecutor(self)
        else:
            self.executor = GraphExecutor(self)
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.executor.init_params(init_key)
        self.bn_state = self.executor.init_state()
        if self.optimizer is not None:
            if getattr(self.config, "fused_optimizer", False):
                self.optimizer = self._maybe_fuse_optimizer(self.optimizer)
            if getattr(cfg, "overlap_grad_sync", False):
                self.optimizer = self._maybe_shard_optimizer(self.optimizer)
            self.opt_state = self.optimizer.init_state(self.params)
            self._train_step = self.executor.make_train_step(
                self.optimizer, self.loss_type, self.metric_types,
                self._loss_tensor)
            if cfg.on_nonfinite != "none":
                from flexflow_tpu.logger import fflogger

                if getattr(self.executor, "jits_per_group", False) \
                        or cfg.grad_accum_steps > 1:
                    fflogger.warning(
                        "on_nonfinite=%r: divergence guard unsupported "
                        "under operator placement / grad accumulation — "
                        "training runs unguarded", cfg.on_nonfinite)
                else:
                    from flexflow_tpu.runtime.resilience import \
                        init_guard_state

                    self._guarded_step = \
                        self.executor.make_guarded_train_step(
                            self.optimizer, self.loss_type,
                            self.metric_types, self._loss_tensor,
                            guard_cfg={
                                "on_nonfinite": cfg.on_nonfinite,
                                "growth_interval":
                                    cfg.loss_scale_growth_interval,
                            })
                    self._guard_state = init_guard_state(cfg.loss_scale)
        self._eval_step = self.executor.make_eval_step(
            self.loss_type, self.metric_types, self._loss_tensor)

        if cfg.taskgraph_file:
            from flexflow_tpu.runtime.profiler import export_sim_taskgraph

            export_sim_taskgraph(self, cfg.taskgraph_file)

        if getattr(cfg, "telemetry", "on") != "off":
            # HBM accounting ledger (runtime/flightrec.py, ISSUE 15):
            # params/opt-state byte rows + the lint footprint
            # cross-check, published as ff_hbm_* gauges at scrape time
            # and embedded in every post-mortem bundle. Registered ONCE
            # per model (a recompile must not duplicate the source),
            # under a per-instance name (two models in one process must
            # not overwrite each other's rows).
            from flexflow_tpu.runtime import flightrec

            led = flightrec.hbm_ledger()
            if self._hbm_registered_on is not led:
                self._hbm_registered_on = led
                led.add_source(self._hbm_source)
            if self._lint_hbm_estimate is not None:
                flightrec.hbm_ledger().set_lint_estimate(
                    self._lint_hbm_estimate)

    def _maybe_fuse_optimizer(self, opt):
        """FFConfig.fused_optimizer: replicated-param strategies (single
        device / pure DP) get the global-flatten FusedUpdate; GSPMD-sharded
        strategies (TP/FSDP) get ShardedFusedUpdate, which flattens each
        device's LOCAL shards inside a shard_map — shard-local, zero
        collectives. Only operator-placement lowering still falls back to
        the per-leaf update (params live on disjoint sub-meshes, so no
        single program sees them all); a leaf whose shape doesn't divide
        its mesh extent also falls back, with the leaf named."""
        from flexflow_tpu.logger import fflogger
        from flexflow_tpu.runtime.optimizer import (FusedUpdate,
                                                    ShardedFusedUpdate)

        if getattr(self.executor, "jits_per_group", False):
            fflogger.warning(
                "fused_optimizer: unsupported under an operator-placement "
                "strategy (params live on disjoint sub-meshes) — using "
                "the per-leaf update")
            return opt
        shardings = (self.executor.param_shardings()
                     if self.mesh is not None and self.mesh.devices.size > 1
                     else {})
        sharded = any(any(e is not None for e in ns.spec)
                      for per_op in shardings.values()
                      for ns in per_op.values())
        if not sharded:
            return FusedUpdate(opt)

        from jax.sharding import PartitionSpec as P

        specs = {}
        for op, ws in self.params.items():
            specs[op] = {}
            for w, arr in ws.items():
                ns = shardings.get(op, {}).get(w)
                spec = ns.spec if ns is not None else P()
                try:
                    ShardedFusedUpdate.local_leaf_size(
                        arr.shape, spec, self.mesh)
                except ValueError as e:
                    fflogger.warning(
                        "fused_optimizer: weight %s/%s: %s — using the "
                        "per-leaf update", op, w, e)
                    return opt
                specs[op][w] = spec
        return ShardedFusedUpdate(opt, self.mesh, specs)

    def _maybe_shard_optimizer(self, opt):
        """FFConfig.overlap_grad_sync: pair the bucketed in-scan gradient
        reduce-scatter with the ZeRO-1 sharded optimizer update
        (runtime/optimizer.py Zero1Update) — each data shard updates its
        slice of params/opt-state from the already-scattered grads, then
        params all-gather once; opt-state HBM divides by the data degree.
        Falls back (reason logged) under operator placement (no single
        program sees every param), under a fused optimizer (its flat
        state layout is already sharded its own way — the in-scan grad
        buckets still apply), or on a mesh with no data axis > 1."""
        from flexflow_tpu.logger import fflogger
        from flexflow_tpu.runtime.optimizer import (FusedUpdate,
                                                    ShardedFusedUpdate,
                                                    Zero1Update)

        if getattr(self.executor, "jits_per_group", False):
            fflogger.warning(
                "overlap_grad_sync: ZeRO-1 update unsupported under an "
                "operator-placement strategy — using the unsharded update")
            return opt
        if isinstance(opt, (FusedUpdate, ShardedFusedUpdate)):
            fflogger.warning(
                "overlap_grad_sync: fused_optimizer already stores flat "
                "state in its own layout — skipping the ZeRO-1 wrap (the "
                "in-scan gradient buckets still apply)")
            return opt
        scatter = self.executor.grad_scatter_shardings()
        if not scatter:
            fflogger.info(
                "overlap_grad_sync: no data axis > 1 on mesh %s — nothing "
                "to scatter over", self.config.mesh_shape)
            return opt
        return Zero1Update(opt, scatter, self.executor.param_shardings())

    # ---------------------------------------------------------- train verbs

    def _stage_batch(self):
        batch = {}
        for dl in self._dataloaders:
            batch[dl.name] = dl.next_batch()
        return batch

    def _reset_dataloaders(self):
        for dl in self._dataloaders:
            dl.reset()

    def init_layers(self):
        """API parity (reference FFModel::init_layers model.cc:1342); params
        are initialized in compile(), so this is a barrier only."""
        jax.block_until_ready(self.params)

    def zero_gradients(self):
        pass  # functional autodiff: gradients are created fresh each step

    def next_batch_all(self):
        self._current_batch = self._stage_batch()

    def forward(self):
        pass  # fused into backward's value_and_grad (see class docstring)

    def backward(self):
        pass  # fused into update()

    def update(self):
        """Run one fused train step on the staged batch."""
        batch = self._current_batch or self._stage_batch()
        self._run_train_step(batch)

    def _run_train_step(self, batch: Dict[str, np.ndarray],
                        inject_nan: bool = False):
        sharded = self.executor.shard_batch(batch)
        self._rng, step_key = jax.random.split(self._rng)
        if self._guarded_step is not None:
            # guarded path (config.on_nonfinite != "none"): same RNG
            # split, bitwise-identical trajectory while finite; non-finite
            # steps leave params/opt state untouched in-graph. inject_nan
            # is the FF_FAULT nan_loss hook (a traced arg — no recompile).
            (self.params, self.opt_state, self.bn_state, loss, mets,
             self._guard_state) = self._guarded_step(
                self.params, self.opt_state, self.bn_state, sharded,
                step_key, self._guard_state, jnp.asarray(bool(inject_nan)))
        else:
            if inject_nan:
                raise RuntimeError(
                    "nan_loss injection needs the in-graph divergence "
                    "guard: set FFConfig.on_nonfinite before compile()")
            (self.params, self.opt_state, self.bn_state, loss, mets) = \
                self._train_step(self.params, self.opt_state, self.bn_state,
                                 sharded, step_key)
        self._step_count += 1
        self._last_loss = loss
        self._last_metrics = mets
        return loss, mets

    def _scan_eligible(self) -> bool:
        """Scanned multi-step training needs one program over one mesh
        (PlacementExecutor jits per sub-mesh group) and every dataset
        device-resident in the pre-batched (num_batches, batch, ...) layout."""
        return (self._train_step is not None
                # the scanned program has no divergence guard; with a
                # guard compiled in, fit must stay per-step or NaN steps
                # would commit silently
                and self._guarded_step is None
                and self._dataloaders
                # unequal loader lengths wrap per-loader on the per-step
                # path; the scanned program has one batch index, so the
                # two paths would diverge — fall back to per-step
                and len({dl.num_batches for dl in self._dataloaders}) == 1
                and not getattr(self.executor, "jits_per_group", False)
                and all(dl._try_stage_on_device() for dl in self._dataloaders))

    def train_scanned(self, n_steps: int):
        """Run `n_steps` training steps as ONE device program (lax.scan over
        the device-resident dataset — executor.make_train_scan). Per-step
        host dispatch disappears; losses/metrics come back stacked, shape
        (n_steps,). Batch order and wrap policy match the per-step path.
        """
        if not self._scan_eligible():  # NB: eligibility check also stages
            # the loaders on device — must run even under python -O
            raise RuntimeError(
                "train_scanned needs compile() with an optimizer, "
                "device-resident dataloaders, and a single-mesh executor")
        if self._train_scan is None:
            self._train_scan = self.executor.make_train_scan(
                self.optimizer, self.loss_type, self.metric_types,
                self._loss_tensor)
        staged = {dl.name: dl._dev_data for dl in self._dataloaders}
        nb = min(dl.num_batches for dl in self._dataloaders)
        start = (self._dataloaders[0].next_index
                 // self._dataloaders[0].batch_size) % nb
        self._rng, scan_key = jax.random.split(self._rng)
        (self.params, self.opt_state, self.bn_state, losses, mets) = \
            self._train_scan(self.params, self.opt_state, self.bn_state,
                             staged, scan_key, start, n_steps)
        for dl in self._dataloaders:  # keep per-step verbs in sync
            dl.next_index = ((start + n_steps) % nb) * dl.batch_size
        self._step_count += n_steps
        self._last_loss = losses[-1]
        self._last_metrics = {k: v[-1] for k, v in mets.items()}
        return losses, mets

    # ---------------------------------------------------------------- fit

    def fit(self, epochs: Optional[int] = None, batch_size: Optional[int] = None,
            callbacks: Sequence = (), verbose: bool = True):
        """Training loop with throughput print (parity: base_model.py:374-436)."""
        assert self._train_step is not None, "compile() with an optimizer first"
        assert self._dataloaders, \
            "no dataloaders attached; create SingleDataLoader(ff, tensor, data)"
        epochs = epochs or self.config.epochs
        bs = batch_size or self.config.batch_size
        if batch_size is not None:
            for dl in self._dataloaders:
                dl.batch_size = batch_size
        num_batches = min(dl.num_batches for dl in self._dataloaders)
        assert num_batches > 0, (
            f"dataset smaller than batch_size "
            f"({min(dl.num_samples for dl in self._dataloaders)} samples < "
            f"{bs}); no full batch to train on")
        # loader preference: device-resident datasets (next_batch is an
        # on-device slice — the reference's ZC-resident design) > native
        # threaded host prefetch (csrc/dataloader.cc) > Python slicing.
        # Eligibility is decided for ALL loaders before any upload, and a
        # failed upload unstages the others, so a mixed or OOM-ing set never
        # strands half-staged copies in HBM.
        native_dl = None
        for dl in self._dataloaders:
            # a pin from a previous fit() (unstage/OOM) is not permanent:
            # re-attempt once per fit; genuine failures just re-fail
            dl._dev_failed = False
        staged = (all(dl.device_eligible() for dl in self._dataloaders)
                  and all(dl._try_stage_on_device()
                          for dl in self._dataloaders))
        if not staged:
            for dl in self._dataloaders:
                dl.unstage()
            from flexflow_tpu.runtime.native_loader import group_loader_for
            native_dl = group_loader_for(self)
            if native_dl is not None:
                num_batches = native_dl.num_batches
        # multi-step scanned epochs (config.scan_steps chunks per dispatch);
        # callbacks only observe epoch boundaries, so chunking inside an
        # epoch is observationally identical
        use_scan = (self.config.scan_steps > 0 and native_dl is None
                    and staged and self._scan_eligible())
        # fault tolerance (runtime/resilience.py): when checkpoint_dir is
        # set, auto-resume from the newest checkpoint (step counter, RNG,
        # dataloader cursors), checkpoint every checkpoint_every steps,
        # and turn SIGTERM (the preemption notice) into checkpoint-at-the-
        # next-step-boundary + graceful stop
        sup = None
        start_epoch = it0 = 0
        if self.config.checkpoint_dir:
            from flexflow_tpu.runtime.resilience import TrainSupervisor

            sup = TrainSupervisor(self)
            if sup.rewind_after and native_dl is not None:
                # the native threaded loader's shuffled cursor cannot seek
                # backwards, so a rewind would replay steps against the
                # WRONG batches — skip-step still protects; rewind needs
                # the deterministic loaders
                from flexflow_tpu.logger import fflogger

                fflogger.warning(
                    "nonfinite_rewind_after: rewind disabled under the "
                    "native dataloader (its cursor cannot rewind); "
                    "non-finite steps are still skipped in-graph")
                sup.rewind_after = 0
            # keep fit's dispatch async: only poll the guard's per-step
            # flag when prompt rewind is requested; otherwise the device-
            # side skip counter reconciles at finalize()
            sup.poll_nonfinite = bool(sup.rewind_after)
            sup.install()
            resumed = sup.resume()
            if resumed:
                start_epoch = min(resumed // num_batches, epochs)
                it0 = resumed % num_batches
            if use_scan and sup._fault_plan().has_step_events(
                    "nan_loss", "hang"):
                # per-step injection can't reach inside a scanned chunk —
                # silently ignoring a scheduled fault would make an
                # operator drill pass vacuously; run per-step instead
                from flexflow_tpu.logger import fflogger

                fflogger.warning(
                    "FF_FAULT schedules nan_loss/hang step events: "
                    "running per-step (scanned chunks bypass injection)")
                use_scan = False
        stopped = False
        warm = None
        # per-step wall breakdown (host_wait / h2d / dispatch / device),
        # reset at the warmup barrier so compile never pollutes it; logged
        # and stored on self.last_step_breakdown at the end of fit
        bd = {"host_wait": 0.0, "h2d": 0.0, "dispatch": 0.0,
              "device": 0.0, "steps": 0}
        # unified telemetry plane (runtime/telemetry.py): each step's
        # measured breakdown becomes a span tree on the "train" track
        # (trace id "step-<n>"), supervisor events (checkpoint publish,
        # rewind, watchdog) land on the same timeline from
        # resilience.py, and step wall time feeds an SLO histogram —
        # one exported trace shows the overlap schedule end to end
        from flexflow_tpu.runtime import flightrec as _flightrec
        from flexflow_tpu.runtime import telemetry as _telemetry

        tm_on = getattr(self.config, "telemetry", "on") != "off"
        # unconditional: configure() is how telemetry="off" reaches the
        # recorder's own gate (the train step-time and checkpoint-stall
        # SLOs window the histograms fit and the supervisor feed)
        _flightrec.configure(self.config)
        if tm_on and getattr(self.config, "metrics_port", 0):
            _telemetry.start_http_server(self.config.metrics_port)
        tm_step_hist = (_telemetry.registry().histogram(
            "ff_train_step_seconds",
            "fit() per-step wall time (host wait + h2d + dispatch)")
            if tm_on else None)
        # host-overlap step engine (runtime/pipeline_loader.py): a worker
        # thread prefetches + commits batches to device ahead of the loop,
        # and a dispatch-ahead ring below keeps up to
        # config.dispatch_ahead steps in flight. Host-resident data only
        # (device-resident loaders already slice on device, so there is
        # nothing to overlap); excluded under per-step guard polling
        # (prompt rewind syncs the loss every step anyway) and per-group
        # placement programs (their batches materialize inside the step).
        use_overlap = (not use_scan and not staged
                       and self.config.prefetch_depth > 0
                       and not getattr(self.executor, "jits_per_group", False)
                       and (sup is None or not sup.poll_nonfinite))
        pipe = None
        ring = collections.deque()  # in-flight step losses (device scalars)

        def _note_warm(first_loss):
            # ONE warmup barrier shared by all loop flavors: block on the
            # first step's own loss scalar — an output of the step
            # program, so it transitively waits on everything the step
            # produced; a second full-params sync was pure redundancy.
            # Excludes compile from the throughput window and resets the
            # breakdown counters.
            nonlocal warm, total
            jax.block_until_ready(first_loss)
            warm = time.time()
            total = 0
            for k in bd:
                bd[k] = 0 if k == "steps" else 0.0
            if pipe is not None:
                pipe.reset_stats()

        for cb in callbacks:
            cb.set_model(self)
            cb.on_train_begin()
        t0 = time.time()
        total = 0
        try:
            for epoch in range(start_epoch, epochs):
                # resuming mid-epoch: loader cursors were just restored —
                # the usual epoch-start reset would rewind them
                resuming = (sup is not None and epoch == start_epoch
                            and it0 > 0)
                for cb in callbacks:
                    cb.on_epoch_begin(epoch)
                self._perf = PerfMetrics()
                if native_dl is not None:
                    # reshuffle + restart prefetch each epoch; a resumed
                    # process's fresh loader sits on its construction-time
                    # permutation, so resuming into epoch >= 1 must also
                    # reset (one reshuffle — the uninterrupted run's exact
                    # permutations are unrecoverable for a shuffled
                    # loader, which is why bitwise resume is scoped to the
                    # deterministic loaders)
                    if epoch > start_epoch or (epoch == start_epoch
                                               and start_epoch > 0):
                        if pipe is not None:
                            # quiesce first: prefetched batches from the
                            # old epoch are discarded, the reset runs with
                            # the worker idle
                            pipe.epoch_break(native_dl.reset)
                        else:
                            native_dl.reset()
                    if resuming:
                        # the native loader's shuffled cursor cannot seek:
                        # discard the already-trained batches (pipe is
                        # still None here — it starts below, after the
                        # skip, so it never prefetches discarded batches)
                        for _ in range(it0):
                            native_dl.next_batch()
                elif not resuming:
                    if pipe is not None:
                        pipe.epoch_break(self._reset_dataloaders)
                    else:
                        self._reset_dataloaders()
                if use_overlap and pipe is None:
                    from flexflow_tpu.runtime.pipeline_loader import \
                        PipelineLoader

                    depth = self.config.prefetch_depth
                    pipe = (PipelineLoader.from_native(native_dl, self,
                                                       depth=depth)
                            if native_dl is not None else
                            PipelineLoader.from_loaders(self, depth=depth))
                    pipe.start()
                    self._pipeline = pipe
                epoch_mets = []  # device scalars; converted once per epoch so
                # the host never blocks mid-epoch (keeps XLA dispatch async)
                if use_scan:
                    it = it0
                    while it < num_batches:
                        if num_batches - it >= self.config.scan_steps:
                            chunk = self.config.scan_steps
                            t_c0 = time.perf_counter()
                            _, smets = self.train_scanned(chunk)
                            if tm_on:
                                # dispatch time of one scanned chunk
                                # (device completion is async; the
                                # epoch_sync span carries the wait)
                                _telemetry.tracer().complete(
                                    "train_scan_chunk", t_c0,
                                    time.perf_counter() - t_c0,
                                    track="train", steps=chunk)
                            epoch_mets.append((smets, bs, chunk))
                        else:
                            # ragged epoch tail: n_steps is static to the
                            # scanned program, so a tail-sized scan would
                            # compile the whole model a second time — the
                            # per-step program is the cheaper spelling
                            chunk = 1
                            _, smets = self._run_train_step(
                                self._stage_batch())
                            epoch_mets.append((smets, bs, 1))
                        total += bs * chunk
                        it += chunk
                        if warm is None:
                            _note_warm(self._last_loss)
                        if sup is not None and sup.after_step():
                            stopped = True
                            break
                else:
                    it = it0
                    while it < num_batches:
                        t_b = time.perf_counter()
                        if pipe is not None:
                            # already sharded + committed by the worker:
                            # this wait is pure "input not ready yet"
                            batch = pipe.get()
                            t_h = t_s = time.perf_counter()
                        else:
                            batch = (native_dl.next_batch()
                                     if native_dl is not None
                                     else self._stage_batch())
                            t_h = time.perf_counter()
                            batch = self.executor.shard_batch(batch)
                            t_s = time.perf_counter()
                        loss, mets = self._run_train_step(
                            batch, inject_nan=(sup is not None
                                               and sup.nan_due()))
                        t_d = time.perf_counter()
                        bd["host_wait"] += t_h - t_b
                        bd["h2d"] += t_s - t_h
                        bd["dispatch"] += t_d - t_s
                        bd["steps"] += 1
                        if tm_on:
                            sid = f"step-{self._step_count}"
                            tr = _telemetry.tracer()
                            tr.complete("train_step", t_b, t_d - t_b,
                                        trace_id=sid, track="train",
                                        step=self._step_count)
                            tr.complete("host_wait", t_b, t_h - t_b,
                                        trace_id=sid, track="train")
                            if t_s > t_h:
                                tr.complete("h2d", t_h, t_s - t_h,
                                            trace_id=sid, track="train")
                            tr.complete("dispatch", t_s, t_d - t_s,
                                        trace_id=sid, track="train")
                            tm_step_hist.observe(t_d - t_b)
                            # train-side SLO tick for unsupervised fits
                            # (the supervisor's after_step ticks when
                            # one is installed): one predicate + one
                            # time compare until a window has elapsed
                            _flightrec.slo_monitor().maybe_evaluate()
                        epoch_mets.append((mets, bs, 1))
                        total += bs
                        if warm is None:
                            _note_warm(loss)
                        elif pipe is not None:
                            # dispatch-ahead ring: block on the OLDEST
                            # in-flight step's loss once more than
                            # config.dispatch_ahead steps are outstanding.
                            # This waits on DEVICE progress (that step was
                            # dispatched dispatch_ahead steps ago), which
                            # is exactly what the supervisor's watchdog
                            # must time — not host dispatch
                            ring.append(loss)
                            if len(ring) > self.config.dispatch_ahead:
                                old = ring.popleft()
                                t_w = time.perf_counter()
                                with (sup.watchdog.arm(
                                        f"step {self._step_count} device "
                                        f"progress",
                                        scale=self.config.dispatch_ahead + 1)
                                      if sup is not None
                                      else contextlib.nullcontext()):
                                    jax.block_until_ready(old)
                                dt_w = time.perf_counter() - t_w
                                bd["device"] += dt_w
                                if tm_on:
                                    _telemetry.tracer().complete(
                                        "device_wait", t_w, dt_w,
                                        track="train")
                        if sup is not None:
                            step_before = self._step_count
                            if sup.after_step():
                                stopped = True
                                break
                            if self._step_count < step_before:
                                # divergence rewind: the supervisor rolled
                                # params/cursors/step back k steps — drop
                                # the discarded steps from this epoch's
                                # accounting and re-run them (a rewind
                                # past the epoch start clamps to it; those
                                # earlier steps re-run inside this epoch)
                                k = step_before - self._step_count
                                drop = min(k, len(epoch_mets))
                                if drop:
                                    del epoch_mets[-drop:]
                                total = max(total - bs * k, 0)
                                # restore the loop invariant
                                # _step_count == epoch_base + it: the
                                # step for index `it` already ran, so the
                                # next index is it + 1 - k
                                it = max(it + 1 - k, 0)
                                continue
                        it += 1
                it0 = 0
                # the epoch-end conversion is fit's big host sync point —
                # it blocks on every step dispatched since the last sync,
                # so the supervisor's watchdog (step_timeout_s) arms here,
                # scaled by the number of steps it waits on
                t_sync = time.perf_counter()
                with (sup.watchdog.arm(f"epoch {epoch} metrics sync",
                                       scale=max(len(epoch_mets), 1))
                      if sup is not None else contextlib.nullcontext()):
                    for mets, bs, n in epoch_mets:
                        # per-step entries hold scalars (n=1); scanned
                        # chunks hold stacked (n,) arrays — np.asarray
                        # unifies both
                        arrs = {k: np.asarray(v) for k, v in mets.items()}
                        for j in range(n):
                            self._perf.update(
                                {k: float(a[j] if a.ndim else a)
                                 for k, a in arrs.items()}, bs)
                dt_sync = time.perf_counter() - t_sync
                bd["device"] += dt_sync
                if tm_on:
                    _telemetry.tracer().complete(
                        "epoch_sync", t_sync, dt_sync, track="train",
                        epoch=epoch, steps=len(epoch_mets))
                ring.clear()  # everything in flight just synced above
                if verbose:
                    print(f"epoch {epoch}: loss={float(self._last_loss):.4f} "
                          + self._perf.report(self.loss_type, self.metric_types))
                if stopped:  # preemption checkpoint written; partial epoch
                    break
                # a callback returning True from on_epoch_end stops training
                # (reference keras/callbacks.py early_stop semantics)
                if any(cb.on_epoch_end(epoch) for cb in callbacks):
                    break
        finally:
            if pipe is not None:
                # quiesce BEFORE the supervisor's final checkpoint: stop()
                # discards prefetched-but-untrained batches and rewinds
                # the loader cursors to the consumed position, so the
                # final save records exactly the synchronous loop's state
                pipe.stop()
                self._pipeline = None
            if native_dl is not None:
                native_dl.close()
            if sup is not None:
                sup.finalize()
        jax.block_until_ready(self.params)
        elapsed = time.time() - (warm or t0)
        if bd["steps"]:
            from flexflow_tpu.logger import fflogger

            wall = max(elapsed, 1e-9)
            if pipe is not None:
                # h2d ran on the worker thread — overlapped with device
                # compute, so it is reported but not part of loop wall
                bd["h2d"] = pipe.stats["h2d_s"]
            self.last_step_breakdown = dict(
                bd, wall_s=wall, overlap=pipe is not None,
                host_wait_fraction=min(bd["host_wait"] / wall, 1.0))
            fflogger.info(
                "fit step breakdown (%d steps, overlap=%s): host_wait "
                "%.1f%% | h2d %.1f%%%s | dispatch %.1f%% | device %.1f%% "
                "of %.3fs wall",
                bd["steps"], pipe is not None,
                100 * bd["host_wait"] / wall, 100 * bd["h2d"] / wall,
                " (worker, overlapped)" if pipe is not None else "",
                100 * bd["dispatch"] / wall, 100 * bd["device"] / wall,
                wall)
        if total and elapsed > 0 and verbose:
            print(f"epochs {epochs}, ELAPSED TIME = {elapsed:.4f}s, "
                  f"THROUGHPUT = {total / elapsed:.2f} samples/s")
        if tm_on:
            # close the simulator feedback loop (ISSUE 19b): compare the
            # search's predicted step time against the observed histogram,
            # publish the ff_csim_* drift gauges, and fold the observation
            # into the cost DB as a telemetry-tagged calib entry
            try:
                from flexflow_tpu.search import cost_db as _cost_db

                _cost_db.export_calibration(
                    self, path=getattr(self.config, "cost_db_path", "")
                    or None)
            except Exception:
                pass  # calibration must never fail a completed fit
        for cb in callbacks:
            cb.on_train_end()
        return self._perf

    def step_breakdown(self, batch: Optional[Dict[str, np.ndarray]] = None,
                       iters: int = 3) -> Dict[str, float]:
        """Per-step compute/collective/epilogue breakdown of the compiled
        train step (runtime/profiler.py step_phase_breakdown): measured
        full-step and optimizer-epilogue wall time, plus the production
        program's collective instruction count/bytes — the observability
        for the in-graph overlap work (is the epilogue actually
        shrinking?). Merges into ``last_step_breakdown`` alongside fit()'s
        host-side numbers and returns the merged dict."""
        from flexflow_tpu.runtime.profiler import step_phase_breakdown

        rows = step_phase_breakdown(self, batch=batch, iters=iters)
        merged = dict(self.last_step_breakdown or {})
        merged.update(rows)
        self.last_step_breakdown = merged
        return merged

    def evaluate(self, batch: Dict[str, np.ndarray]):
        sharded = self.executor.shard_batch(batch)
        loss, mets, logits = self._eval_step(self.params, self.bn_state, sharded)
        loss = float(loss)
        if not np.isfinite(loss):
            # eval already syncs the loss to host — a free divergence
            # signal (counter + log; resilience.py counters)
            from flexflow_tpu.logger import fflogger
            from flexflow_tpu.runtime.resilience import COUNTERS

            COUNTERS["eval_nonfinite"] += 1
            fflogger.warning("evaluate: non-finite loss %r at step %d",
                             loss, self._step_count)
        return loss, {k: float(v) for k, v in mets.items()}, logits

    def predict(self, batch: Dict[str, np.ndarray]):
        """Label-free inference through the forward-only program."""
        if self._predict_fn is None:
            fwd = self.executor.make_forward([self._final_tensor])
            # the placement executor jits per group (its arrays live on
            # different sub-meshes, which one outer jit cannot accept)
            self._predict_fn = fwd if getattr(
                self.executor, "jits_per_group", False) else jax.jit(fwd)
        sharded = self.executor.shard_batch(batch)
        return self._predict_fn(self.params, self.bn_state, sharded)[0]

    def generate(self, tokens, max_new_tokens: int, temperature: float = 0.0,
                 top_k: int = 0, eos_token_id=None, pad_token_id: int = 0,
                 num_beams: int = 1, length_penalty: float = 0.0,
                 prompt_lengths=None, quantize=None,
                 prefill_chunk: int = 0, return_scores: bool = False,
                 seed: int = 0, early_exit: bool = False):
        """KV-cache autoregressive decoding for decoder-only LM graphs
        (runtime/generation.py). tokens: (B, S0) int32 prompts; returns
        (B, S0 + max_new_tokens) int32 with generated tokens in columns
        S0 onward — or, with return_scores=True, a (tokens, scores)
        tuple where scores is (B, max_new_tokens) per-token model
        logprobs for greedy/sampling (pads after eos carry 0.0) and (B,)
        length-penalty-normalized total logp of the chosen beam for beam
        search. prompt_lengths (B,) enables ragged right-padded prompts.
        num_beams > 1 switches to beam search (temperature/top_k ignored
        there; ragged prompts supported via prompt_lengths, same as
        greedy/sampling). length_penalty follows the
        norm score/len**penalty — the default 0.0 means RAW SUM of
        logprobs (length-biased toward short beams; HF-style length
        normalization is length_penalty=1.0). quantize="int8" decodes
        with weight-only int8 (lossy; halves weight HBM traffic vs
        bf16). prefill_chunk=N bounds prefill score memory.
        early_exit=True decodes through a while_loop that stops once
        every row has emitted eos — identical tokens to the full-length
        scan, fewer steps when rows finish early (greedy/sampling only).

        Compilation caching: each distinct (sampling config) keeps a
        Generator, and each distinct (max_new_tokens, ragged,
        prefill_chunk, scores | beam params) + prompt SHAPE traces its
        own XLA program. Programs are LRU-bounded per Generator
        (FF_GEN_PROGRAM_CACHE, default 8) so a long-lived serving
        process sweeping shapes doesn't accumulate compiled programs
        without bound; sweeping sampling configs still grows
        _generators — reuse temperatures/top_k where possible."""
        from flexflow_tpu.runtime.generation import Generator

        # beam search ignores temperature/top_k: key those out so a
        # sampling sweep reuses one Generator (and its compiled programs)
        key = ((0.0, 0, eos_token_id, pad_token_id, quantize)
               if num_beams > 1
               else (temperature, top_k, eos_token_id, pad_token_id,
                     quantize))
        gen = self._generators.get(key)
        if gen is None:
            # construct from the KEYED values (not the raw args): a beam
            # call keys temperature/top_k out, and its cached Generator
            # must behave greedy if a later num_beams=1 call reuses it
            gen = self._generators[key] = Generator(
                self, temperature=key[0], top_k=key[1],
                eos_id=eos_token_id, pad_id=pad_token_id,
                quantize=quantize)
        if num_beams > 1:
            return gen.beam_search(tokens, max_new_tokens, num_beams,
                                   length_penalty,
                                   prefill_chunk=prefill_chunk,
                                   return_scores=return_scores,
                                   prompt_lengths=prompt_lengths)
        return gen(tokens, max_new_tokens, seed=seed,
                   prompt_lengths=prompt_lengths,
                   prefill_chunk=prefill_chunk,
                   return_scores=return_scores, early_exit=early_exit)

    def _hbm_source(self):
        """HBM-ledger row (runtime/flightrec.py): what this model's
        training state holds on device, per subsystem."""
        def _nbytes(tree):
            return sum(int(getattr(a, "nbytes", 0))
                       for a in jax.tree_util.tree_leaves(tree))

        subs = {"params": _nbytes(self.params)}
        if self.opt_state is not None:
            subs["opt_state"] = _nbytes(self.opt_state)
        if self.bn_state:
            subs["bn_state"] = _nbytes(self.bn_state)
        return (self._hbm_name, subs)

    def dump_flight_record(self, directory: Optional[str] = None,
                           **note) -> Optional[str]:
        """Manual post-mortem bundle (runtime/flightrec.py, ISSUE 15):
        synchronously snapshot the recent trace window, metrics
        registry, log ring, HBM ledger, per-engine stats and the
        config/env fingerprint into an atomic, manifest-hashed bundle
        directory; returns its path. ``directory`` overrides
        ``FFConfig.flight_recorder_dir`` (one of the two must be set).
        Returns None when ``FFConfig.telemetry="off"`` — the off
        contract covers manual dumps too."""
        from flexflow_tpu.runtime import flightrec

        # recorder-only configure: re-arming the SLO monitor here would
        # reset live breach state on an operator's dump
        flightrec.recorder().configure(self.config)
        return flightrec.dump("manual", directory=directory,
                              source="model", **note)

    def make_serving_engine(self, **kwargs):
        """Continuous-batching serving engine (runtime/serving.py): one
        fixed-shape slot-decode program + a paged KV cache shared by all
        slots; the host scheduler admits queued prompts into freed slots
        and retires rows on eos/length. A radix prefix cache shares the
        KV pages of identical page-aligned prompt prefixes across
        requests (copy-on-write; on by default), and a draft model
        (``draft_model=`` + ``speculate_k=``) enables speculative
        decoding — token-identical greedy output, several tokens per
        verify dispatch. The quantized serving tier
        (``kv_cache_dtype="int8"|"fp8"|"bf16"`` and
        ``weight_dtype="int8"|"fp8"``) stores KV pages and/or weights
        narrow with in-kernel dequant: 2-4x the tokens per pool byte at
        a documented per-dtype divergence budget (docs/serving.md
        "Quantized tier"). ``host_kv_pages`` adds a pinned host-memory
        tier under the prefix cache (evicted ref-0 pages demote to host
        RAM and promote back on a hit — the shared-prefix corpus
        becomes host-RAM-sized), and ``warmup(prompts)`` drives every
        reachable prefill variant so timed windows never compile.
        Multi-tenant serving (ISSUE 14): per-request
        temperature/top-p/top-k/seed ride ``submit()`` as slot-resident
        state (greedy = temperature 0, bitwise; counter-based seeded
        streams reproduce across slots and failover), sampled requests
        speculate via the rejection-sampled accept rule
        (distribution-identical to the plain sampler), and
        ``adapter_pool_pages > 0`` + ``register_adapter()`` serve
        per-request LoRA adapters from a paged device pool with zero
        recompiles. Knobs default to this model's FFConfig
        (serve_slots, kv_page_size, kv_pages, decode_buckets,
        serve_prefix_cache, host_kv_pages, serve_speculate_k,
        draft_model, kv_cache_dtype, serve_weight_dtype,
        serve_temperature/top_p/top_k, serve_adapter_pool_pages,
        serve_lora_rank); kwargs override per engine (see
        ServingEngine)."""
        from flexflow_tpu.runtime.serving import ServingEngine

        return ServingEngine(self, **kwargs)

    def serve(self, prompts, max_new_tokens: int = 32, **kwargs):
        """One-shot continuous-batching serve: run `prompts` (list of 1-D
        int32 token arrays, any mix of lengths) to completion and return
        (outputs, stats) — outputs[i] is prompt + generated tokens for
        prompts[i] (None for a failed request), stats the engine's
        throughput/latency/occupancy summary. Greedy continuous batching
        is token-identical to per-request generate()."""
        eng = self.make_serving_engine(**kwargs)
        reqs = eng.run(prompts, max_new_tokens=max_new_tokens)
        outs = [r.output if r.state == "done" else None for r in reqs]
        return outs, eng.stats()

    def make_serving_router(self, replicas: int = 2, **kwargs):
        """Fleet serving router (runtime/router.py ServingRouter): N
        continuous-batching replicas of this model, each driven on its
        own thread, with failover (a crashed/hung replica is fenced and
        its work resubmitted to survivors exactly once), per-request
        deadlines, overload shedding (``max_queue`` /
        FFConfig.serve_max_queue) and least-loaded + prefix-affinity
        placement on the replicas' live health counters. ``roles=``
        (or FFConfig.serve_replica_roles) disaggregates the fleet:
        ``prefill`` replicas absorb long-prompt admission and hand the
        finished KV pages off to ``decode`` replicas as a serialized
        page slab — greedy streams stay token-identical, and a dead
        tier degrades to the mixed path. ``replicas`` is only the
        STARTING size: membership is live (``add_replica`` /
        ``remove_replica`` / ``request_preempt`` with exactly-once
        state evacuation), and runtime/autoscale.py's AutoscalePolicy
        can drive it from the SLO monitor's breach signal. Router
        kwargs (``max_queue``, ``health_timeout_s``,
        ``dispatch_backlog``, ``roles``, ``handoff_min_pages``,
        ``start``) are split out; everything else is forwarded to
        every replica's ServingEngine."""
        from flexflow_tpu.runtime.router import ServingRouter

        return ServingRouter(self, replicas=replicas, **kwargs)

    def serve_fleet(self, prompts, max_new_tokens: int = 32,
                    replicas: int = 2,
                    deadline_s: Optional[float] = None, **kwargs):
        """One-shot fleet serve: run `prompts` through a fresh N-replica
        ServingRouter and return (outputs, stats) — outputs[i] is prompt
        + generated tokens for prompts[i], or None for a request that
        failed, expired (``deadline_s``) or was shed; stats is the
        router's fleet ledger (per-replica engine rows included). Greedy
        fleet output is token-identical to single-replica serve() — the
        router moves work, never changes it."""
        router = self.make_serving_router(replicas=replicas, **kwargs)
        try:
            reqs = router.run(prompts, max_new_tokens=max_new_tokens,
                              deadline_s=deadline_s)
            outs = [r.output if r.state == "done" else None for r in reqs]
            stats = router.stats()
        finally:
            router.close()
        return outs, stats

    def generate_seq2seq(self, src_tokens, tgt_prompt=None,
                         max_new_tokens: int = 32, bos_token_id: int = 1,
                         temperature: float = 0.0, top_k: int = 0,
                         eos_token_id: Optional[int] = None,
                         pad_token_id: int = 0, seed: int = 0):
        """Encoder-decoder decoding (runtime/seq2seq_generation.py): the
        encoder runs once on `src_tokens` (B, S_src), cross-attention k/v
        are projected once, and the decoder runs the KV-cached one-program
        token loop starting from `tgt_prompt` (B, T0) — or a BOS column of
        `bos_token_id` when omitted. Returns (B, T0 + max_new_tokens)
        int32. Graph contract and v1 scope: see Seq2SeqGenerator."""
        from flexflow_tpu.runtime.seq2seq_generation import Seq2SeqGenerator

        key = ("s2s", temperature, top_k, eos_token_id, pad_token_id)
        gen = self._generators.get(key)
        if gen is None:
            gen = self._generators[key] = Seq2SeqGenerator(
                self, temperature=temperature, top_k=top_k,
                eos_id=eos_token_id, pad_id=pad_token_id)
        src = np.asarray(src_tokens)
        if tgt_prompt is None:
            tgt_prompt = np.full((src.shape[0], 1), bos_token_id, np.int32)
        return gen(src, tgt_prompt, max_new_tokens, seed=seed)

    # ------------------------------------------------------------ weights IO

    def get_weights(self, op_name: str, weight_name: str = "kernel") -> np.ndarray:
        tie = self._tied.get((op_name, weight_name))
        if tie is not None:
            from flexflow_tpu.runtime.executor import tie_transform

            src_op, src_w, tf = tie
            return np.asarray(tie_transform(
                np.asarray(self.params[src_op][src_w]), tf))
        return np.asarray(self.params[op_name][weight_name])

    def set_weights(self, op_name: str, weight_name: str, value: np.ndarray):
        tie = self._tied.get((op_name, weight_name))
        if tie is not None:
            raise ValueError(
                f"{op_name}.{weight_name} is tied to {tie[0]}.{tie[1]} — "
                f"set the source weight instead")
        shardings = self.executor.param_shardings()
        sh = shardings[op_name][weight_name]
        self.params[op_name][weight_name] = jax.device_put(
            jnp.asarray(value), sh)
        self._params_version += 1  # in-place mutation: bump by hand

    # ------------------------------------------------------------- strategy

    def export_strategies(self, filename: str):
        save_strategies_to_file(filename, self.config.strategies)

    def import_strategies(self, filename: str):
        self.config.strategies.update(load_strategies_from_file(filename))
