"""Tensor handles for the op graph.

A `Tensor` is a symbolic handle: static dims + dtype + owner op — the analog of
the reference's region-backed Tensor (reference: include/tensor.h:27-80) with
Legion regions replaced by jax.Arrays materialized at execution time under a
`NamedSharding`. `Parameter` adds sync type, matching reference
include/tensor.h Parameter.

Dims are logical and ordered the same way as the reference API surface
(e.g. conv tensors are NCHW in user-facing shape); layout for the MXU is XLA's
job, not the graph's.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, TYPE_CHECKING

import numpy as np

from flexflow_tpu.ffconst import DataType, ParameterSyncType, dtype_to_np

if TYPE_CHECKING:
    from flexflow_tpu.ops.base import Op


@dataclasses.dataclass
class Tensor:
    dims: Tuple[int, ...]
    dtype: DataType
    owner_op: Optional["Op"] = None
    owner_idx: int = 0
    name: str = ""

    @property
    def num_dims(self) -> int:
        return len(self.dims)

    @property
    def batch_dim(self) -> int:
        # Reference convention: dim 0 is the sample dim for activations.
        return 0

    def get_shape(self) -> Tuple[int, ...]:
        return self.dims

    def np_dtype(self):
        return dtype_to_np(self.dtype)

    def volume(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def size_bytes(self) -> int:
        return self.volume() * np.dtype(self.np_dtype()).itemsize

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    def __repr__(self):
        owner = self.owner_op.name if self.owner_op is not None else "input"
        return f"Tensor(dims={self.dims}, dtype={self.dtype.name}, owner={owner})"


@dataclasses.dataclass
class Parameter(Tensor):
    """A trainable weight. sync_type chooses the gradient plane; on TPU both
    PS and NCCL collapse into psum emitted by sharded autodiff (reference kept
    them distinct: src/runtime/optimizer.cc:93-358)."""

    sync_type: ParameterSyncType = ParameterSyncType.NONE

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other
