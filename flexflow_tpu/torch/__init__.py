from flexflow_tpu.torch.model import PyTorchModel  # noqa: F401
from flexflow_tpu.torch.fx import torch_to_flexflow  # noqa: F401
