"""PyTorchModel: replay a .ff text IR onto an FFModel.

Reference: python/flexflow/torch/model.py:23-226 — parse each line
(`name, ins:, outs:, OPTYPE, params...`), call the corresponding native
builder method, track tensors by producer name in tensor_dict.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from flexflow_tpu.ffconst import ActiMode, AggrMode, PoolType
from flexflow_tpu.flexflow_type import OpType, int_to_enum, str_to_enum


class PyTorchModel:
    def __init__(self, filename: Optional[str] = None, model=None):
        self.tensor_dict: Dict[str, object] = {}
        self.lines: List[str] = []
        if filename is not None:
            with open(filename) as f:
                self.lines = f.readlines()
        elif model is not None:
            from flexflow_tpu.torch.fx import torch_to_strings

            self.lines = torch_to_strings(model)
        else:
            raise ValueError("need filename or model")

    def _input_key(self, ins: List[str], idx: int) -> str:
        return ins[idx]

    def apply(self, ffmodel, input_tensors: List) -> List:
        output_tensors = []
        input_idx = 0
        kinds: Dict[str, OpType] = {}  # op name -> IR op type (for GETITEM)
        for line in self.lines:
            items = [i.strip() for i in line.strip().split(",")]
            assert len(items) >= 3, f"wrong format: {line!r}"
            op_name = items[0]
            ins = [i for i in (s.strip() for s in items[1].split(":")) if i]
            op_type = str_to_enum(OpType, items[3])
            kinds[op_name] = op_type
            T = self.tensor_dict

            if op_type == OpType.INPUT:
                assert not ins
                T[op_name] = input_tensors[input_idx]
                input_idx += 1
            elif op_type == OpType.OUTPUT:
                output_tensors += [T[i] for i in ins]
            elif op_type == OpType.LINEAR:
                od = int(items[4])
                activ = int_to_enum(ActiMode, int(items[5]))
                bias = bool(int(items[6]))
                T[op_name] = ffmodel.dense(T[ins[0]], od, activation=activ,
                                           use_bias=bias, name=op_name)
            elif op_type == OpType.CONV2D:
                oc, kh, kw, sh, sw, ph, pw = (int(v) for v in items[4:11])
                activ = int_to_enum(ActiMode, int(items[11]))
                groups = int(items[12])
                bias = bool(int(items[13]))
                T[op_name] = ffmodel.conv2d(T[ins[0]], oc, kh, kw, sh, sw,
                                            ph, pw, activation=activ,
                                            groups=groups, use_bias=bias,
                                            name=op_name)
            elif op_type == OpType.POOL2D:
                k, s_, p = int(items[4]), int(items[5]), int(items[6])
                pool = int_to_enum(PoolType, int(items[7]))
                activ = int_to_enum(ActiMode, int(items[8]))
                if k == 0:  # global (adaptive 1x1) pool marker
                    kh, kw = T[ins[0]].dims[2], T[ins[0]].dims[3]
                else:
                    kh = kw = k
                T[op_name] = ffmodel.pool2d(T[ins[0]], kh, kw, s_, s_, p, p,
                                            pool_type=pool, activation=activ,
                                            name=op_name)
            elif op_type == OpType.BATCH_NORM:
                T[op_name] = ffmodel.batch_norm(T[ins[0]], relu=False,
                                                name=op_name)
            elif op_type == OpType.LAYER_NORM:
                T[op_name] = ffmodel.layer_norm(T[ins[0]], name=op_name)
            elif op_type == OpType.DROPOUT:
                T[op_name] = ffmodel.dropout(T[ins[0]], float(items[4]),
                                             name=op_name)
            elif op_type == OpType.RELU:
                T[op_name] = ffmodel.relu(T[ins[0]], name=op_name)
            elif op_type == OpType.SIGMOID:
                T[op_name] = ffmodel.sigmoid(T[ins[0]], name=op_name)
            elif op_type == OpType.TANH:
                T[op_name] = ffmodel.tanh(T[ins[0]], name=op_name)
            elif op_type == OpType.ELU:
                T[op_name] = ffmodel.elu(T[ins[0]], name=op_name)
            elif op_type == OpType.GELU:
                T[op_name] = ffmodel.gelu(T[ins[0]], name=op_name)
            elif op_type == OpType.IDENTITY:
                T[op_name] = T[ins[0]]
            elif op_type == OpType.SOFTMAX:
                T[op_name] = ffmodel.softmax(T[ins[0]], name=op_name)
            elif op_type == OpType.FLAT:
                T[op_name] = ffmodel.flat(T[ins[0]], name=op_name)
            elif op_type == OpType.ADD:
                T[op_name] = ffmodel.add(T[ins[0]], T[ins[1]], name=op_name)
            elif op_type == OpType.SUBTRACT:
                T[op_name] = ffmodel.subtract(T[ins[0]], T[ins[1]], name=op_name)
            elif op_type == OpType.MULTIPLY:
                T[op_name] = ffmodel.multiply(T[ins[0]], T[ins[1]], name=op_name)
            elif op_type == OpType.DIVIDE:
                T[op_name] = ffmodel.divide(T[ins[0]], T[ins[1]], name=op_name)
            elif op_type == OpType.EXP:
                T[op_name] = ffmodel.exp(T[ins[0]], name=op_name)
            elif op_type == OpType.CONCAT:
                axis = int(items[4])
                T[op_name] = ffmodel.concat([T[i] for i in ins], axis,
                                            name=op_name)
            elif op_type == OpType.SPLIT:
                raw = items[4]
                sizes = [int(v) for v in raw.split(":")] if ":" in raw \
                    else int(raw)
                T[op_name] = ffmodel.split(T[ins[0]], sizes, axis=1,
                                           name=op_name)
            elif op_type == OpType.GETITEM:
                idx = int(items[4])
                src = T[ins[0]]
                if isinstance(src, (list, tuple)):
                    T[op_name] = src[idx]
                elif idx == 0 and \
                        kinds.get(ins[0]) == OpType.MULTIHEAD_ATTENTION:
                    # nn.MultiheadAttention returns (output, weights); here
                    # only the output tensor is materialized, so [0] is it.
                    # Restricted to MHA sources: getitem[0] on an ordinary
                    # tensor is real indexing and must not silently alias
                    T[op_name] = src
                else:
                    raise ValueError(
                        f"{op_name}: getitem[{idx}] on {ins[0]} "
                        f"({kinds.get(ins[0])}) is not supported — tensor "
                        f"indexing has no .ff IR lowering")
            elif op_type == OpType.RESHAPE:
                shape = [int(v) for v in items[4].split(":") if v]
                T[op_name] = ffmodel.reshape(T[ins[0]], shape, name=op_name)
            elif op_type == OpType.EMBEDDING:
                num, dim = int(items[4]), int(items[5])
                T[op_name] = ffmodel.embedding(T[ins[0]], num, dim,
                                               AggrMode.AGGR_MODE_NONE,
                                               name=op_name)
            elif op_type == OpType.MULTIHEAD_ATTENTION:
                ed, nh = int(items[4]), int(items[5])
                q = T[ins[0]]
                k = T[ins[1]] if len(ins) > 1 else q
                v = T[ins[2]] if len(ins) > 2 else k
                T[op_name] = ffmodel.multihead_attention(q, k, v, ed, nh,
                                                         name=op_name)
            elif op_type == OpType.MEAN:
                raw = items[4]
                dims = [int(v) for v in raw.split(":") if v] \
                    if raw not in ("None", "") else [1]
                T[op_name] = ffmodel.mean(T[ins[0]], dims, name=op_name)
            else:
                raise AssertionError(f"unhandled op type {op_type}")
        return output_tensors
