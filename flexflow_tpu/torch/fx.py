"""PyTorch-FX exporter: torch.nn.Module -> .ff text IR.

Reference: python/flexflow/torch/fx.py:47-357. Line format (parser at
torch/model.py):

    <name>, <in1>:<in2>:..., <out1>:..., <OPTYPE>[, params...]

Uses torch.fx.symbolic_trace; supported modules/functions mirror the
reference's parse_* table plus LayerNorm/GELU/MultiheadAttention extensions.
"""

from __future__ import annotations

from typing import List

from flexflow_tpu.ffconst import ActiMode, PoolType
from flexflow_tpu.flexflow_type import OpType, enum_to_int, enum_to_str


class Node:
    def __init__(self, name, inedges, outedges):
        self.name = name
        self.inedges = inedges
        self.outedges = outedges


class InputNode(Node):
    def __init__(self, name, users):
        super().__init__(name, None, list(users))


class OutputNode(Node):
    def __init__(self, name, args):
        super().__init__(name, args, None)


class ModuleNode(Node):
    def __init__(self, name, args, users, module):
        super().__init__(name, args, list(users))
        self.module = module


class FunctionNode(Node):
    def __init__(self, name, args, users, target):
        super().__init__(name, args, list(users))
        self.target = target


def _symbolic_trace(model):
    import torch

    assert isinstance(model, torch.nn.Module)
    traced = torch.fx.symbolic_trace(model)
    # tuple unpacks like `out, _ = self.attn(x, x, x)` leave a dead
    # getitem[1] in the trace; drop it before emission
    traced.graph.eliminate_dead_code()
    modules_by_name = dict(model.named_modules())
    graph: List[Node] = []
    for node in traced.graph.nodes:
        if node.op == "call_module":
            graph.append(ModuleNode(node.name, node.args, node.users,
                                    modules_by_name[node.target]))
        elif node.op == "placeholder":
            graph.append(InputNode(node.name, node.users))
        elif node.op == "get_attr":
            pass
        elif node.op in ("call_function", "call_method"):
            graph.append(FunctionNode(node.name, node.args, node.users,
                                      node.target))
        elif node.op == "output":
            graph.append(OutputNode(node.name, node.args))
        else:
            raise AssertionError(f"unhandled fx op {node.op}")
    return graph


def _inoutedge(op_str, inedges, outedges):
    if inedges is not None:
        for e in inedges:
            name = e.name if hasattr(e, "name") else str(e)
            op_str += name + ":"
    op_str += ", "
    if outedges is not None:
        for e in outedges:
            name = e.name if hasattr(e, "name") else str(e)
            op_str += name + ":"
    op_str += ", "
    return op_str


def _tensor_args(node):
    out = []
    for a in node.inedges:
        if isinstance(a, (list, tuple)):  # e.g. torch.cat([x, y], dim)
            out += [e for e in a
                    if hasattr(e, "name") or type(e).__name__ == "Node"]
        elif hasattr(a, "name") or type(a).__name__ == "Node":
            out.append(a)
    return out


def _emit(node) -> str:
    import torch
    import torch.nn as nn

    s = node.name + ", "
    if isinstance(node, InputNode):
        s = _inoutedge(s, None, node.outedges)
        return s + enum_to_str(OpType, OpType.INPUT) + "\n"
    if isinstance(node, OutputNode):
        ins = node.inedges[0] if isinstance(node.inedges[0], (tuple, list)) \
            else node.inedges
        s = _inoutedge(s, list(ins), None)
        return s + enum_to_str(OpType, OpType.OUTPUT) + "\n"

    if isinstance(node, ModuleNode):
        m = node.module
        s = _inoutedge(s, _tensor_args(node), node.outedges)
        if isinstance(m, nn.Linear):
            return s + (f"{enum_to_str(OpType, OpType.LINEAR)}, "
                        f"{m.out_features}, "
                        f"{enum_to_int(ActiMode, ActiMode.AC_MODE_NONE)}, "
                        f"{1 if m.bias is not None else 0}\n")
        if isinstance(m, nn.Conv2d):
            return s + (f"{enum_to_str(OpType, OpType.CONV2D)}, "
                        f"{m.out_channels}, {m.kernel_size[0]}, "
                        f"{m.kernel_size[1]}, {m.stride[0]}, {m.stride[1]}, "
                        f"{m.padding[0]}, {m.padding[1]}, "
                        f"{enum_to_int(ActiMode, ActiMode.AC_MODE_NONE)}, "
                        f"{m.groups}, {1 if m.bias is not None else 0}\n")
        if isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
            pt = PoolType.POOL_MAX if isinstance(m, nn.MaxPool2d) \
                else PoolType.POOL_AVG
            k = m.kernel_size if isinstance(m.kernel_size, int) else m.kernel_size[0]
            st = m.stride if isinstance(m.stride, int) else m.stride[0]
            p = m.padding if isinstance(m.padding, int) else m.padding[0]
            return s + (f"{enum_to_str(OpType, OpType.POOL2D)}, {k}, {st}, "
                        f"{p}, {enum_to_int(PoolType, pt)}, "
                        f"{enum_to_int(ActiMode, ActiMode.AC_MODE_NONE)}\n")
        if isinstance(m, (nn.AdaptiveMaxPool2d, nn.AdaptiveAvgPool2d)):
            pt = PoolType.POOL_MAX if isinstance(m, nn.AdaptiveMaxPool2d) \
                else PoolType.POOL_AVG
            out_sz = m.output_size
            if not isinstance(out_sz, (tuple, list)):
                out_sz = (out_sz, out_sz)
            if any(v != 1 for v in out_sz):
                raise AssertionError(
                    f"adaptive pool with output_size {m.output_size}: only "
                    f"global (1x1) pooling is expressible in the .ff IR")
            # kernel 0 = 'global': the replayer resolves it to the input's
            # spatial size at graph build, where shapes are known (the
            # reference emitted a fixed 3/1/0 here — a latent FIXME,
            # fx.py parse_adaptivepool2d — that breaks small feature maps)
            return s + (f"{enum_to_str(OpType, OpType.POOL2D)}, 0, 1, 0, "
                        f"{enum_to_int(PoolType, pt)}, "
                        f"{enum_to_int(ActiMode, ActiMode.AC_MODE_NONE)}\n")
        if isinstance(m, nn.BatchNorm2d):
            return s + enum_to_str(OpType, OpType.BATCH_NORM) + "\n"
        if isinstance(m, nn.LayerNorm):
            return s + enum_to_str(OpType, OpType.LAYER_NORM) + "\n"
        if isinstance(m, nn.Dropout):
            return s + f"{enum_to_str(OpType, OpType.DROPOUT)}, {m.p}\n"
        if isinstance(m, nn.ReLU):
            return s + enum_to_str(OpType, OpType.RELU) + "\n"
        if isinstance(m, nn.Sigmoid):
            return s + enum_to_str(OpType, OpType.SIGMOID) + "\n"
        if isinstance(m, nn.Tanh):
            return s + enum_to_str(OpType, OpType.TANH) + "\n"
        if isinstance(m, nn.ELU):
            return s + enum_to_str(OpType, OpType.ELU) + "\n"
        if isinstance(m, nn.GELU):
            return s + enum_to_str(OpType, OpType.GELU) + "\n"
        if isinstance(m, nn.Softmax):
            return s + enum_to_str(OpType, OpType.SOFTMAX) + "\n"
        if isinstance(m, nn.Flatten):
            return s + enum_to_str(OpType, OpType.FLAT) + "\n"
        if isinstance(m, nn.Identity):
            return s + enum_to_str(OpType, OpType.IDENTITY) + "\n"
        if isinstance(m, nn.Embedding):
            return s + (f"{enum_to_str(OpType, OpType.EMBEDDING)}, "
                        f"{m.num_embeddings}, {m.embedding_dim}\n")
        if isinstance(m, nn.MultiheadAttention):
            return s + (f"{enum_to_str(OpType, OpType.MULTIHEAD_ATTENTION)}, "
                        f"{m.embed_dim}, {m.num_heads}\n")
        raise AssertionError(f"unsupported module {type(m).__name__}")

    assert isinstance(node, FunctionNode)
    t = node.target
    tname = t if isinstance(t, str) else getattr(t, "__name__", str(t))
    tensor_ins = _tensor_args(node)
    s = _inoutedge(s, tensor_ins, node.outedges)
    if tname in ("add", "add_", "__add__", "iadd"):
        return s + enum_to_str(OpType, OpType.ADD) + "\n"
    if tname in ("sub", "__sub__"):
        return s + enum_to_str(OpType, OpType.SUBTRACT) + "\n"
    if tname in ("mul", "__mul__"):
        return s + enum_to_str(OpType, OpType.MULTIPLY) + "\n"
    if tname in ("truediv", "__truediv__", "div"):
        return s + enum_to_str(OpType, OpType.DIVIDE) + "\n"
    if tname == "relu":
        return s + enum_to_str(OpType, OpType.RELU) + "\n"
    if tname == "gelu":
        return s + enum_to_str(OpType, OpType.GELU) + "\n"
    if tname == "tanh":
        return s + enum_to_str(OpType, OpType.TANH) + "\n"
    if tname == "sigmoid":
        return s + enum_to_str(OpType, OpType.SIGMOID) + "\n"
    if tname == "exp":
        return s + enum_to_str(OpType, OpType.EXP) + "\n"
    if tname == "softmax":
        return s + enum_to_str(OpType, OpType.SOFTMAX) + "\n"
    if tname == "flatten":
        return s + enum_to_str(OpType, OpType.FLAT) + "\n"
    # list-valued params are ':'-joined — the .ff line is comma-delimited, so
    # str(list) would corrupt the format (the reference had this bug latent;
    # its RESHAPE lines already use ':' separators)
    def _colon(v):
        if isinstance(v, (list, tuple)):
            return ":".join(str(x) for x in v)
        return str(v)

    if tname == "cat":
        axis = node.inedges[1] if len(node.inedges) > 1 else 1
        return s + f"{enum_to_str(OpType, OpType.CONCAT)}, {axis}\n"
    if tname in ("split", "chunk"):
        sizes = node.inedges[1]
        return s + f"{enum_to_str(OpType, OpType.SPLIT)}, {_colon(sizes)}\n"
    if tname == "getitem":
        idx = node.inedges[1]
        return s + f"{enum_to_str(OpType, OpType.GETITEM)}, {idx}\n"
    if tname == "reshape" or tname == "view":
        shape = []
        for v in node.inedges[1:]:
            shape += list(v) if isinstance(v, (list, tuple)) else [v]
        return s + (enum_to_str(OpType, OpType.RESHAPE) + ", "
                    + ":".join(str(v) for v in shape) + "\n")
    if tname == "mean":
        dims = node.inedges[1] if len(node.inedges) > 1 else [1]
        if not isinstance(dims, (list, tuple)):
            dims = [dims]
        return s + f"{enum_to_str(OpType, OpType.MEAN)}, {_colon(dims)}\n"
    raise AssertionError(f"unsupported function {tname}")


def torch_to_flexflow(model, filename: str) -> None:
    """Trace and export to a .ff file (reference fx.py:236)."""
    graph = _symbolic_trace(model)
    with open(filename, "w") as f:
        for node in graph:
            f.write(_emit(node))


def torch_to_strings(model) -> List[str]:
    graph = _symbolic_trace(model)
    return [_emit(node) for node in graph]
