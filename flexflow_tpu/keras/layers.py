"""Keras layer classes lowering onto FFModel builder calls.

Reference: python/flexflow/keras/layers/{core,convolutional,pool,merge,
normalization}.py. Shapes are batch-less (batch prepended at compile from
FFConfig.batch_size, as the reference does)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from flexflow_tpu.ffconst import ActiMode, AggrMode, PoolType

_ACTIVATIONS = {
    None: ActiMode.AC_MODE_NONE,
    "linear": ActiMode.AC_MODE_NONE,
    "relu": ActiMode.AC_MODE_RELU,
    "sigmoid": ActiMode.AC_MODE_SIGMOID,
    "tanh": ActiMode.AC_MODE_TANH,
    "gelu": ActiMode.AC_MODE_GELU,
}


class KerasTensor:
    def __init__(self, layer: Optional["Layer"], shape: Tuple[int, ...],
                 inputs: Sequence["KerasTensor"] = ()):
        self.layer = layer
        self.shape = tuple(shape)  # WITHOUT batch dim
        self.inputs = list(inputs)

    def __repr__(self):
        lname = self.layer.name if self.layer else "input"
        return f"KerasTensor({lname}, shape={self.shape})"


class Layer:
    _counters = {}

    def __init__(self, name: Optional[str] = None):
        kind = type(self).__name__.lower()
        if name is None:
            n = Layer._counters.get(kind, 0)
            Layer._counters[kind] = n + 1
            name = f"{kind}_{n}" if n else kind
        self.name = name

    def __call__(self, x):
        xs = x if isinstance(x, (list, tuple)) else [x]
        shape = self.compute_output_shape([t.shape for t in xs])
        return KerasTensor(self, shape, xs)

    def compute_output_shape(self, in_shapes: List[Tuple[int, ...]]):
        raise NotImplementedError

    def build(self, ff, fftensors: List):
        """Lower onto the FFModel builder; returns the output fftensor."""
        raise NotImplementedError

    # -- weight access (reference: layer.get_weights(ffmodel) /
    # layer.set_weights(ffmodel, kernel, bias) over Parameter regions,
    # flexflow_cbinding.py Parameter:14-41; used by the net2net examples) --

    def get_weights(self, ffmodel):
        """Returns this layer's weights as numpy arrays (kernel[, bias])."""
        specs = ffmodel.get_op_by_name(self.name).weight_specs()
        return tuple(ffmodel.get_weights(self.name, s.name) for s in specs)

    def set_weights(self, ffmodel, *arrays):
        specs = ffmodel.get_op_by_name(self.name).weight_specs()
        assert len(arrays) == len(specs), \
            f"{self.name}: expected {len(specs)} arrays, got {len(arrays)}"
        for spec, arr in zip(specs, arrays):
            ffmodel.set_weights(self.name, spec.name, np.asarray(arr))


class InputLayer(Layer):
    def __init__(self, shape=None, dtype="float32", name=None):
        super().__init__(name)
        self.shape = tuple(shape)
        self.dtype = dtype

    def compute_output_shape(self, in_shapes):
        return self.shape


def Input(shape, dtype="float32", name=None) -> KerasTensor:
    layer = InputLayer(shape, dtype, name)
    return KerasTensor(layer, layer.shape, [])


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform", bias_initializer="zeros",
                 input_shape=None, name=None):
        super().__init__(name)
        self.units = units
        self.activation = _ACTIVATIONS[activation]
        self.use_bias = use_bias
        self.input_shape = input_shape

    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0][:-1]) + (self.units,)

    def build(self, ff, xs):
        return ff.dense(xs[0], self.units, self.activation, self.use_bias,
                        name=self.name)


class Conv2D(Layer):
    """NCHW (channels_first), matching the reference Keras clone."""

    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, use_bias=True,
                 groups=1, input_shape=None, name=None):
        super().__init__(name)
        self.filters = filters
        self.kernel = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.strides = (strides, strides) if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding
        self.activation = _ACTIVATIONS[activation]
        self.use_bias = use_bias
        self.groups = groups
        self.input_shape = input_shape

    def _pads(self, in_shape):
        if self.padding == "same":
            return (self.kernel[0] // 2, self.kernel[1] // 2)
        if self.padding == "valid":
            return (0, 0)
        p = self.padding
        return (p, p) if isinstance(p, int) else tuple(p)

    def compute_output_shape(self, in_shapes):
        c, h, w = in_shapes[0]
        ph, pw = self._pads(in_shapes[0])
        oh = (h + 2 * ph - self.kernel[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel[1]) // self.strides[1] + 1
        return (self.filters, oh, ow)

    def build(self, ff, xs):
        ph, pw = self._pads(None)
        return ff.conv2d(xs[0], self.filters, self.kernel[0], self.kernel[1],
                         self.strides[0], self.strides[1], ph, pw,
                         self.activation, self.groups, self.use_bias,
                         name=self.name)


class _Pool2D(Layer):
    pool_type = PoolType.POOL_MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid",
                 name=None):
        super().__init__(name)
        self.pool = (pool_size, pool_size) if isinstance(pool_size, int) \
            else tuple(pool_size)
        strides = strides if strides is not None else self.pool
        self.strides = (strides, strides) if isinstance(strides, int) \
            else tuple(strides)
        self.padding = padding

    def _pads(self):
        return (self.pool[0] // 2, self.pool[1] // 2) \
            if self.padding == "same" else (0, 0)

    def compute_output_shape(self, in_shapes):
        c, h, w = in_shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool[1]) // self.strides[1] + 1
        return (c, oh, ow)

    def build(self, ff, xs):
        ph, pw = self._pads()
        return ff.pool2d(xs[0], self.pool[0], self.pool[1], self.strides[0],
                         self.strides[1], ph, pw, self.pool_type,
                         name=self.name)


class MaxPooling2D(_Pool2D):
    pool_type = PoolType.POOL_MAX


class AveragePooling2D(_Pool2D):
    pool_type = PoolType.POOL_AVG


class Flatten(Layer):
    def compute_output_shape(self, in_shapes):
        return (int(np.prod(in_shapes[0])),)

    def build(self, ff, xs):
        return ff.flat(xs[0], name=self.name)


class Activation(Layer):
    def __init__(self, activation, name=None):
        super().__init__(name)
        self.kind = activation

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        if self.kind == "softmax":
            return ff.softmax(xs[0], name=self.name)
        fn = {"relu": ff.relu, "sigmoid": ff.sigmoid, "tanh": ff.tanh,
              "elu": ff.elu, "gelu": ff.gelu}[self.kind]
        return fn(xs[0], name=self.name)


class Dropout(Layer):
    def __init__(self, rate, seed=0, name=None):
        super().__init__(name)
        self.rate = rate
        self.seed = seed

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        return ff.dropout(xs[0], self.rate, self.seed, name=self.name)


class BatchNormalization(Layer):
    def __init__(self, relu=False, name=None):
        super().__init__(name)
        self.relu = relu

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        return ff.batch_norm(xs[0], relu=self.relu, name=self.name)


class LayerNormalization(Layer):
    def __init__(self, epsilon=1e-5, name=None):
        super().__init__(name)
        self.eps = epsilon

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        return ff.layer_norm(xs[0], self.eps, name=self.name)


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, name=None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def compute_output_shape(self, in_shapes):
        return tuple(in_shapes[0]) + (self.output_dim,)

    def build(self, ff, xs):
        return ff.embedding(xs[0], self.input_dim, self.output_dim,
                            AggrMode.AGGR_MODE_NONE, name=self.name)


class Concatenate(Layer):
    def __init__(self, axis=1, name=None):
        super().__init__(name)
        self.axis = axis

    def compute_output_shape(self, in_shapes):
        ax = self.axis - 1 if self.axis > 0 else len(in_shapes[0]) + self.axis
        out = list(in_shapes[0])
        out[ax] = sum(s[ax] for s in in_shapes)
        return tuple(out)

    def build(self, ff, xs):
        return ff.concat(xs, self.axis, name=self.name)


class _Merge(Layer):
    op = "add"

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        return getattr(ff, self.op)(xs[0], xs[1], name=self.name)


class Add(_Merge):
    op = "add"


class Subtract(_Merge):
    op = "subtract"


class Multiply(_Merge):
    op = "multiply"


def add(tensors, name=None):
    return Add(name=name)(tensors)


def subtract(tensors, name=None):
    return Subtract(name=name)(tensors)


def multiply(tensors, name=None):
    return Multiply(name=name)(tensors)


def concatenate(tensors, axis=1, name=None):
    return Concatenate(axis=axis, name=name)(tensors)


class Reshape(Layer):
    def __init__(self, target_shape, name=None):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def compute_output_shape(self, in_shapes):
        return self.target_shape

    def build(self, ff, xs):
        batch = xs[0].dims[0]
        return ff.reshape(xs[0], (batch,) + self.target_shape, name=self.name)


class Permute(Layer):
    def __init__(self, dims, name=None):
        super().__init__(name)
        self.dims = tuple(dims)  # 1-indexed over non-batch dims (Keras)

    def compute_output_shape(self, in_shapes):
        s = in_shapes[0]
        return tuple(s[d - 1] for d in self.dims)

    def build(self, ff, xs):
        perm = (0,) + tuple(d for d in self.dims)
        return ff.transpose(xs[0], perm, name=self.name)


class MultiHeadAttention(Layer):
    def __init__(self, num_heads, key_dim, name=None):
        super().__init__(name)
        self.num_heads = num_heads
        self.key_dim = key_dim

    def compute_output_shape(self, in_shapes):
        return in_shapes[0]

    def build(self, ff, xs):
        q = xs[0]
        k = xs[1] if len(xs) > 1 else q
        v = xs[2] if len(xs) > 2 else k
        embed = q.dims[-1]
        return ff.multihead_attention(q, k, v, embed, self.num_heads,
                                      name=self.name)
