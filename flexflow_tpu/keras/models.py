"""Keras Model / Sequential lowering onto FFModel.

Reference: python/flexflow/keras/models/base_model.py (516 LoC — compile at
:130 creating the native layers, fit at :196/:374-436 driving dataloaders +
train loop with tracing + THROUGHPUT print), sequential.py, model.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.ffconst import DataType, LossType, MetricsType
from flexflow_tpu.keras.layers import InputLayer, KerasTensor, Layer
from flexflow_tpu.keras.optimizers import get_optimizer
from flexflow_tpu.model import FFModel
from flexflow_tpu.runtime.dataloader import SingleDataLoader
from flexflow_tpu.runtime.loss import loss_type_from_name
from flexflow_tpu.runtime.metrics import metrics_from_names


class BaseModel:
    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.ffconfig = FFConfig.parse_args()
        self.ffmodel: Optional[FFModel] = None
        self._optimizer = None
        self._loss = None
        self._metrics = None
        self._input_kts: List[KerasTensor] = []
        self._output_kt: Optional[KerasTensor] = None
        self._input_fftensors = []

    # -- graph lowering -------------------------------------------------------

    def _topo_layers(self) -> List[KerasTensor]:
        seen, order = set(), []

        def visit(kt: KerasTensor):
            if id(kt) in seen:
                return
            seen.add(id(kt))
            for i in kt.inputs:
                visit(i)
            order.append(kt)

        visit(self._output_kt)
        return order

    def _lower(self):
        cfg = self.ffconfig
        ff = FFModel(cfg)
        self.ffmodel = ff
        kt_to_fft: Dict[int, object] = {}
        for kt in self._topo_layers():
            if isinstance(kt.layer, InputLayer):
                dtype = (DataType.DT_INT32
                         if str(kt.layer.dtype).startswith("int")
                         else DataType.DT_FLOAT)
                t = ff.create_tensor((cfg.batch_size,) + kt.shape,
                                     dtype=dtype, name=kt.layer.name)
                kt_to_fft[id(kt)] = t
            else:
                xs = [kt_to_fft[id(i)] for i in kt.inputs]
                kt_to_fft[id(kt)] = kt.layer.build(ff, xs)
        # bind in DECLARED inputs= order, not graph-traversal order — fit/
        # evaluate/predict zip data arrays against this list positionally
        self._input_fftensors = [kt_to_fft[id(kt)] for kt in self._input_kts]
        self._final_fft = kt_to_fft[id(self._output_kt)]

    # -- keras API ------------------------------------------------------------

    def compile(self, optimizer, loss=None, metrics=None, **kwargs):
        self._optimizer = get_optimizer(optimizer)
        self._loss = loss_type_from_name(loss)
        self._metrics = metrics_from_names(metrics or [])
        self._lower()
        self.ffmodel.compile(self._optimizer, self._loss, self._metrics,
                             final_tensor=self._final_fft)

    def fit(self, x, y, epochs: int = 1, batch_size: Optional[int] = None,
            callbacks: Sequence = (), verbose: bool = True):
        from flexflow_tpu.runtime.dataloader import attach_training_data

        assert self.ffmodel is not None, "compile() first"
        attach_training_data(self.ffmodel, self._input_fftensors, x, y,
                             self._loss)
        return self.ffmodel.fit(epochs=epochs, batch_size=batch_size,
                                callbacks=callbacks, verbose=verbose)

    def evaluate(self, x, y, batch_size: Optional[int] = None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        b = batch_size or self.ffconfig.batch_size
        y = np.asarray(y)
        if self._loss == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY \
                and y.ndim == 1:
            y = y.reshape(-1, 1)
        batch = {t.name.split(":")[0]: np.asarray(a)[:b]
                 for t, a in zip(self._input_fftensors, xs)}
        batch["label"] = y[:b]
        loss, mets, _ = self.ffmodel.evaluate(batch)
        return loss, mets

    def predict(self, x, batch_size: Optional[int] = None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        batch = {t.name.split(":")[0]: np.asarray(a)
                 for t, a in zip(self._input_fftensors, xs)}
        return np.asarray(self.ffmodel.predict(batch))

    def summary(self):
        lines = [f'Model: "{self.name or type(self).__name__}"', "_" * 60]
        if self.ffmodel is not None and self.ffmodel.ops:
            for op in self.ffmodel.ops:
                shape = op.outputs[0].dims if op.outputs else ()
                lines.append(f"{op.name:30s} {type(op).__name__:20s} {shape}")
        elif self._output_kt is not None:  # pre-compile: keras graph walk
            for kt in self._topo_layers():
                lname = kt.layer.name if kt.layer else "input"
                ltype = type(kt.layer).__name__ if kt.layer else "Input"
                lines.append(f"{lname:30s} {ltype:20s} {kt.shape}")
        return "\n".join(lines)

    def get_weights(self, op_name, weight_name="kernel"):
        return self.ffmodel.get_weights(op_name, weight_name)

    def __call__(self, x):
        """Use a built (not necessarily compiled) model as a layer inside
        another model: replay its layer graph onto new inputs (reference
        nested models, e.g. seq_mnist_cnn_nested.py / Sequential.add(Model))."""
        xs = x if isinstance(x, (list, tuple)) else [x]
        assert self._output_kt is not None, "model has no layers"
        assert len(xs) == len(self._input_kts), \
            f"model expects {len(self._input_kts)} inputs, got {len(xs)}"
        mapping = {id(kt): v for kt, v in zip(self._input_kts, xs)}
        for kt in self._topo_layers():
            if id(kt) in mapping:
                continue
            if isinstance(kt.layer, InputLayer):
                raise ValueError("nested model has an unbound input")
            ins = [mapping[id(i)] for i in kt.inputs]
            mapping[id(kt)] = kt.layer(ins if len(ins) > 1 else ins[0])
        return mapping[id(self._output_kt)]


class Model(BaseModel):
    def __init__(self, inputs=None, outputs=None, name=None, **kw):
        super().__init__(name)
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._input_kts = list(ins)
        self._output_kt = outputs if not isinstance(outputs, (list, tuple)) \
            else outputs[0]


class Sequential(BaseModel):
    def __init__(self, layers: Sequence[Layer] = (), name=None):
        super().__init__(name)
        self._layers: List[Layer] = []
        self._kt = None
        for l in layers:
            self.add(l)

    def add(self, layer):
        from flexflow_tpu.keras.layers import Input

        if self._kt is None:
            if isinstance(layer, InputLayer):
                self._kt = Input(layer.shape, layer.dtype, layer.name)
                self._input_kts = [self._kt]
                self._output_kt = self._kt
                return
            dtype = "float32"
            if isinstance(layer, BaseModel):  # nested model as first "layer"
                inner = layer._input_kts[0]
                shape = inner.shape
                if isinstance(inner.layer, InputLayer):
                    dtype = inner.layer.dtype  # e.g. int32 embedding ids
            else:
                shape = getattr(layer, "input_shape", None)
            assert shape is not None, \
                "first layer needs input_shape= (or add an InputLayer)"
            self._kt = Input(shape, dtype)
            self._input_kts = [self._kt]
        self._layers.append(layer)
        self._kt = layer(self._kt)
        self._output_kt = self._kt
