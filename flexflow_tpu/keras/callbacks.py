"""Keras callbacks, including the accuracy-gate callbacks the reference CI
uses as regression tests.

Reference: python/flexflow/keras/callbacks.py:64-90 (VerifyMetrics,
EpochVerifyMetrics), examples/python/keras/accuracy.py:18-24 (ModelAccuracy
targets)."""

from __future__ import annotations

import enum


class ModelAccuracy(enum.Enum):
    MNIST_MLP = 90
    MNIST_CNN = 90
    REUTERS_MLP = 90
    CIFAR10_CNN = 90
    CIFAR10_ALEXNET = 90


class Callback:
    def __init__(self):
        self.model = None

    def set_model(self, model):
        self.model = model

    def on_train_begin(self):
        pass

    def on_train_end(self):
        pass

    def on_epoch_begin(self, epoch):
        pass

    def on_epoch_end(self, epoch):
        pass


def _data_provenance() -> str:
    """Data-source stamp for gate output (VERDICT r4 #9): a gate that
    passed on the synthetic fallback must say so, so it can never be read
    as a reference-parity real-data result."""
    from flexflow_tpu.keras.datasets import loaded_provenance

    return loaded_provenance()


class VerifyMetrics(Callback):
    """Assert at train end that accuracy reached the target."""

    def __init__(self, accuracy: ModelAccuracy):
        super().__init__()
        self.target = accuracy.value

    def on_train_end(self):
        acc = 100.0 * self.model._perf.accuracy
        assert acc >= self.target, \
            f"accuracy {acc:.2f}% below target {self.target}% " \
            f"(data: {_data_provenance()})"
        print(f"[VerifyMetrics] accuracy {acc:.2f}% >= {self.target}% OK "
              f"(data: {_data_provenance()})")


class EpochVerifyMetrics(Callback):
    """Early-stop once the per-epoch accuracy reaches the target; assert at
    the end that it ever did. Returning True from on_epoch_end stops fit()
    (reference callbacks.py early_stop=True)."""

    def __init__(self, accuracy: ModelAccuracy, early_stop: bool = True):
        super().__init__()
        self.target = accuracy.value
        self.early_stop = early_stop
        self.reached = False

    def on_epoch_end(self, epoch):
        acc = 100.0 * self.model._perf.accuracy
        if acc >= self.target:
            self.reached = True
            print(f"[EpochVerifyMetrics] accuracy {acc:.2f}% >= "
                  f"{self.target}% at epoch {epoch} OK "
                  f"(data: {_data_provenance()})")
            return self.early_stop
        return False

    def on_train_end(self):
        assert self.reached, \
            f"accuracy never reached target {self.target}% " \
            f"(data: {_data_provenance()})"


class PrintDebug(Callback):
    def __init__(self, every: int = 1):
        super().__init__()
        self.every = every

    def on_epoch_end(self, epoch):
        if epoch % self.every == 0:
            print(f"[PrintDebug] epoch {epoch}: "
                  f"acc={100.0 * self.model._perf.accuracy:.2f}%")


class ModelCheckpoint(Callback):
    """Periodic checkpointing during fit (the reference's Keras clone has no
    ModelCheckpoint — SURVEY §5.4 marks this as our orbax-backed extension).
    Pair with flexflow_tpu.runtime.checkpoint.auto_resume for preemption
    recovery."""

    def __init__(self, directory: str, every_epochs: int = 1):
        super().__init__()
        self.directory = directory
        self.every_epochs = max(every_epochs, 1)
        self._last_saved_step = None

    def _save(self):
        from flexflow_tpu.runtime.checkpoint import save_checkpoint

        # one numbering scheme: the model's global step counter
        step = self.model._step_count
        if step != self._last_saved_step:
            save_checkpoint(self.model, self.directory, step=step)
            self._last_saved_step = step

    def on_epoch_end(self, epoch):
        if (epoch + 1) % self.every_epochs == 0:
            self._save()

    def on_train_end(self):
        self._save()
