"""Keras optimizer facade (reference: python/flexflow/keras/optimizers.py)."""

from __future__ import annotations

from flexflow_tpu.runtime.optimizer import AdamOptimizer, SGDOptimizer


class SGD:
    def __init__(self, learning_rate=0.01, lr=None, momentum=0.0,
                 nesterov=False, weight_decay=0.0, schedule=None):
        self.inner = SGDOptimizer(lr=lr if lr is not None else learning_rate,
                                  momentum=momentum, nesterov=nesterov,
                                  weight_decay=weight_decay,
                                  schedule=schedule)


class Adam:
    def __init__(self, learning_rate=0.001, lr=None, beta_1=0.9, beta_2=0.999,
                 epsilon=1e-8, weight_decay=0.0, schedule=None):
        self.inner = AdamOptimizer(alpha=lr if lr is not None else learning_rate,
                                   beta1=beta_1, beta2=beta_2, epsilon=epsilon,
                                   weight_decay=weight_decay,
                                   schedule=schedule)


def get_optimizer(opt):
    if isinstance(opt, (SGD, Adam)):
        return opt.inner
    if isinstance(opt, (SGDOptimizer, AdamOptimizer)):
        return opt
    if isinstance(opt, str):
        return {"sgd": SGDOptimizer(lr=0.01),
                "adam": AdamOptimizer(alpha=0.001)}[opt.lower()]
    if isinstance(opt, dict):  # reference accepts dicts from config
        kind = opt.get("type", "sgd").lower()
        if kind == "sgd":
            return SGDOptimizer(lr=float(opt.get("lr", 0.01)),
                                momentum=float(opt.get("momentum", 0.0)),
                                nesterov=bool(opt.get("nesterov", False)))
        return AdamOptimizer(alpha=float(opt.get("lr", 0.001)))
    raise ValueError(f"unknown optimizer {opt!r}")
