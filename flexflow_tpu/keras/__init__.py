"""Keras-clone frontend.

Reference: python/flexflow/keras/ (~3.5k LoC) — a reimplementation of the
Keras Sequential/functional API whose layers lower onto the native FFModel
builder, with optimizers/losses/metrics/initializers/callbacks (including the
VerifyMetrics accuracy-gate callbacks) and bundled datasets.
"""

from flexflow_tpu.keras import layers  # noqa: F401
from flexflow_tpu.keras import models  # noqa: F401
from flexflow_tpu.keras import optimizers  # noqa: F401
from flexflow_tpu.keras import callbacks  # noqa: F401
from flexflow_tpu.keras import datasets  # noqa: F401
from flexflow_tpu.keras.layers import Input  # noqa: F401
from flexflow_tpu.keras.models import Model, Sequential  # noqa: F401
