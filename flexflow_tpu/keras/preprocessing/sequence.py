"""Sequence preprocessing (role parity with the reference's re-export of
keras_preprocessing.sequence, python/flexflow/keras/preprocessing/
sequence.py)."""

from __future__ import annotations

import numpy as np


def pad_sequences(sequences, maxlen=None, dtype="int32", padding="pre",
                  truncating="pre", value=0):
    if maxlen is None:
        maxlen = max((len(s) for s in sequences), default=0)
    out = np.full((len(sequences), maxlen), value, dtype=dtype)
    for i, seq in enumerate(sequences):
        seq = list(seq)
        if len(seq) > maxlen:
            seq = seq[-maxlen:] if truncating == "pre" else seq[:maxlen]
        if not seq:
            continue
        if padding == "pre":
            out[i, -len(seq):] = seq
        else:
            out[i, :len(seq)] = seq
    return out
