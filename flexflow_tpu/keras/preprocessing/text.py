"""Text preprocessing (role parity with the reference's re-export of
keras_preprocessing.text, python/flexflow/keras/preprocessing/text.py —
this environment has no keras_preprocessing, so the subset the examples
use is implemented from scratch)."""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional

import numpy as np

_SPLIT_RE = re.compile(r"[\s!\"#$%&()*+,\-./:;<=>?@\[\\\]^_`{|}~\t\n]+")


def text_to_word_sequence(text: str, lower: bool = True) -> List[str]:
    if lower:
        text = text.lower()
    return [w for w in _SPLIT_RE.split(text) if w]


class Tokenizer:
    """Word-index tokenizer. Index 0 is reserved (padding), matching the
    keras convention; `num_words` caps the vocabulary to the most frequent
    words at transform time."""

    def __init__(self, num_words: Optional[int] = None, lower: bool = True,
                 oov_token: Optional[str] = None):
        self.num_words = num_words
        self.lower = lower
        self.oov_token = oov_token
        self.word_counts: Dict[str, int] = {}
        self.word_index: Dict[str, int] = {}

    def fit_on_texts(self, texts: Iterable[str]):
        for t in texts:
            for w in text_to_word_sequence(t, self.lower):
                self.word_counts[w] = self.word_counts.get(w, 0) + 1
        ranked = sorted(self.word_counts, key=self.word_counts.get,
                        reverse=True)
        offset = 1
        self.word_index = {}
        if self.oov_token is not None:
            self.word_index[self.oov_token] = offset
            offset += 1
        for i, w in enumerate(ranked):
            self.word_index[w] = i + offset

    def texts_to_sequences(self, texts: Iterable[str]) -> List[List[int]]:
        out = []
        oov = self.word_index.get(self.oov_token) \
            if self.oov_token is not None else None
        for t in texts:
            seq = []
            for w in text_to_word_sequence(t, self.lower):
                idx = self.word_index.get(w)
                if idx is not None and (self.num_words is None
                                        or idx < self.num_words):
                    seq.append(idx)
                elif oov is not None:
                    seq.append(oov)
            out.append(seq)
        return out

    def sequences_to_matrix(self, sequences, mode: str = "binary"):
        """Vectorize integer sequences to a (n, num_words) matrix — the
        bag-of-words step the reference's reuters examples run before their
        Dense stack (seq_reuters_mlp.py)."""
        if self.num_words is None:
            raise ValueError("sequences_to_matrix needs num_words")
        n = len(sequences)
        m = np.zeros((n, self.num_words), dtype=np.float32)
        for i, seq in enumerate(sequences):
            seq = np.asarray(seq).reshape(-1)
            seq = seq[(seq >= 0) & (seq < self.num_words)]
            if mode == "binary":
                m[i, seq] = 1.0
            elif mode in ("count", "freq"):
                np.add.at(m[i], seq, 1.0)
                if mode == "freq" and len(seq):
                    m[i] /= len(seq)
            else:
                raise ValueError(f"unknown mode {mode!r}")
        return m
