from flexflow_tpu.keras.preprocessing import sequence, text  # noqa: F401
