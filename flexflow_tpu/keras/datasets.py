"""Bundled datasets (reference: python/flexflow/keras/datasets/{mnist,
cifar10,reuters}.py, which download from network).

This environment has zero egress, so loaders read the standard Keras cache
(~/.keras/datasets/...) when present and otherwise fall back to DETERMINISTIC
SYNTHETIC data with learnable class structure (cluster-per-class), so
training/accuracy-gate tests remain meaningful offline. The fallback is
announced on stderr."""

from __future__ import annotations

import os
import sys

import numpy as np

_KERAS_CACHE = os.path.expanduser("~/.keras/datasets")

# dataset-name -> "real" | "synthetic" for every load_data() call made in
# this process (VERDICT r4 #9: gate results must carry their data source,
# so a synthetic pass can never be mistaken for reference-parity accuracy)
_PROVENANCE: dict = {}


def _record(name: str, source: str):
    _PROVENANCE[name] = source


def loaded_provenance() -> str:
    """'mnist=synthetic,cifar10=real' for all datasets loaded so far, or
    'no-dataset-loaded'. Printed by the accuracy-gate callbacks."""
    if not _PROVENANCE:
        return "no-dataset-loaded"
    return ",".join(f"{k}={v}" for k, v in sorted(_PROVENANCE.items()))


def _limit(pair_train, pair_test):
    """Honor FLEXFLOW_DATASET_LIMIT=N (cap samples per split) so e2e sweeps
    stay fast; full data when unset."""
    n = int(os.environ.get("FLEXFLOW_DATASET_LIMIT", 0))
    if n <= 0:
        return pair_train, pair_test
    (xtr, ytr), (xte, yte) = pair_train, pair_test
    return (xtr[:n], ytr[:n]), (xte[:n], yte[:n])


def _synthetic_images(n, shape, num_classes, seed):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, num_classes, n).astype(np.int32)
    proto = rs.rand(num_classes, *shape).astype(np.float32)
    x = proto[y] * 160 + rs.rand(n, *shape).astype(np.float32) * 95
    return x.astype(np.uint8), y


class digits:
    """REAL data, bundled in the package: the UCI ML optical handwritten
    digits (1797 8x8 grayscale images, sklearn's load_digits source),
    shipped as flexflow_tpu/data/digits.npz (~47 KB). The only real image
    dataset obtainable in this zero-egress image — the accuracy tier's
    real-data gates train on it (reference gates train real MNIST the same
    way, accuracy.py:18-24)."""

    @staticmethod
    def load_data():
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        full = os.path.join(pkg, "data", "digits.npz")
        _record("digits", "real")
        with np.load(full) as f:
            return _limit((f["x_train"], f["y_train"]),
                          (f["x_test"], f["y_test"]))


class mnist:
    @staticmethod
    def load_data(path="mnist.npz"):
        full = os.path.join(_KERAS_CACHE, path)
        if os.path.exists(full):
            _record("mnist", "real")
            with np.load(full, allow_pickle=True) as f:
                return _limit((f["x_train"], f["y_train"]),
                              (f["x_test"], f["y_test"]))
        _record("mnist", "synthetic")
        print("[flexflow_tpu.keras.datasets] mnist cache missing; using "
              "deterministic synthetic data (offline environment)",
              file=sys.stderr)
        xtr, ytr = _synthetic_images(8192, (28, 28), 10, seed=0)
        xte, yte = _synthetic_images(1024, (28, 28), 10, seed=1)
        return _limit((xtr, ytr), (xte, yte))


class cifar10:
    @staticmethod
    def load_data():
        full = os.path.join(_KERAS_CACHE, "cifar-10-batches-py")
        if os.path.exists(full):
            _record("cifar10", "real")
            import pickle

            xs, ys = [], []
            for i in range(1, 6):
                with open(os.path.join(full, f"data_batch_{i}"), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"].reshape(-1, 3, 32, 32))
                ys.append(np.asarray(d[b"labels"]))
            with open(os.path.join(full, "test_batch"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            return _limit((np.concatenate(xs), np.concatenate(ys)),
                          (d[b"data"].reshape(-1, 3, 32, 32),
                           np.asarray(d[b"labels"])))
        _record("cifar10", "synthetic")
        print("[flexflow_tpu.keras.datasets] cifar10 cache missing; using "
              "deterministic synthetic data (offline environment)",
              file=sys.stderr)
        xtr, ytr = _synthetic_images(8192, (3, 32, 32), 10, seed=2)
        xte, yte = _synthetic_images(1024, (3, 32, 32), 10, seed=3)
        return _limit((xtr, ytr), (xte, yte))


class reuters:
    @staticmethod
    def load_data(num_words=1000, maxlen=200, test_split=0.2):
        full = os.path.join(_KERAS_CACHE, "reuters.npz")
        if os.path.exists(full):
            _record("reuters", "real")
            with np.load(full, allow_pickle=True) as f:
                xs, ys = f["x"], f["y"]
            xs = [[w for w in seq if w < num_words] for seq in xs]
            n_test = int(len(xs) * test_split)
            return _limit((xs[n_test:], ys[n_test:].astype(np.int32)),
                          (xs[:n_test], ys[:n_test].astype(np.int32)))
        _record("reuters", "synthetic")
        print("[flexflow_tpu.keras.datasets] reuters: synthetic fallback",
              file=sys.stderr)
        rs = np.random.RandomState(4)
        n, classes = 4096, 46
        y = rs.randint(0, classes, n).astype(np.int32)
        x = rs.randint(1, num_words, (n, maxlen)).astype(np.int32)
        # make it learnable: class-dependent token bias
        x[:, 0] = y % num_words
        return _limit((x[: n // 2], y[: n // 2]), (x[n // 2:], y[n // 2:]))
