"""Framework-wide enums.

Mirrors the reference's constant vocabulary (reference: include/ffconst.h:1-130,
TASO-aligned OperatorType) so strategy files, importers, and user code can use
the same names. Values are Python enums, not ABI-pinned ints, except where the
reference's numeric values leak into file formats (none do — strategy files key
by op *name*, reference: src/runtime/strategy.cc:95-148).
"""

import enum


class ActiMode(enum.Enum):
    AC_MODE_NONE = 10
    AC_MODE_RELU = 11
    AC_MODE_SIGMOID = 12
    AC_MODE_TANH = 13
    AC_MODE_GELU = 14


class AggrMode(enum.Enum):
    AGGR_MODE_NONE = 20
    AGGR_MODE_SUM = 21
    AGGR_MODE_AVG = 22


class PoolType(enum.Enum):
    POOL_MAX = 30
    POOL_AVG = 31


class DataType(enum.Enum):
    DT_FLOAT = 40
    DT_DOUBLE = 41
    DT_INT32 = 42
    DT_INT64 = 43
    DT_BOOLEAN = 44
    DT_HALF = 45
    DT_BFLOAT16 = 46
    DT_NONE = 49


class LossType(enum.Enum):
    LOSS_CATEGORICAL_CROSSENTROPY = 50
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53
    LOSS_IDENTITY = 54


class CompMode(enum.Enum):
    COMP_MODE_TRAINING = 70
    COMP_MODE_INFERENCE = 71


class ParameterSyncType(enum.Enum):
    NONE = 80
    PS = 81
    NCCL = 82  # kept for API parity; lowers to XLA all-reduce (psum) on TPU


class MetricsType(enum.Enum):
    METRICS_ACCURACY = 1001
    METRICS_CATEGORICAL_CROSSENTROPY = 1002
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004
    METRICS_MEAN_SQUARED_ERROR = 1008
    METRICS_ROOT_MEAN_SQUARED_ERROR = 1016
    METRICS_MEAN_ABSOLUTE_ERROR = 1032


class OperatorType(enum.Enum):
    """Op vocabulary (reference: include/ffconst.h OperatorType, TASO-aligned)."""

    OP_INPUT = enum.auto()
    OP_WEIGHT = enum.auto()
    OP_NOOP = enum.auto()
    OP_CONV2D = enum.auto()
    OP_DROPOUT = enum.auto()
    OP_LINEAR = enum.auto()
    OP_BATCHMATMUL = enum.auto()
    OP_POOL2D = enum.auto()
    OP_RELU = enum.auto()
    OP_SIGMOID = enum.auto()
    OP_TANH = enum.auto()
    OP_ELU = enum.auto()
    OP_GELU = enum.auto()
    OP_FLAT = enum.auto()
    OP_SOFTMAX = enum.auto()
    OP_BATCHNORM = enum.auto()
    OP_LAYERNORM = enum.auto()
    OP_RMSNORM = enum.auto()
    OP_CONCAT = enum.auto()
    OP_SPLIT = enum.auto()
    OP_EMBEDDING = enum.auto()
    OP_EW_ADD = enum.auto()
    OP_EW_MUL = enum.auto()
    OP_EW_SUB = enum.auto()
    OP_EW_DIV = enum.auto()
    OP_EW_MAX = enum.auto()
    OP_EW_MIN = enum.auto()
    OP_SCALAR_MULTIPLY = enum.auto()
    OP_EXP = enum.auto()
    OP_SIN = enum.auto()
    OP_COS = enum.auto()
    OP_POW = enum.auto()
    OP_RSQRT = enum.auto()
    OP_IDENTITY = enum.auto()
    OP_RESHAPE = enum.auto()
    OP_REVERSE = enum.auto()
    OP_TRANSPOSE = enum.auto()
    OP_TOPK = enum.auto()
    OP_MULTIHEAD_ATTENTION = enum.auto()
    OP_ATTENTION = enum.auto()  # modern fused (flash/ring) attention
    OP_CAST = enum.auto()
    OP_PAD = enum.auto()
    OP_MEAN = enum.auto()
    OP_REDUCE_SUM = enum.auto()
    OP_FUSED = enum.auto()
    OP_LSTM = enum.auto()
    OP_GRU = enum.auto()
    OP_RNN = enum.auto()
    OP_MOE = enum.auto()  # mixture-of-experts (net-new vs reference)
    OP_GATHER = enum.auto()
    OP_AGG_SPEC = enum.auto()
    OP_GROUP_BY = enum.auto()
    OP_SLICE = enum.auto()
    OP_SQUEEZE = enum.auto()
    OP_UNSQUEEZE = enum.auto()
    OP_MAXIMUM = enum.auto()
    OP_MINIMUM = enum.auto()
    OP_SIGMOID_SILU_MULTI = enum.auto()
    OP_ROTARY_EMBEDDING = enum.auto()


# --- dtype lowering ---------------------------------------------------------

import numpy as _np  # noqa: E402


_DTYPE_TO_NP = {
    DataType.DT_FLOAT: _np.float32,
    DataType.DT_DOUBLE: _np.float64,
    DataType.DT_INT32: _np.int32,
    DataType.DT_INT64: _np.int64,
    DataType.DT_BOOLEAN: _np.bool_,
    DataType.DT_HALF: _np.float16,
}


def dtype_to_np(dt: DataType):
    if dt == DataType.DT_BFLOAT16:
        import jax.numpy as jnp

        return jnp.bfloat16
    return _DTYPE_TO_NP[dt]


def np_to_dtype(np_dtype) -> DataType:
    import jax.numpy as jnp

    d = _np.dtype(np_dtype) if np_dtype != jnp.bfloat16 else np_dtype
    if d == jnp.bfloat16:
        return DataType.DT_BFLOAT16
    for k, v in _DTYPE_TO_NP.items():
        if _np.dtype(v) == d:
            return k
    raise ValueError(f"unsupported numpy dtype {np_dtype}")
