"""Autoregressive generation with a static-shape KV cache.

Reference parity + extension: the reference's inference story is
CompMode::COMP_MODE_INFERENCE (include/ffconst.h:1-130) — the training
graph run forward-only, re-attending the whole prefix at every step
(src/ops/attention.cu keeps full-sequence descriptors). This module is the
TPU-native modern path: ONE jitted program performs prefill + a
`lax.scan` decode loop over a fixed-shape KV cache, so every decode step
is the same compiled XLA program (no retracing, no dynamic shapes) and
the host dispatches once per generate() call, not once per token.

Design notes:
  * The graph is validated up front: only ops whose forward is
    per-position (dense/norm/elementwise/embedding/...) plus causal
    self-attention are allowed, so a graph that silently mixes positions
    (conv, pooling, LSTM, concat on seq, ...) is rejected with the op
    name instead of generating garbage.
  * The KV cache stores PRE-broadcast kv heads ((B, L, KVH, Dh)), so
    grouped-query attention shrinks cache HBM by heads/kv_heads — the
    reason GQA exists (models/llama.py).
  * Sampling: greedy (temperature=0), temperature, optional top-k.
    After `eos_id` is emitted a row keeps emitting `pad_id`.
  * Sharding: the decode program runs under the model's mesh via jit;
    params keep their training shardings (head-sharded TP decodes with
    per-shard caches by GSPMD propagation from the weight shardings).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.ffconst import DataType, OperatorType
from flexflow_tpu.ops.attention import MultiHeadAttention
from flexflow_tpu.ops.base import InputOp
from flexflow_tpu.runtime.executor import resolve_tied_params

# ops whose forward treats every (batch, position) independently — safe to
# run on a (B, 1, ...) decode slice exactly as on the full sequence
_DECODE_SAFE = {
    OperatorType.OP_LINEAR,
    OperatorType.OP_EMBEDDING,
    OperatorType.OP_LAYERNORM,
    OperatorType.OP_RMSNORM,
    OperatorType.OP_DROPOUT,   # inference: identity
    OperatorType.OP_CAST,
    OperatorType.OP_SCALAR_MULTIPLY,
    OperatorType.OP_IDENTITY,
    OperatorType.OP_EXP,
    OperatorType.OP_SIN,
    OperatorType.OP_COS,
    OperatorType.OP_POW,
    OperatorType.OP_RSQRT,
    OperatorType.OP_RELU,
    OperatorType.OP_SIGMOID,
    OperatorType.OP_TANH,
    OperatorType.OP_ELU,
    OperatorType.OP_GELU,
    OperatorType.OP_EW_ADD,
    OperatorType.OP_EW_MUL,
    OperatorType.OP_EW_SUB,
    OperatorType.OP_EW_DIV,
    OperatorType.OP_EW_MAX,
    OperatorType.OP_EW_MIN,
    # MoE routes each token independently (router logits -> top-k expert
    # FFNs); the inference walk overrides capacity to the slab's token
    # count, which guarantees ZERO drops (a token never picks the same
    # expert twice) — standard inference semantics for capacity-trained
    # MoE, and the row-independence guarantee decode promises
    OperatorType.OP_MOE,
}


class Generator:
    """Compiles generate() programs for a decoder-only LM built on FFModel.

    Build once per model (after compile()); each (prompt shape,
    max_new_tokens) pair jits its own program, cached on this object.
    """

    def __init__(self, model, temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 quantize: Optional[str] = None):
        if quantize not in (None, "int8", "fp8"):
            raise ValueError(f"quantize must be None, 'int8' or 'fp8', "
                             f"got {quantize!r}")
        if quantize == "fp8" and getattr(jnp, "float8_e4m3fn", None) is None:
            raise ValueError(
                "quantize='fp8' needs a jax build with jnp.float8_e4m3fn;"
                " this build lacks it — use 'int8'")
        self.model = model
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.quantize = quantize
        # int8 cache: see _quantized_params for the validity rule
        self._qparams = None
        self._qparams_key = None
        self._q_refs = None
        # per-generator weight override (rolling deploy hot-swap): when
        # set, decode programs read these params instead of model.params
        # — same tree structure/shapes/dtypes, so warm programs never
        # retrace. None = serve the shared model weights.
        self._params_override = None
        self._override_version = 0
        # compiled decode programs, LRU-bounded (FF_GEN_PROGRAM_CACHE,
        # default 8): a long-lived serving process sweeping
        # max_new_tokens/prompt shapes must not accumulate XLA programs
        # (and their device buffers) for the life of the model
        import collections

        self._jitted: Dict = collections.OrderedDict()

        if getattr(model.executor, "jits_per_group", False):
            raise NotImplementedError(
                "generate() is unsupported under an operator-placement "
                "strategy (params live on disjoint sub-meshes; one decode "
                "program cannot span them) — compile with a non-placement "
                "strategy for generation")
        input_ops = [op for op in model.ops if isinstance(op, InputOp)]
        tok_inputs = [op for op in input_ops
                      if op.outputs[0].dtype in (DataType.DT_INT32,
                                                 DataType.DT_INT64)]
        if len(input_ops) != 1 or not tok_inputs:
            kinds = ", ".join(
                f"{op.name}:{op.outputs[0].dtype.name}" for op in input_ops)
            raise ValueError(
                "generate() needs a decoder-only LM with exactly one "
                f"integer token input; this graph has [{kinds}]")
        self.token_input = tok_inputs[0]
        self.attn_ops = []
        for op in model.ops:
            if isinstance(op, InputOp):
                continue
            if isinstance(op, MultiHeadAttention):
                if not op.causal:
                    raise ValueError(
                        f"{op.name}: generate() requires causal attention")
                if not (op.inputs[0] is op.inputs[1] is op.inputs[2]):
                    raise ValueError(
                        f"{op.name}: generate() supports self-attention "
                        "only (q, k, v must be the same tensor)")
                self.attn_ops.append(op)
            elif op.op_type == OperatorType.OP_SOFTMAX:
                ax = op.axis % op.outputs[0].num_dims
                if ax != op.outputs[0].num_dims - 1:
                    raise ValueError(
                        f"{op.name}: softmax over a non-feature axis mixes "
                        "positions; not decodable")
            elif op.op_type not in _DECODE_SAFE:
                raise ValueError(
                    f"{op.name} ({op.op_type.name}) mixes sequence "
                    "positions or is unsupported in the KV-cache decode "
                    "path; generate() supports transformer decoder graphs")
        if not self.attn_ops:
            raise ValueError("graph has no attention ops; nothing to cache")
        # topo index of the last attention op: beyond it every op is
        # per-position, so the prefill tail (lm_head included) can run on
        # the final position only instead of the whole prompt
        self._last_attn_idx = max(i for i, op in enumerate(model.ops)
                                  if op in self.attn_ops)

    # ---- weight-only quantization (int8 / fp8) -----------------------------

    def _quantized_params(self):
        """Weight-only quantization, dtype-parameterized (``self.
        quantize`` = 'int8' or 'fp8'): every float weight with >= 2 dims
        stores as {"q": int8|float8_e4m3fn, "s": f32 per-OUTPUT-CHANNEL
        scale}; dequant happens per-use inside the jitted decode program
        (the narrow->compute convert fuses into the consuming matmul, so
        the weight read from HBM — the decode bottleneck — is the
        quantized bytes: half of bf16, a quarter of f32). Scales vary
        over every dim EXCEPT the leading (contraction-side) axis —
        finer than per-tensor on every weight and finer than the old
        per-last-dim scheme on 3-D attention weights (wq (in, H, Dh)
        gets an (H, Dh) scale grid instead of sharing one scale across
        heads); granularity is unconstrained for correctness because the
        weight is dequantized before the matmul consumes it. 1-D weights
        (norm scales, biases) stay exact. Lossy by design: logits shift
        slightly vs full precision — tests/test_quantized_serving.py
        pins per-channel strictly no worse than a per-tensor baseline on
        every zoo layer."""
        import weakref

        # validity = version (bumped by the params setter / set_weights)
        # AND leaf identity (catches raw in-place `ff.params[op][w] = x`
        # mutation) AND liveness of the recorded leaves (a dead weakref
        # means an id could have been recycled, so ids stop being
        # authoritative — rebuild)
        src = self._source_params()
        leaves = jax.tree_util.tree_leaves(src)
        try:
            refs = tuple(weakref.ref(w) for w in leaves)
        except TypeError:
            # non-weakref-able leaf: liveness is unverifiable, so ids are
            # never authoritative — disable caching rather than risk a
            # recycled-id stale hit
            refs = None
        key = (self.model._params_version, self._override_version,
               tuple(map(id, leaves)))
        if (self._qparams is not None and self._qparams_key == key
                and self._q_refs is not None
                and all(r() is not None for r in self._q_refs)):
            return self._qparams
        if self.quantize == "fp8":
            qdtype = jnp.float8_e4m3fn
            qmax = float(jnp.finfo(qdtype).max)
        else:
            qdtype, qmax = jnp.int8, 127.0
        out = {}
        for op_name, ws in src.items():
            q_ws = {}
            for w_name, w in ws.items():
                if w.ndim >= 2 and jnp.issubdtype(w.dtype, jnp.floating):
                    wf = jnp.asarray(w, jnp.float32)
                    scale = jnp.max(jnp.abs(wf), axis=0,
                                    keepdims=True) / qmax
                    scale = jnp.maximum(scale, 1e-12)
                    # clip BEFORE the cast: an fp8 overflow cast is nan,
                    # not saturation
                    q = jnp.clip(wf / scale, -qmax, qmax)
                    if qdtype == jnp.int8:
                        q = jnp.round(q)
                    q_ws[w_name] = {"q": q.astype(qdtype), "s": scale}
                else:
                    q_ws[w_name] = w
            out[op_name] = q_ws
        self._qparams = out
        self._qparams_key = key
        self._q_refs = refs
        return out

    @staticmethod
    def _deq(v, cdtype):
        if isinstance(v, dict) and "q" in v:
            return (v["q"].astype(jnp.float32) * v["s"]).astype(cdtype)
        return v

    # ---- graph walks -------------------------------------------------------

    def _compute_dtype(self):
        if self.model.config.compute_dtype == "bfloat16":
            return jnp.bfloat16
        return jnp.float32

    def _walk(self, params, state, tokens, caches, pos, last_only=False,
              rope_pos=None, row_lengths=None, prompt_len=None,
              chunk_start=None, skip_tail=False, gather_last=False,
              paged=None, lora=None):
        """Interpret the graph on a (B, S) token slab. pos=None means
        prefill (positions 0..S-1, fills cache); otherwise S == 1 and pos
        is the traced cache slot of the token. last_only=True narrows the
        prefill tail: past the last attention op every op is per-position
        (validated in __init__), so only the final position flows through
        the lm_head — O(1/S) of its FLOPs and no (B, S, V) logits
        materialization; with `row_lengths` (ragged right-padded prompts)
        the tail gathers each row's own last valid position instead of
        column -1, and decode steps get per-row RoPE positions + a pad-
        slot cache mask (see MultiHeadAttention.decode_forward)."""
        bf16 = self._compute_dtype() == jnp.bfloat16

        def to_compute(a):
            if bf16 and a.dtype == jnp.float32:
                return a.astype(jnp.bfloat16)
            return a

        s_full = tokens.shape[1]
        vals = {self.token_input.outputs[0]: tokens}
        new_caches = {}
        for idx, op in enumerate(self.model.ops):
            if isinstance(op, InputOp):
                continue
            if skip_tail and idx > self._last_attn_idx:
                # non-final prefill chunk: only the caches matter; the
                # post-attention tail (final norm + lm_head) is unused
                return None, new_caches
            xs = [vals[t] for t in op.inputs]
            if (last_only and pos is None and idx > self._last_attn_idx
                    and s_full > 1):
                if row_lengths is None:
                    xs = [x[:, -1:] if (x.ndim >= 2 and x.shape[1] == s_full)
                          else x for x in xs]
                else:
                    last = (row_lengths - 1)[:, None]

                    def take_last(x):
                        if not (x.ndim >= 2 and x.shape[1] == s_full):
                            return x
                        ix = last.reshape((-1, 1) + (1,) * (x.ndim - 2))
                        ix = jnp.broadcast_to(
                            ix, (x.shape[0], 1) + x.shape[2:])
                        return jnp.take_along_axis(x, ix, axis=1)

                    xs = [take_last(x) for x in xs]
            if self.quantize:
                cdtype = self._compute_dtype()
                deq = lambda v: self._deq(v, cdtype)
                p = {k: deq(v) for k, v in params.get(op.name, {}).items()}
                p = resolve_tied_params(self.model, params, op.name, p,
                                        leaf=deq)
            else:
                p = resolve_tied_params(self.model, params, op.name,
                                        params.get(op.name, {}))
            if bf16:
                p = {k: to_compute(v) for k, v in p.items()}
            with jax.named_scope(op.name):
                if isinstance(op, MultiHeadAttention):
                    cache = caches[op.name]
                    if paged is not None:
                        # continuous-batching slot decode over the paged
                        # pool (runtime/serving.py): per-slot positions,
                        # page-table gather instead of a contiguous cache.
                        # A (B, S>1) slab is the speculative-decode verify
                        # pass: write_pos is (B, S) per-position. "impl"
                        # routes the attention body (einsum page-gather
                        # oracle vs the Pallas paged kernel) per engine.
                        if tokens.shape[1] > 1:
                            out, nc = op.paged_verify_forward(
                                p, xs, cache, paged["page_table"],
                                paged["write_pos"], paged["rope_pos"],
                                paged["row_len"], paged["prompt_pad"],
                                impl=paged.get("impl"))
                        else:
                            out, nc = op.paged_decode_forward(
                                p, xs, cache, paged["page_table"],
                                paged["write_pos"], paged["rope_pos"],
                                paged["row_len"], paged["prompt_pad"],
                                impl=paged.get("impl"))
                    elif pos is None:
                        if gather_last:
                            # ragged chunked prefill: read-only query of
                            # each row's last prompt position against the
                            # chunk-filled cache
                            out, nc = op.query_forward(
                                p, xs, cache, rope_pos=row_lengths - 1,
                                row_lengths=row_lengths)
                        elif chunk_start is not None:
                            out, nc = op.chunk_forward(p, xs, cache,
                                                       chunk_start)
                        else:
                            out, nc = op.prefill_forward(p, xs, cache)
                    else:
                        out, nc = op.decode_forward(
                            p, xs, cache, pos, rope_pos=rope_pos,
                            row_lengths=row_lengths, prompt_len=prompt_len)
                    new_caches[op.name] = nc
                    outs = [out]
                else:
                    kwargs = {}
                    if getattr(op, "wants_shard_ctx", False):
                        kwargs["shard_ctx"] = None
                    if lora is not None \
                            and op.name in lora["pool"] \
                            and op.op_type == OperatorType.OP_LINEAR:
                        # multi-tenant serving (runtime/serving.py): the
                        # per-slot adapter-page gather + batched LoRA
                        # delta, inside the one fixed-shape program
                        from flexflow_tpu.ops.lora import gather_op_lora

                        kwargs["lora"] = gather_op_lora(
                            lora["pool"], op.name, lora["pages"])
                    if op.op_type == OperatorType.OP_MOE:
                        # inference capacity = the slab's token count:
                        # guarantees zero drops (see MoE.forward), hence
                        # row independence for ragged/batched decode
                        kwargs["capacity"] = int(
                            np.prod(xs[0].shape[:-1]))
                    if op.stateful:
                        outs, _ = op.forward_stateful(
                            p, state.get(op.name, {}), xs,
                            training=False, rng=None)
                    else:
                        outs = op.forward(p, xs, training=False, rng=None,
                                          **kwargs)
            for i, t in enumerate(op.outputs):
                vals[t] = outs[i]
        return vals[self.model._final_tensor], new_caches

    def _prefill(self, params, state, tokens, caches, row_lengths,
                 prefill_chunk, lora=None):
        """Whole-prompt prefill, or chunked (`prefill_chunk` > 0 and the
        prompt longer than it): each chunk writes its k/v and attends the
        static prefix slice under the same causal rule — score memory is
        O(chunk * S) not O(S^2). Logits are bitwise-equal to whole-prompt
        prefill on the einsum path; when whole-prompt prefill rides the
        flash kernel (TPU), accumulation order differs, so equality is
        within kernel tolerance there.

        Ragged + chunked (round 5): a ragged row's last position can fall
        in ANY chunk, so every chunk runs cache-only (skip_tail) and a
        final read-only GATHER pass queries each row's own last prompt
        token against the filled cache (MultiHeadAttention.query_forward)
        — right-padding keeps this sound: a real position's causal window
        holds only real positions, and pad slots' garbage k/v are masked
        by row_lengths in the gather and in every decode step."""
        b, s0 = tokens.shape
        if not prefill_chunk or s0 <= prefill_chunk:
            return self._walk(params, state, tokens, caches, None,
                              last_only=True, row_lengths=row_lengths,
                              prompt_len=s0, lora=lora)
        starts = list(range(0, s0, prefill_chunk))
        if row_lengths is not None:
            for st in starts:
                _, caches = self._walk(
                    params, state, tokens[:, st:st + prefill_chunk],
                    caches, None, chunk_start=st, skip_tail=True, lora=lora)
            tok_last = jnp.take_along_axis(
                tokens, (row_lengths - 1)[:, None], axis=1)      # (B, 1)
            return self._walk(params, state, tok_last, caches, None,
                              last_only=True, row_lengths=row_lengths,
                              gather_last=True, lora=lora)
        for st in starts[:-1]:
            _, caches = self._walk(
                params, state, tokens[:, st:st + prefill_chunk], caches,
                None, chunk_start=st, skip_tail=True, lora=lora)
        st = starts[-1]
        return self._walk(params, state, tokens[:, st:], caches, None,
                          last_only=True, chunk_start=st, lora=lora)

    # ---- sampling ----------------------------------------------------------

    def _sample(self, logits, key, with_score=False):
        """logits (B, V) -> (token (B,) int32, logp (B,) f32 or None).
        The score is the MODEL's log-probability of the chosen token
        (raw softmax, independent of temperature/top-k warping of the
        sampling distribution); computed only when requested, so
        score-free decode programs never pay the full-vocab
        log_softmax."""
        logits = logits.astype(jnp.float32)
        if self.temperature <= 0.0:
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            warped = logits / self.temperature
            vocab = logits.shape[-1]
            top_k = self.top_k
            if top_k >= vocab:
                # HF semantics: top_k >= vocab is a legal no-op
                # (full-distribution sampling) — a caller sweeping top_k or
                # serving a tiny-vocab model must not crash at trace time.
                if not getattr(self, "_warned_topk", False):
                    from flexflow_tpu.logger import fflogger
                    fflogger.warning(
                        "top_k=%d >= vocab %d; treating as top_k=0 "
                        "(full-distribution sampling)", top_k, vocab)
                    self._warned_topk = True
                top_k = 0
            if top_k > 0:
                # scatter from the top_k indices (not a >=kth threshold
                # compare, which keeps every logit TIED with the k-th
                # value — more than k candidates on ties)
                vals, idxs = jax.lax.top_k(warped, top_k)
                warped = jnp.full_like(warped, -jnp.inf).at[
                    jnp.arange(warped.shape[0])[:, None], idxs].set(vals)
            tok = jax.random.categorical(key, warped, axis=-1
                                         ).astype(jnp.int32)
        if not with_score:
            return tok, None
        logp = jax.nn.log_softmax(logits, axis=-1)
        score = jnp.take_along_axis(logp, tok[:, None], axis=-1)[:, 0]
        return tok, score

    # ---- the compiled program ---------------------------------------------

    def _build(self, max_new_tokens: int, ragged: bool = False,
               prefill_chunk: int = 0, with_scores: bool = False,
               early_exit: bool = False):
        cdtype = self._compute_dtype()

        def gen(params, state, tokens, key, lengths):
            b, s0 = tokens.shape
            max_len = s0 + max_new_tokens
            row_lengths = lengths if ragged else None
            caches = {op.name: op.init_cache(b, max_len, cdtype)
                      for op in self.attn_ops}
            logits, caches = self._prefill(params, state, tokens, caches,
                                           row_lengths, prefill_chunk)
            key, sub = jax.random.split(key)
            tok, score = self._sample(logits[:, -1], sub,
                                      with_score=with_scores)
            done = jnp.zeros((b,), bool)
            if self.eos_id is not None:
                done = tok == self.eos_id

            def step(caches, tok, done, key, i):
                """Shared decode-step body for the scan and while paths —
                i is the 0-based index of the NEXT token to produce."""
                logits, caches = self._walk(
                    params, state, tok[:, None], caches, s0 + i,
                    rope_pos=(row_lengths + i) if ragged else None,
                    row_lengths=row_lengths, prompt_len=s0)
                key, sub = jax.random.split(key)
                nxt, sc = self._sample(logits[:, 0], sub,
                                       with_score=with_scores)
                if self.eos_id is not None:
                    nxt = jnp.where(done, self.pad_id, nxt)
                    if with_scores:
                        sc = jnp.where(done, 0.0, sc)  # pads score 0
                    done = done | (nxt == self.eos_id)
                return caches, nxt, sc, done, key

            def body(carry, i):
                caches, tok, done, key = carry
                caches, nxt, sc, done, key = step(caches, tok, done, key, i)
                ys = (nxt, sc) if with_scores else nxt
                return (caches, nxt, done, key), ys

            if max_new_tokens > 1 and early_exit:
                # while_loop wrapper: stop as soon as every live row has
                # emitted eos. Token-identical to the full-length scan —
                # the skipped iterations would only have appended pads
                # (which the output buffers are pre-filled with). Costs
                # one extra (i, buffers) carry vs the scan; wins whenever
                # rows finish early. No eos_id => done never flips and the
                # loop runs the full length, same as the scan.
                buf = jnp.full((b, max_new_tokens), self.pad_id, jnp.int32)
                buf = buf.at[:, 0].set(tok)
                sbuf = jnp.zeros((b, max_new_tokens), jnp.float32)
                if with_scores:
                    sbuf = sbuf.at[:, 0].set(score)

                def cond(carry):
                    i = carry[0]
                    done = carry[4]
                    return (i < max_new_tokens - 1) & ~jnp.all(done)

                def wbody(carry):
                    i, caches, tok, (buf, sbuf), done, key = carry
                    caches, nxt, sc, done, key = step(caches, tok, done,
                                                      key, i)
                    buf = buf.at[:, i + 1].set(nxt)
                    if with_scores:
                        sbuf = sbuf.at[:, i + 1].set(sc)
                    return (i + 1, caches, nxt, (buf, sbuf), done, key)

                carry = (jnp.asarray(0, jnp.int32), caches, tok,
                         (buf, sbuf), done, key)
                _, _, _, (buf, sbuf), _, _ = jax.lax.while_loop(
                    cond, wbody, carry)
                new, scores = buf, sbuf
            elif max_new_tokens > 1:
                _, ys = jax.lax.scan(
                    body, (caches, tok, done, key),
                    jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
                rest = ys[0] if with_scores else ys
                new = jnp.concatenate([tok[:, None], rest.T], axis=1)
                if with_scores:
                    scores = jnp.concatenate([score[:, None], ys[1].T],
                                             axis=1)
            else:
                new = tok[:, None]
                if with_scores:
                    scores = score[:, None]
            out = jnp.concatenate([tokens, new], axis=1)
            return (out, scores) if with_scores else out

        return jax.jit(gen)

    # ---- beam search -------------------------------------------------------

    def _build_beam(self, max_new_tokens: int, num_beams: int,
                    length_penalty: float, prefill_chunk: int = 0,
                    ragged: bool = False):
        """Beam decode as one jitted scan. Beams live flattened on the
        batch dim (B*K rows); each step re-orders the KV caches by beam
        parent with a batched gather. Finished beams (emitted eos) are
        frozen: only pad continues them, at logp 0, so their score stops
        changing; the final pick normalizes by emitted length^penalty.
        With `ragged` (right-padded prompts + row lengths), prefill
        scores each row at its OWN last valid position — exactly as the
        greedy path does — and decode steps carry per-row RoPE positions
        and the pad-slot cache mask, repeated per beam."""
        cdtype = self._compute_dtype()
        K = num_beams

        def gen(params, state, tokens, lengths):
            b, s0 = tokens.shape
            max_len = s0 + max_new_tokens
            row_lengths = lengths if ragged else None
            caches = {op.name: op.init_cache(b, max_len, cdtype)
                      for op in self.attn_ops}
            logits, caches = self._prefill(params, state, tokens, caches,
                                           row_lengths, prefill_chunk)
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32),
                                      axis=-1)                  # (B, V)
            vocab = logp.shape[-1]
            scores, tok = jax.lax.top_k(logp, K)                # (B, K)
            tok = tok.astype(jnp.int32)
            done = (tok == self.eos_id) if self.eos_id is not None \
                else jnp.zeros((b, K), bool)
            # beam-flatten the caches: row b*K+k is beam k of batch row b
            caches = jax.tree.map(
                lambda c: jnp.repeat(c, K, axis=0), caches)
            # per-beam row lengths for the flattened (B*K) decode batch
            rep_lengths = (jnp.repeat(lengths, K) if ragged else None)
            buf = jnp.full((b, K, max_new_tokens), self.pad_id, jnp.int32)
            buf = buf.at[:, :, 0].set(tok)
            new_len = jnp.ones((b, K), jnp.int32)

            def body(carry, i):
                caches, buf, tok, scores, done, new_len = carry
                logits, caches = self._walk(
                    params, state, tok.reshape(b * K, 1), caches, s0 + i,
                    rope_pos=(rep_lengths + i) if ragged else None,
                    row_lengths=rep_lengths, prompt_len=s0)
                logp = jax.nn.log_softmax(
                    logits[:, 0].astype(jnp.float32), axis=-1)
                logp = logp.reshape(b, K, vocab)
                # frozen beams: pad continues at logp 0, everything else -inf
                frozen = jnp.full((vocab,), -jnp.inf
                                  ).at[self.pad_id].set(0.0)
                logp = jnp.where(done[..., None], frozen[None, None, :], logp)
                cand = (scores[..., None] + logp).reshape(b, K * vocab)
                scores, flat = jax.lax.top_k(cand, K)           # (B, K)
                parent = flat // vocab                          # (B, K)
                tok = (flat % vocab).astype(jnp.int32)
                gather = lambda a: jnp.take_along_axis(a, parent, axis=1)
                done = gather(done)
                new_len = gather(new_len)
                buf = jnp.take_along_axis(
                    buf, parent[:, :, None], axis=1)
                buf = buf.at[:, :, i + 1].set(tok)
                # reorder caches by beam parent (batched row gather)
                rows = (jnp.arange(b)[:, None] * K + parent).reshape(-1)
                caches = jax.tree.map(
                    lambda c: jnp.take(c, rows, axis=0), caches)
                if self.eos_id is not None:
                    new_len = jnp.where(done, new_len, new_len + 1)
                    done = done | (tok == self.eos_id)
                else:
                    new_len = new_len + 1
                return (caches, buf, tok, scores, done, new_len), None

            if max_new_tokens > 1:
                (caches, buf, tok, scores, done, new_len), _ = jax.lax.scan(
                    body, (caches, buf, tok, scores, done, new_len),
                    jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
            norm = scores / jnp.maximum(new_len, 1).astype(
                jnp.float32) ** length_penalty
            best = jnp.argmax(norm, axis=1)                     # (B,)
            picked = jnp.take_along_axis(
                buf, best[:, None, None], axis=1)[:, 0]         # (B, T)
            best_score = jnp.take_along_axis(norm, best[:, None],
                                             axis=1)[:, 0]
            return jnp.concatenate([tokens, picked], axis=1), best_score

        return jax.jit(gen)

    def _source_params(self):
        """The weight tree decode programs read: the per-generator
        override when one is installed (rolling deploy), else the shared
        model params."""
        if self._params_override is not None:
            return self._params_override
        return self.model.params

    def set_params(self, tree):
        """Install (or, with ``tree=None``, clear) a per-generator weight
        override. The tree must match ``model.params`` in structure,
        shapes and dtypes — same geometry, so every warm decode program
        stays valid and nothing retraces. Invalidate the quantized-weight
        cache so the next program pull re-quantizes from the new source
        exactly once."""
        if tree is not None:
            ref_leaves, ref_def = jax.tree_util.tree_flatten(
                self.model.params)
            new_leaves, new_def = jax.tree_util.tree_flatten(tree)
            if new_def != ref_def:
                raise ValueError(
                    "set_params: tree structure differs from model.params "
                    "— a weight swap must be same-geometry")
            for ref, new in zip(ref_leaves, new_leaves):
                if (getattr(ref, "shape", None) != getattr(new, "shape",
                                                           None)
                        or getattr(ref, "dtype", None)
                        != getattr(new, "dtype", None)):
                    raise ValueError(
                        f"set_params: leaf geometry mismatch "
                        f"{getattr(ref, 'shape', None)}/"
                        f"{getattr(ref, 'dtype', None)} vs "
                        f"{getattr(new, 'shape', None)}/"
                        f"{getattr(new, 'dtype', None)}")
        self._params_override = tree
        self._override_version += 1
        self._qparams = None
        self._qparams_key = None
        self._q_refs = None

    def _params(self):
        return (self._quantized_params() if self.quantize
                else self._source_params())

    def _cached_program(self, key, build):
        """LRU lookup/insert for compiled decode programs."""
        import os

        fn = self._jitted.get(key)
        if fn is not None:
            self._jitted.move_to_end(key)
            return fn
        fn = self._jitted[key] = build()
        try:
            cap = int(os.environ.get("FF_GEN_PROGRAM_CACHE", "8") or 8)
        except ValueError:
            cap = 8
        while cap > 0 and len(self._jitted) > cap:
            self._jitted.popitem(last=False)
        return fn

    def beam_search(self, tokens: np.ndarray, max_new_tokens: int,
                    num_beams: int, length_penalty: float = 0.0,
                    prefill_chunk: int = 0, return_scores: bool = False,
                    prompt_lengths=None):
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths, ragged = self._check_lengths(tokens, prompt_lengths)
        # prompt shape is part of the key: each LRU entry then holds ~one
        # XLA executable, so eviction genuinely bounds compiled programs
        # (a shape-generic jit wrapper would grow an unbounded internal
        # per-shape cache behind a single key)
        key = ("beam", max_new_tokens, num_beams, length_penalty,
               prefill_chunk, ragged, tuple(tokens.shape))
        fn = self._cached_program(key, lambda: self._build_beam(
            max_new_tokens, num_beams, length_penalty, prefill_chunk,
            ragged=ragged))
        out, score = fn(self._params(), self.model.bn_state, tokens,
                        lengths)
        if return_scores:
            # (B,) length-penalty-normalized total logp of the chosen beam
            return np.asarray(out), np.asarray(score)
        return np.asarray(out)

    @staticmethod
    def _check_lengths(tokens, prompt_lengths):
        """Validate (B,) prompt lengths against the prompt slab; returns
        (lengths_device_array, ragged_flag). Uniform prompts pass zeros —
        the compiled program ignores them."""
        ragged = prompt_lengths is not None
        if not ragged:
            return jnp.zeros((tokens.shape[0],), jnp.int32), False
        lengths = np.asarray(prompt_lengths, np.int32)
        if lengths.shape != (tokens.shape[0],):
            raise ValueError(
                f"prompt_lengths shape {lengths.shape} != "
                f"({tokens.shape[0]},)")
        if (lengths < 1).any() or (lengths > tokens.shape[1]).any():
            raise ValueError(
                f"prompt_lengths must be in [1, {tokens.shape[1]}], "
                f"got {lengths.tolist()}")
        return jnp.asarray(lengths), True

    def __call__(self, tokens: np.ndarray, max_new_tokens: int,
                 seed: int = 0, prompt_lengths=None,
                 prefill_chunk: int = 0, return_scores: bool = False,
                 early_exit: bool = False):
        """tokens (B, S0) int32 prompts -> (B, S0 + max_new_tokens) int32
        with the generated tokens in columns S0 onward. Uniform-length
        prompts by default; `prompt_lengths` (B,) enables ragged RIGHT-
        padded prompts — row b's prompt is tokens[b, :prompt_lengths[b]],
        pad slots are masked out of attention and RoPE continues from each
        row's true length. `prefill_chunk` > 0 prefills the prompt in
        chunks of that many positions (O(chunk * S) score memory).
        `early_exit` swaps the fixed-length decode scan for a while_loop
        that stops once every row has emitted eos — identical tokens,
        fewer steps whenever rows finish early."""
        tokens = jnp.asarray(tokens, jnp.int32)
        lengths, ragged = self._check_lengths(tokens, prompt_lengths)
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        # prompt shape in the key: see beam_search — makes LRU eviction
        # actually bound compiled executables, not just jit wrappers
        cache_key = (max_new_tokens, ragged, prefill_chunk, return_scores,
                     early_exit, tuple(tokens.shape))
        fn = self._cached_program(cache_key, lambda: self._build(
            max_new_tokens, ragged, prefill_chunk,
            with_scores=return_scores, early_exit=early_exit))
        key = jax.random.PRNGKey(seed)
        res = fn(self._params(), self.model.bn_state, tokens, key, lengths)
        if return_scores:
            # (B, S0+new) tokens + (B, new) model logprobs per new token
            # (pads after eos carry 0.0)
            out, scores = res
            return np.asarray(out), np.asarray(scores)
        return np.asarray(res)
