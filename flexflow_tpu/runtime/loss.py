"""Loss functions.

Reference: src/loss_functions/loss_functions.cu — sparse/dense categorical
cross-entropy and MSE *backward* kernels with scale = 1/global-batch
(include/loss_functions.h:47-49). Here losses are forward scalars and autodiff
produces the gradient; the 1/B scaling comes from the mean reduction, which
matches the reference's scale factor exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flexflow_tpu.ffconst import LossType


def compute_loss(loss_type: LossType, logits, labels):
    """Scalar training loss. `labels`: int class ids for sparse CE (reference
    sparse_categorical_crossentropy_loss_backward), one-hot/dense probs for
    dense CE, targets for MSE."""
    if logits.dtype == jnp.bfloat16:
        logits = logits.astype(jnp.float32)  # softmax/MSE numerics in f32
    if loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
        logp = jax.nn.log_softmax(logits, axis=-1)
        lab = labels.astype(jnp.int32)
        if lab.ndim == logits.ndim:  # trailing singleton label dim
            lab = lab[..., 0]
        nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)
    if loss_type == LossType.LOSS_CATEGORICAL_CROSSENTROPY:
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))
    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.square(logits - labels))
    if loss_type == LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE:
        # reference sums over features, averages over batch
        return jnp.mean(jnp.sum(jnp.square(logits - labels),
                                axis=tuple(range(1, logits.ndim))))
    if loss_type == LossType.LOSS_IDENTITY:
        return jnp.mean(logits)
    raise ValueError(f"unknown loss {loss_type}")


_KERAS_LOSS_NAMES = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
}


def loss_type_from_name(name) -> LossType:
    if isinstance(name, LossType):
        return name
    return _KERAS_LOSS_NAMES[name]
