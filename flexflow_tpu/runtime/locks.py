"""ffsan runtime plane: the named-lock hierarchy registry + sanitizer.

Every lock in ``flexflow_tpu/runtime`` is created through this module's
factories with a NAME from the declared hierarchy below — the lock order
that has so far lived only as prose in CHANGES.md ("lock order
router->engine", PR 8) becomes one table that three consumers share:

  * the factories here (runtime wiring: which rank a lock carries);
  * the static ``concurrency`` pass (flexflow_tpu/analysis/sanitize),
    which extracts the lock graph from the AST and checks every
    acquisition edge against these ranks in milliseconds;
  * the runtime sanitizer (``FF_SANITIZE=1`` / ``FFConfig.sanitize``),
    which wraps the same factories' output in order-asserting proxies
    and catches what static analysis cannot see (dynamic call paths,
    callbacks, two objects of the same class).

Rank semantics: a thread may only acquire a lock whose rank is STRICTLY
GREATER than every ranked lock it already holds (outer-to-inner =
ascending rank). Re-acquiring the same object (RLock reentrancy) is
always legal. Two DIFFERENT objects at the same rank may not nest — two
engine locks held by one thread is exactly the A->B/B->A fleet deadlock
the hierarchy exists to prevent.

With the sanitizer OFF (the default) the factories return the raw
``threading`` primitives — byte-identical behavior and zero overhead;
the only residual cost of this plane is one module-global read per
engine program dispatch (the retrace sentinel's gate). The mode is
read at LOCK CREATION time: enable via env ``FF_SANITIZE`` for
process-wide coverage (module-level telemetry locks are created at
import), or via ``FFConfig.sanitize`` for every lock created after the
config exists (engines, routers, pools — the serving plane).

The RETRACE SENTINEL is the second sanitizer layer: after an engine's
``warmup()`` the program set is closed — any further jit cache miss is
the silent-retrace bug class relearned in PRs 3/7/10/11 (an uncommitted
device_put, a drifting argument signature, an unwarmed hit-prefill
variant). Armed engines route every dispatch through ``sentinel.call``,
which compares the jitted callable's trace-cache size across the call
and records (strict: raises) the program name + the argument signature
that diverged.

Violations and retraces are routed to the flight recorder as
``sanitizer_lock_order`` / ``sanitizer_retrace`` incident triggers, and
``lock_graph_snapshot()`` rides every post-mortem bundle
(sanitizer.json).
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import traceback
import weakref
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LOCK_RANKS", "make_lock", "make_rlock", "make_condition",
    "configure", "set_mode", "mode", "violations", "retrace_log",
    "reset", "lock_graph_snapshot", "RetraceSentinel",
    "LockOrderViolation", "RetraceViolation",
]

# ---------------------------------------------------------------- hierarchy

# The declared lock order, outermost (lowest rank) first. A thread
# holding rank R may only acquire ranks > R. Gaps are deliberate —
# future locks slot in without renumbering.
#
#   deploy > autoscale > router > engine > prefix-cache > adapter-pool >
#   loader/saver > watchdog > flightrec/slo/hbm > telemetry > native-loader
#
LOCK_RANKS: Dict[str, int] = {
    "deploy": 5,             # RollingDeployer roll state (outermost: a
    #                          deploy step acquires router + engine
    #                          locks beneath it)
    "autoscale": 7,          # AutoscalePolicy decision state (a scale
    #                          step acquires router + engine locks
    #                          beneath it, never the deploy lock)
    "router": 10,            # ServingRouter fleet ledger (RLock)
    "engine": 20,            # ServingEngine tick/queue/slots (RLock)
    "prefix-cache": 30,      # RadixPrefixCache tiered-migration publisher cv
    "adapter-pool": 40,      # LoraAdapterPool host allocator
    "pipeline-loader": 45,   # PipelineLoader prefetch cv
    "checkpoint-saver": 48,  # _AsyncSaver publisher cv
    "watchdog": 52,          # resilience Watchdog arm/fire handshake
    "flightrec": 60,         # FlightRecorder pending/trigger state (RLock)
    "slo-monitor": 62,       # SLOMonitor window state (RLock)
    "hbm-ledger": 64,        # HBMLedger source/estimate state
    "weak-callables": 66,    # _WeakCallables ref lists (flightrec substrate)
    "telemetry-registry": 70,  # metrics Registry family table
    "telemetry-family": 72,    # one metric family's children
    "telemetry-tracer": 74,    # trace ring
    "telemetry-server": 76,    # scrape-server start latch
    "native-loader": 80,     # libffdl build/dlopen latch
}

_VALID_MODES = ("off", "on", "strict")

_env = os.environ.get("FF_SANITIZE", "").strip().lower()
_MODE = ("strict" if _env == "strict"
         else "on" if _env in ("1", "on", "true", "yes")
         else "off")


class LockOrderViolation(RuntimeError):
    """Strict-mode sanitizer: a lock was acquired against the declared
    hierarchy (the violating pair + both acquisition stacks are in the
    message and in ``violations()``)."""


class RetraceViolation(RuntimeError):
    """Strict-mode sanitizer: a warm program retraced after warmup()."""


def mode() -> str:
    return _MODE


def set_mode(new: str) -> str:
    """Set the sanitizer mode ('off' | 'on' | 'strict'); returns the
    previous mode. Lock PROXYING is decided at creation time — this
    gates the retrace sentinel and any proxies already created."""
    global _MODE
    if new not in _VALID_MODES:
        raise ValueError(f"sanitize mode {new!r}: must be one of "
                         f"{_VALID_MODES}")
    prev = _MODE
    _MODE = new
    return prev


def configure(cfg) -> None:
    """Adopt FFConfig.sanitize (engines/routers call this before
    creating their locks, the flightrec.configure pattern). An empty
    value means 'leave the env-derived mode alone'."""
    val = getattr(cfg, "sanitize", "") or ""
    if val:
        set_mode(val)


# ------------------------------------------------------------ held tracking

_tls = threading.local()

# bounded evidence rings: a violation storm must not grow memory
_violations: collections.deque = collections.deque(maxlen=256)
_violation_pairs: Dict[Tuple[str, str], int] = {}
_retraces: collections.deque = collections.deque(maxlen=256)
_evidence_lock = threading.Lock()   # ffsan: allow(raw-lock) — the
#   sanitizer's own evidence ring cannot be a ranked lock (it is taken
#   while an arbitrary ranked lock is being acquired)
_registry: List[weakref.ref] = []   # live proxies, for the snapshot


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _Held:
    __slots__ = ("name", "rank", "obj", "count", "stack")

    def __init__(self, name, rank, obj, stack):
        self.name, self.rank, self.obj = name, rank, obj
        self.count = 1
        self.stack = stack


def _capture() -> str:
    return "".join(traceback.format_stack(limit=18)[:-2])


def _check_order(name: str, rank: int, obj) -> None:
    """Called BEFORE the inner acquire: report (strict: raise) when any
    held ranked lock's rank is >= the one being acquired."""
    if getattr(_tls, "reporting", False):
        # the violation handler itself takes ranked locks (logger,
        # flight recorder) while the violating stack is still held —
        # checking those would record sanitizer self-noise
        return
    held = _held()
    for e in held:
        if e.obj is obj:
            return              # reentrant re-acquire: always legal
    for e in held:
        if e.rank >= rank:
            _report_order(e, name, rank)
            return              # one report per acquisition is enough


def _note_acquired(name: str, rank: int, obj) -> None:
    held = _held()
    for e in held:
        if e.obj is obj:
            e.count += 1
            return
    held.append(_Held(name, rank, obj, _capture()))


def _note_released(obj, all_levels: bool = False) -> int:
    """Pop one recursion level (or all, for RLock._release_save);
    returns the count released so _acquire_restore can re-note it."""
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        e = held[i]
        if e.obj is obj:
            if all_levels:
                n = e.count
                del held[i]
                return n
            e.count -= 1
            if e.count == 0:
                del held[i]
            return 1
    return 0    # acquired before sanitize was enabled: ignore


def _note_restored(name, rank, obj, count: int) -> None:
    if count <= 0:
        return
    held = _held()
    e = _Held(name, rank, obj, _capture())
    e.count = count
    held.append(e)


def _report_order(outer: "_Held", inner_name: str, inner_rank: int) -> None:
    rec = {
        "kind": "lock-order",
        "outer": outer.name, "outer_rank": outer.rank,
        "inner": inner_name, "inner_rank": inner_rank,
        "thread": threading.current_thread().name,
        "outer_stack": outer.stack,
        "inner_stack": _capture(),
    }
    pair = (outer.name, inner_name)
    with _evidence_lock:
        first = pair not in _violation_pairs
        _violation_pairs[pair] = _violation_pairs.get(pair, 0) + 1
        if first:
            _violations.append(rec)
    if first:
        from flexflow_tpu.logger import fflogger

        _tls.reporting = True
        try:
            fflogger.error(
                "ffsan: LOCK ORDER VIOLATION — acquiring %r(rank %d) "
                "while holding %r(rank %d) on thread %s\n"
                "outer acquired at:\n%sinner acquisition:\n%s",
                inner_name, inner_rank, outer.name, outer.rank,
                rec["thread"], outer.stack, rec["inner_stack"])
            _trip("sanitizer_lock_order", outer=outer.name,
                  inner=inner_name, outer_rank=outer.rank,
                  inner_rank=inner_rank, thread=rec["thread"])
        finally:
            _tls.reporting = False
    if _MODE == "strict":
        raise LockOrderViolation(
            f"lock order violation: acquiring {inner_name!r}"
            f"(rank {inner_rank}) while holding {outer.name!r}"
            f"(rank {outer.rank})\nouter acquired at:\n{outer.stack}")


def _trip(cause: str, **args) -> None:
    # lazy: locks.py must stay importable from everywhere in runtime/
    # without dragging the telemetry plane in (flightrec -> telemetry
    # both import THIS module for their own locks)
    try:
        from flexflow_tpu.runtime import flightrec

        flightrec.trip(cause, **args)
    except Exception:
        pass    # forensics must never take the locking path down


# ----------------------------------------------------------------- proxies


class _SanLock:
    """Order-asserting proxy over one threading primitive. Supports the
    Lock/RLock surface plus the private hooks threading.Condition uses
    (_is_owned/_release_save/_acquire_restore), so ``make_condition``
    can wrap a tracked lock."""

    def __init__(self, name: str, rank: int, inner):
        self.name = name
        self.rank = rank
        self._inner = inner
        with _evidence_lock:
            _registry.append(weakref.ref(self))

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _MODE != "off":
            _check_order(self.name, self.rank, self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquired(self.name, self.rank, self)
        return got

    def release(self):
        self._inner.release()
        _note_released(self)

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked()

    # -- threading.Condition integration hooks --
    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        n = _note_released(self, all_levels=True)
        return (state, n)

    def _acquire_restore(self, saved):
        state, n = saved
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        _note_restored(self.name, self.rank, self, max(n, 1))

    def __repr__(self):
        return f"<ffsan {type(self._inner).__name__} {self.name!r} " \
               f"rank={self.rank}>"


def _rank_of(name: str) -> int:
    try:
        return LOCK_RANKS[name]
    except KeyError:
        raise ValueError(
            f"unknown lock name {name!r}: every runtime lock must be "
            f"declared in locks.LOCK_RANKS (known: "
            f"{sorted(LOCK_RANKS)})") from None


def make_lock(name: str):
    """A named lock at the declared rank. Sanitizer off: a raw
    ``threading.Lock`` (zero overhead, byte-identical behavior)."""
    rank = _rank_of(name)
    inner = threading.Lock()        # ffsan: allow(raw-lock) — factory
    if _MODE == "off":
        return inner
    return _SanLock(name, rank, inner)


def make_rlock(name: str):
    rank = _rank_of(name)
    inner = threading.RLock()       # ffsan: allow(raw-lock) — factory
    if _MODE == "off":
        return inner
    return _SanLock(name, rank, inner)


def make_condition(name: str):
    """A Condition over a tracked RLock at the declared rank. The
    proxy's _release_save/_acquire_restore keep the held-stack exact
    across ``wait()`` (the thread genuinely does not hold the lock
    while waiting)."""
    rank = _rank_of(name)
    if _MODE == "off":
        return threading.Condition()    # ffsan: allow(raw-lock) — factory
    return threading.Condition(         # ffsan: allow(raw-lock) — factory
        lock=_SanLock(name, rank,
                      threading.RLock()))  # ffsan: allow(raw-lock)


# ---------------------------------------------------------- retrace sentinel


def _arg_signature(args) -> List[str]:
    """Compact per-argument signature — the datum a silent retrace
    diverged on. For array-likes: type, shape, dtype and (for jax
    arrays) commitment — the committed/uncommitted flip IS the classic
    warm-program retrace (PR 3's device_put lesson)."""
    out = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            committed = getattr(a, "_committed", None)
            weak = getattr(a, "weak_type", None)
            sig = f"{type(a).__name__}{tuple(shape)}:{dtype}"
            if committed is not None:
                sig += ":committed" if committed else ":UNCOMMITTED"
            if weak:
                sig += ":weak"
            out.append(sig)
        else:
            out.append(type(a).__name__)
    return out


class RetraceSentinel:
    """Per-engine jit-cache-miss watch. ``call()`` is the dispatch
    funnel: unarmed (or sanitizer off) it is one global read + two attr
    checks; armed, it brackets the call with the jitted callable's
    ``_cache_size()`` and records any growth as a retrace of a warm
    program, with the argument signature that diverged. ``note_miss``
    covers the program-DICT level: a whole new program key after
    warmup is the same bug class (an unwarmed variant)."""

    def __init__(self, owner: str = ""):
        self.owner = owner
        self.armed = False
        self.hits = 0

    @contextlib.contextmanager
    def suspended(self):
        """Exempt a deliberate warm-path compile (e.g.
        warm_page_import after warmup) from the closed-set
        check."""
        prev = self.armed
        self.armed = False
        try:
            yield
        finally:
            self.armed = prev

    def arm(self) -> None:
        """Close the program set — warmup is done; every later miss is
        a violation. Arming is unconditional; the mode gates at call
        time so a bench can toggle the sentinel without rebuilding."""
        self.armed = True

    def call(self, key, fn, args):
        if not self.armed or _MODE == "off":
            return fn(*args)
        size = getattr(fn, "_cache_size", None)
        if size is None:
            return fn(*args)
        before = size()
        out = fn(*args)
        if size() > before:
            self._record("retrace", key, args)
        return out

    def note_miss(self, key, args=()) -> None:
        if self.armed and _MODE != "off":
            self._record("new-program", key, args)

    def _record(self, kind: str, key, args) -> None:
        self.hits += 1
        rec = {"kind": kind, "owner": self.owner, "program": repr(key),
               "signature": _arg_signature(args),
               "thread": threading.current_thread().name,
               "stack": _capture()}
        with _evidence_lock:
            _retraces.append(rec)
        from flexflow_tpu.logger import fflogger

        # see _check_order: reporting takes ranked locks (logger,
        # recorder) under whatever the caller already holds
        _tls.reporting = True
        try:
            fflogger.error(
                "ffsan: RETRACE after warmup — %s program %r (%s) "
                "signature=%s", kind, rec["program"], self.owner,
                rec["signature"])
            _trip("sanitizer_retrace", program=rec["program"], kind=kind,
                  owner=self.owner, signature=rec["signature"])
        finally:
            _tls.reporting = False
        if _MODE == "strict":
            raise RetraceViolation(
                f"jit cache miss on warm program {rec['program']} "
                f"({kind}, owner={self.owner}): signature "
                f"{rec['signature']}")


# --------------------------------------------------------------- inspection


def violations() -> List[Dict]:
    with _evidence_lock:
        return list(_violations)


def retrace_log() -> List[Dict]:
    with _evidence_lock:
        return list(_retraces)


def reset() -> None:
    """Drop recorded evidence (tests/bench); live locks stay tracked."""
    with _evidence_lock:
        _violations.clear()
        _violation_pairs.clear()
        _retraces.clear()


def lock_graph_snapshot() -> Dict:
    """The sanitizer's state for post-mortem bundles (sanitizer.json):
    declared hierarchy, live tracked locks, and the evidence rings."""
    with _evidence_lock:
        live = [r() for r in _registry]
        _registry[:] = [r for r, o in zip(list(_registry), live)
                        if o is not None]
        locks = [{"name": o.name, "rank": o.rank} for o in live
                 if o is not None]
        pairs = {f"{a}->{b}": n for (a, b), n in _violation_pairs.items()}
        return {"mode": _MODE, "ranks": dict(LOCK_RANKS),
                "tracked_locks": locks,
                "violation_pairs": pairs,
                "violations": list(_violations),
                "retraces": list(_retraces)}
