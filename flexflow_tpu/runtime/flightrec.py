"""Flight recorder + SLO health plane + HBM accounting ledger (ISSUE 15).

The telemetry plane (runtime/telemetry.py) gives the fleet live series
and traces — but when a replica is fenced, a watchdog fires or a
nonfinite rewind triggers, that evidence evaporates with the process.
This module turns the telemetry substrate into operable production
forensics, three pieces on one switch:

  * **Flight recorder** — the always-on in-memory window is the
    telemetry trace ring + the metrics registry + a bounded ring of
    recent log records (``LogRing``, a logging handler on ``fflogger``).
    A *trigger* (watchdog fire, replica fence, nonfinite rewind, uncaught
    engine/driver exception, SIGTERM preempt, any fired FF_FAULT, an SLO
    breach, or a manual ``FFModel.dump_flight_record()`` /
    ``ServingRouter.dump_flight_record()``) snapshots that window into an
    atomic, content-hash-manifested **post-mortem bundle** directory:
    a perfetto-loadable trace of the window, the metrics snapshot, recent
    logs as JSON lines, the trigger cause + stack, an FFConfig/strategy/
    env fingerprint, per-engine ``stats()``/``health()``, and the HBM
    ledger. Triggers are *debounced* (a crash storm merges into the
    pending bundle) and *cooled down* (one bundle per ``cooldown_s``, the
    rest counted as suppressed), retention keeps the newest K bundles,
    and publication is tmp-dir + ``write_manifest`` + ``os.replace`` —
    the checkpoint layer's torn-write discipline, so a bundle either
    verifies intact or is invisible.

  * **Declarative SLO monitor** — ``FFConfig.slo_*`` ceilings/floors
    (p99 TTFT, engine queue wait, prefix-hit-rate floor, speculative
    accept floor, train step-time and checkpoint-stall budgets) evaluated
    over *sliding windows*: each evaluation diffs the registry's
    cumulative histograms (and the engines' hit/accept counters) against
    the previous window's snapshot, so the judged value is the last
    window's traffic only — warmup compiles never leak into a breach. A
    breach fires only after a full window, emits
    ``ff_slo_breach_total{slo,replica}`` + a margin gauge + a structured
    alert log + a trace annotation (and optionally trips the recorder),
    and clears with hysteresis (``slo_clear_windows`` consecutive healthy
    windows).

  * **HBM accounting ledger** — per-subsystem device-memory gauges
    (``ff_hbm_bytes{source,subsystem}``: KV pool incl. the host tier,
    adapter pool, serving weights, params, optimizer state) published by
    weakly-referenced sources at scrape time, cross-checked against
    fflint's footprint estimate (``ff_hbm_lint_estimated_bytes``) and
    included in every bundle — the per-pool resolution ROADMAP item 4's
    memory-objective search will consume.

``FFConfig.telemetry="off"`` (or ``telemetry.set_enabled(False)``, or
this module's own ``set_enabled(False)`` — the bench's overhead control
arm) short-circuits every piece at the same single predicate as every
other telemetry emit: the log ring stops growing, ``trip()`` returns at
one check, the SLO evaluator never judges.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import shutil
import sys
import threading
import time
import traceback
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import locks, telemetry

__all__ = [
    "FlightRecorder", "SLOMonitor", "HBMLedger", "LogRing",
    "recorder", "slo_monitor", "hbm_ledger", "log_ring", "reset",
    "configure", "trip", "dump", "verify_bundle", "list_bundles",
    "register_health_source", "health_rollup", "set_enabled", "enabled",
    "BUNDLE_PREFIX",
]

BUNDLE_PREFIX = "bundle_"
_TMP_PREFIX = "tmp-bundle-"
LOG_RING_CAP = 2048

# module gate (the bench's recorder-off control arm): AND'ed with the
# process-wide telemetry switch and the configured FFConfig.telemetry —
# one predicate guards every emit in this module
_enabled = True


def set_enabled(on: bool) -> bool:
    """Flip the recorder/SLO/ledger gate; returns the previous value.
    Telemetry itself keeps running — this is the marginal-overhead
    control arm (bench ``flightrec_overhead_pct``)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def enabled() -> bool:
    return _enabled


def _on() -> bool:
    """THE predicate (satellite: ``telemetry="off"`` short-circuits the
    recorder and SLO evaluator at the same single check as every other
    emit)."""
    return _enabled and telemetry.enabled() and _recorder._cfg_on


def _jsonable(obj, depth: int = 0):
    """Best-effort JSON projection of a stats()-style dict (numpy
    scalars, nested dicts, the odd object repr)."""
    if depth > 6:
        return str(obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v, depth + 1) for v in obj]
    try:
        return float(obj)       # numpy scalars
    except Exception:
        return str(obj)


class _WeakCallables:
    """One weakly-held callable list (the pattern the recorder's bundle
    sources, the SLO monitor's ratio sources, the HBM ledger and the
    health rollup all need): ``register()`` wraps bound methods in
    WeakMethod so holding a source never keeps an engine alive;
    ``live()`` returns the currently-live callables and prunes dead
    refs."""

    def __init__(self):
        self._lock = locks.make_lock("weak-callables")
        self._refs: List[weakref.ref] = []

    def register(self, fn: Callable):
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._lock:
            self._refs.append(ref)

    def live(self) -> List[Callable]:
        with self._lock:
            refs = list(self._refs)
        out = [fn for fn in (r() for r in refs) if fn is not None]
        if len(out) != len(refs):
            with self._lock:
                self._refs = [r for r in self._refs if r() is not None]
        return out


# ---------------------------------------------------------------- log ring


class LogRing:
    """Bounded in-memory window of recent log records, as JSON-ready
    rows (ts/level/logger/msg + the active telemetry ``trace_id`` so
    lines join per-request traces). Fixed memory: old records fall off.
    Fed by a ``logging.Handler`` installed on ``fflogger`` at first
    ``configure()``; writes are one deque append (thread-safe by the
    GIL's deque atomicity), gated by the module predicate."""

    def __init__(self, cap: int = LOG_RING_CAP):
        self._ring: collections.deque = collections.deque(maxlen=cap)

    def record(self, rec: logging.LogRecord):
        if not _on():
            return
        try:
            row = {"ts": round(rec.created, 6),
                   "level": rec.levelname.lower(),
                   "logger": rec.name,
                   "msg": rec.getMessage()}
            tid = telemetry.current_trace_id()
            if tid is not None:
                row["trace_id"] = tid
            self._ring.append(row)
        except Exception:       # a sick log line must not kill the caller
            pass

    def recent(self, n: Optional[int] = None) -> List[Dict]:
        rows = list(self._ring)
        return rows if n is None else rows[-n:]

    def __len__(self):
        return len(self._ring)


class _RingHandler(logging.Handler):
    """Thin forwarder so ``reset()`` can swap the ring without touching
    the logger's handler list."""

    def emit(self, record):
        _log_ring.record(record)


_ring_handler_installed = False


def _ensure_log_handler():
    global _ring_handler_installed
    if _ring_handler_installed:
        return
    h = _RingHandler(level=logging.DEBUG)
    fflogger.addHandler(h)
    _ring_handler_installed = True


# ------------------------------------------------------------- the recorder


class FlightRecorder:
    """Trigger -> post-mortem bundle. ``trip()`` is asynchronous: the
    first trigger opens a *pending* record and arms a debounce timer;
    further triggers merge into it (a crash storm is ONE bundle whose
    ``trigger.json`` lists the storm); the timer — or an explicit
    ``flush()`` — writes the bundle. After a write, ``cooldown_s``
    suppresses new triggers (counted). ``dump()`` is the synchronous
    manual path: it always writes (merging any pending record) and never
    starts or consumes a cooldown — an operator's explicit request must
    not be rate-limited, nor mask the next real incident."""

    def __init__(self):
        self._lock = locks.make_rlock("flightrec")
        self._cfg_on = True           # FFConfig.telemetry != "off"
        self.directory = os.environ.get("FF_FLIGHT_DIR", "")
        self.keep = 4
        self.cooldown_s = 30.0
        self.debounce_s = 1.0
        self.window_s = 120.0
        self._fingerprint: Dict = {}
        self._seq = 0
        self._sources = _WeakCallables()
        self._pending: Optional[Dict] = None
        self._timer: Optional[threading.Timer] = None
        self._last_bundle_t = -float("inf")
        self.last_bundle_path: Optional[str] = None
        self.bundles_written = 0
        self.triggers_seen = 0
        self.triggers_merged = 0
        self.triggers_suppressed = 0
        self._suppressed_at_last_bundle = 0
        self._write_done = threading.Event()
        self._write_done.set()

    # ---- configuration ----------------------------------------------------

    def configure(self, cfg):
        """Adopt the FFConfig knobs (last configure wins — engines,
        routers and supervisors all pass their model's config, which is
        one object per process in practice). Captures the config/env
        fingerprint every bundle embeds."""
        with self._lock:
            self._cfg_on = getattr(cfg, "telemetry", "on") != "off"
            self.directory = (getattr(cfg, "flight_recorder_dir", "")
                              or os.environ.get("FF_FLIGHT_DIR", ""))
            self.keep = int(getattr(cfg, "flight_keep", self.keep))
            self.cooldown_s = float(
                getattr(cfg, "flight_cooldown_s", self.cooldown_s))
            self.debounce_s = float(
                getattr(cfg, "flight_debounce_s", self.debounce_s))
            self.window_s = float(
                getattr(cfg, "flight_window_s", self.window_s))
            self._fingerprint = _fingerprint(cfg)
            if self.directory:
                os.makedirs(self.directory, exist_ok=True)
                self._seq = max([_bundle_seq(d) for d in
                                 list_bundles(self.directory)] + [self._seq])

    def attach_source(self, fn: Callable[[], Tuple[str, Dict]]):
        """Register a bundle source: ``fn() -> (name, payload_dict)``.
        Weakly referenced (an engine's bound method never keeps the
        engine alive); collected at bundle-write time against a shared
        deadline so a wedged replica cannot hang the post-mortem of its
        own incident."""
        self._sources.register(fn)

    # ---- triggering -------------------------------------------------------

    def trip(self, cause: str, exc: Optional[BaseException] = None,
             **args):
        """Asynchronous trigger. No-op unless the module predicate holds
        AND a bundle directory is configured (the in-memory window is
        always on; *writing* needs a destination)."""
        if not _on():
            return
        with self._lock:
            if not self.directory:
                return
            self.triggers_seen += 1
            now = time.monotonic()
            ev = {"cause": cause, "args": _jsonable(args),
                  "wall_time": time.time()}
            if self._pending is not None:
                self.triggers_merged += 1
                self._pending["merged"].append(ev)
                return
            if not self._write_done.is_set():
                # a bundle write is in flight: this trigger is part of
                # the same storm (the cooldown stamp lands only when
                # the write finishes — without this check the storm's
                # tail would open a second bundle)
                self.triggers_suppressed += 1
                return
            if now - self._last_bundle_t < self.cooldown_s:
                self.triggers_suppressed += 1
                return
            ev["stack"] = self._capture_stack(exc)
            ev["merged"] = []
            self._pending = ev
            self._write_done.clear()
            self._timer = threading.Timer(max(self.debounce_s, 0.0),
                                          self._flush_pending)
            self._timer.daemon = True
            self._timer.start()

    @staticmethod
    def _capture_stack(exc: Optional[BaseException]) -> str:
        if exc is not None:
            return "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))
        live = sys.exc_info()
        if live[0] is not None:
            return "".join(traceback.format_exception(*live))
        return "".join(traceback.format_stack())

    def flush(self, timeout: float = 30.0) -> Optional[str]:
        """Write any pending (debounced) bundle NOW; returns its path
        (or the just-finished path when an in-flight timer write is what
        we waited on; None when this call caused no write — a stale
        previous bundle's path is never returned as if it were this
        incident's)."""
        with self._lock:
            t = self._timer
            before = self.bundles_written
        if t is not None:
            t.cancel()
        self._flush_pending()
        self._write_done.wait(timeout)
        with self._lock:
            return (self.last_bundle_path
                    if self.bundles_written > before else None)

    def wait_pending(self, timeout: float = 30.0) -> bool:
        """Block until no bundle write is pending/in flight."""
        return self._write_done.wait(timeout)

    def _flush_pending(self):
        with self._lock:
            rec = self._pending
            self._pending = None
            self._timer = None
            directory = self.directory
        if rec is None:
            return
        try:
            self._write_bundle(rec, directory)
        except Exception as e:  # noqa: BLE001 — forensics must not
            #   crash the system they observe
            fflogger.warning("flight recorder: bundle write failed "
                             "(%s: %s)", type(e).__name__, e)
        finally:
            self._write_done.set()

    def dump(self, cause: str = "manual",
             directory: Optional[str] = None, **args) -> Optional[str]:
        """Synchronous manual bundle (the ``FFModel.dump_flight_record``
        / router API). Returns the bundle path, or None when telemetry
        is off (the off contract covers manual dumps too). Raises when
        no directory is configured and none is passed."""
        if not _on():
            return None
        with self._lock:
            d = directory or self.directory
            if not d:
                raise ValueError(
                    "dump_flight_record: no bundle directory — set "
                    "FFConfig.flight_recorder_dir (or FF_FLIGHT_DIR) or "
                    "pass directory=")
            # absorb a pending debounced record into this write
            rec = self._pending
            self._pending = None
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        merged = []
        if rec is not None:
            merged = [dict(rec, merged=None)] + rec["merged"]
            for m in merged:
                m.pop("merged", None)
        ev = {"cause": cause, "args": _jsonable(args),
              "wall_time": time.time(),
              "stack": self._capture_stack(None), "merged": merged}
        try:
            return self._write_bundle(ev, d, manual=True)
        finally:
            if rec is not None:
                # only the dump that ABSORBED the pending record owns
                # its completion flag — a concurrent timer-initiated
                # write (pending already popped, still publishing) must
                # not be marked done by an unrelated manual dump
                self._write_done.set()

    # ---- bundle writing ---------------------------------------------------

    def _collect_sources(self, timeout_s: float = 5.0) -> Dict[str, Dict]:
        """Run every live source on its own thread against ONE shared
        deadline: a source blocked behind a wedged engine lock (the very
        incident being recorded) yields an error row, and N wedged
        sources cost one timeout, not N — the bundle write stays well
        inside flush()'s wait."""
        out: Dict[str, Dict] = {}
        boxes: List[Tuple[threading.Thread, Dict]] = []
        for fn in self._sources.live():
            box: Dict = {}

            def _run(fn=fn, box=box):
                try:
                    name, payload = fn()
                    box["name"] = str(name)
                    box["payload"] = _jsonable(payload)
                except Exception as e:  # noqa: BLE001
                    box["error"] = f"{type(e).__name__}: {e}"

            t = threading.Thread(target=_run, daemon=True,
                                 name="ff-flightrec-source")
            t.start()
            boxes.append((t, box))
        deadline = time.monotonic() + timeout_s
        for t, box in boxes:
            t.join(max(deadline - time.monotonic(), 0.0))
            if "name" in box:
                out[box["name"]] = box["payload"]
            elif "error" in box:
                out[f"source-error-{len(out)}"] = {"error": box["error"]}
            else:
                out[f"source-timeout-{len(out)}"] = {
                    "error": f"source did not answer in {timeout_s}s"}
        return out

    def _window_events(self) -> List[Dict]:
        """The trace ring's last ``window_s`` (a complete span whose END
        falls inside the window stays — it is part of the story)."""
        cut = telemetry.now_us() - self.window_s * 1e6
        return [e for e in telemetry.tracer().events()
                if e["ts"] + e.get("dur", 0.0) >= cut]

    def _write_bundle(self, rec: Dict, directory: str,
                      manual: bool = False) -> str:
        from flexflow_tpu.runtime.checkpoint import write_manifest

        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._seq += 1
            seq = self._seq
        slug = "".join(c if c.isalnum() else "_"
                       for c in rec["cause"])[:40] or "trigger"
        name = f"{BUNDLE_PREFIX}{seq:05d}_{slug}"
        final = os.path.join(directory, name)
        tmp = os.path.join(directory, _TMP_PREFIX + name)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)

        trigger = {
            "cause": rec["cause"], "args": rec.get("args", {}),
            "wall_time": rec["wall_time"],
            "iso_time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                      time.localtime(rec["wall_time"])),
            "stack": rec.get("stack", ""),
            "merged_triggers": rec.get("merged", []),
            # suppressed since the PREVIOUS bundle — the count this
            # incident's cooldown/in-flight window swallowed, not the
            # recorder's lifetime total
            "suppressed_in_cooldown": (self.triggers_suppressed
                                       - self._suppressed_at_last_bundle),
            "manual": manual, "pid": os.getpid(),
        }
        _write_json(tmp, "trigger.json", trigger)
        _write_json(tmp, "trace.json",
                    {"traceEvents": self._window_events(),
                     "displayTimeUnit": "ms"})
        _write_json(tmp, "metrics.json", telemetry.registry().snapshot())
        with open(os.path.join(tmp, "logs.jsonl"), "w",
                  encoding="utf-8") as f:
            for row in _log_ring.recent():
                f.write(json.dumps(row, ensure_ascii=False) + "\n")
        _write_json(tmp, "fingerprint.json", self._fingerprint
                    or _fingerprint(None))
        _write_json(tmp, "engines.json", self._collect_sources())
        _write_json(tmp, "hbm.json", _hbm.snapshot())
        _write_json(tmp, "slo.json", _slo.describe())
        # ffsan state (ISSUE 16): the declared lock hierarchy, the
        # live tracked locks, and the violation/retrace evidence
        # rings — for sanitizer_lock_order / sanitizer_retrace
        # incidents this IS the post-mortem; for every other cause
        # it answers "was the sanitizer watching, and was it clean"
        _write_json(tmp, "sanitizer.json",
                    locks.lock_graph_snapshot())
        # the manifest is the LAST write into tmp (it covers every other
        # file), then the publish rename — the checkpoint layer's
        # torn-write discipline: a bundle either verifies or never
        # appears under BUNDLE_PREFIX
        write_manifest(tmp)
        os.replace(tmp, final)
        with self._lock:
            self.bundles_written += 1
            self.last_bundle_path = final
            self._suppressed_at_last_bundle = self.triggers_suppressed
            if not manual:
                self._last_bundle_t = time.monotonic()
        self._retention(directory)
        fflogger.warning(
            "flight recorder: post-mortem bundle %s (cause=%s, "
            "%d merged trigger(s))", final, rec["cause"],
            len(rec.get("merged", [])))
        telemetry.annotate("flight_record", cause=rec["cause"], path=final)
        return final

    def _retention(self, directory: str):
        bundles = list_bundles(directory)
        for d in bundles[:-max(self.keep, 1)]:
            shutil.rmtree(d, ignore_errors=True)

    def stats(self) -> Dict:
        with self._lock:
            return {"directory": self.directory,
                    "bundles_written": self.bundles_written,
                    "triggers_seen": self.triggers_seen,
                    "triggers_merged": self.triggers_merged,
                    "triggers_suppressed": self.triggers_suppressed,
                    "last_bundle": self.last_bundle_path,
                    "pending": self._pending is not None}


def _write_json(d: str, name: str, obj):
    with open(os.path.join(d, name), "w", encoding="utf-8") as f:
        json.dump(obj, f, ensure_ascii=False)


def _bundle_seq(path: str) -> int:
    base = os.path.basename(path)[len(BUNDLE_PREFIX):]
    digits = base.split("_", 1)[0]
    return int(digits) if digits.isdigit() else 0


def list_bundles(directory: str) -> List[str]:
    """Published bundle dirs, oldest first (tmp dirs from a torn write
    are invisible — publication is atomic)."""
    if not directory or not os.path.isdir(directory):
        return []
    out = [os.path.join(directory, n) for n in os.listdir(directory)
           if n.startswith(BUNDLE_PREFIX)
           and os.path.isdir(os.path.join(directory, n))]
    return sorted(out, key=_bundle_seq)


def verify_bundle(path: str):
    """Recompute the bundle's content-hash manifest; raises
    ``checkpoint.CheckpointCorruptError`` on any mismatch (the same
    verifier the checkpoint layer trusts)."""
    from flexflow_tpu.runtime.checkpoint import verify_dir_manifest

    verify_dir_manifest(path, label=f"flight bundle {path}", require=True)


def _fingerprint(cfg) -> Dict:
    """FFConfig (primitive fields), strategy summary and environment —
    enough to reproduce the process that wrote the bundle."""
    out: Dict = {"env": {}, "config": {}, "strategies": {}}
    try:
        import platform

        out["env"]["python"] = platform.python_version()
        out["env"]["platform"] = platform.platform()
    except Exception:
        pass
    try:
        import jax

        out["env"]["jax"] = jax.__version__
        # default_backend touches no new state once a backend exists —
        # and every serving/training process has one by bundle time
        out["env"]["backend"] = jax.default_backend()
        devs = jax.local_devices()
        out["env"]["device_kind"] = devs[0].device_kind if devs else ""
        out["env"]["local_devices"] = len(devs)
    except Exception:
        pass
    out["env"]["vars"] = {k: v for k, v in os.environ.items()
                          if k.startswith(("FF_", "FLEXFLOW_"))}
    if cfg is not None:
        for k, v in vars(cfg).items():
            if isinstance(v, (str, int, float, bool)) or v is None:
                out["config"][k] = v
        strategies = getattr(cfg, "strategies", None) or {}
        out["strategies"] = {str(k): str(v)
                             for k, v in list(strategies.items())[:256]}
        out["strategy_count"] = len(strategies)
    return out


# ------------------------------------------------------------- SLO monitor

# (name, FFConfig knob, direction, kind, keys)
#   hist_p99: keys = histogram family names; the judged value is the
#             window-delta p99 per labeled child series
#   ratio:    keys = (numerator, denominator) counter names read from
#             registered engine sources; judged per source over the
#             window's delta
_SLO_SPECS: Tuple[Tuple[str, str, str, str, Tuple[str, ...]], ...] = (
    ("ttft_p99", "slo_ttft_p99_s", "ceiling", "hist_p99",
     ("ff_serving_ttft_seconds", "ff_router_ttft_seconds")),
    ("queue_wait_p99", "slo_queue_wait_p99_s", "ceiling", "hist_p99",
     ("ff_serving_queue_wait_seconds",)),
    ("step_time_p99", "slo_step_time_p99_s", "ceiling", "hist_p99",
     ("ff_train_step_seconds",)),
    ("checkpoint_stall_p99", "slo_checkpoint_stall_s", "ceiling",
     "hist_p99", ("ff_checkpoint_stall_seconds",)),
    ("prefix_hit_rate", "slo_prefix_hit_rate_min", "floor", "ratio",
     ("prefix_hits", "prefix_lookups")),
    ("spec_accept", "slo_spec_accept_min", "floor", "ratio",
     ("spec_accepted", "spec_proposed")),
)


class _SeriesState:
    __slots__ = ("snapshot", "replica", "breached", "ok_streak",
                 "windows", "last_value")

    def __init__(self, snapshot, replica: str = "?"):
        self.snapshot = snapshot
        # the replica LABEL this series is judged/exported under — the
        # same string ff_slo_breach_total/margin carry, so /healthz and
        # /slo.json join against the metric labels exactly
        self.replica = replica
        self.breached = False
        self.ok_streak = 0
        self.windows = 0
        self.last_value: Optional[float] = None


# quantile over a window's bucket-count deltas: the ONE shared
# estimator (telemetry.bucket_quantile), applied to the difference of
# two cumulative snapshots — the windowed p99 an SLO judges can never
# diverge from the exported histogram p99 operators compare it against
_delta_quantile = telemetry.bucket_quantile


class SLOMonitor:
    """Sliding-window SLO evaluation over the live registry.

    ``maybe_evaluate()`` is the tick — called from the router driver
    loop, the engine scheduler, the supervisor step boundary and the
    ``/healthz`` handler; it returns at one time-compare until a full
    window has elapsed, then judges every active spec's series against
    the window's *delta*. A series first seen mid-stream is baselined
    and judged from the NEXT window (a breach can only fire on a full
    window of its own traffic); an empty window leaves a series' state
    untouched (no data neither confirms nor clears). Breached series
    clear after ``clear_windows`` consecutive healthy windows — the
    hysteresis that keeps a flapping metric from strobing alerts."""

    def __init__(self):
        self._lock = locks.make_rlock("slo-monitor")
        self._cfg_on = True
        self.window_s = 10.0
        self.clear_windows = 2
        self.trip_recorder = False
        self.specs: Dict[str, float] = {}        # name -> bound
        self._by_name = {s[0]: s for s in _SLO_SPECS}
        self._state: Dict[Tuple, _SeriesState] = {}
        self._sources = _WeakCallables()
        self._last_eval: Optional[float] = None
        self.evaluations = 0
        self.breaches_fired = 0

    def configure(self, cfg):
        with self._lock:
            self._cfg_on = getattr(cfg, "telemetry", "on") != "off"
            self.window_s = float(getattr(cfg, "slo_window_s",
                                          self.window_s))
            self.clear_windows = int(getattr(cfg, "slo_clear_windows",
                                             self.clear_windows))
            self.trip_recorder = bool(getattr(cfg, "slo_trip_recorder",
                                              self.trip_recorder))
            specs = {}
            for name, knob, _dir, _kind, _keys in _SLO_SPECS:
                bound = float(getattr(cfg, knob, 0.0) or 0.0)
                if bound > 0:
                    specs[name] = bound
            self.specs = specs
            # prune state for specs no longer configured: a breached
            # series whose spec was disabled would otherwise never be
            # judged again — and never clear — wedging /healthz at
            # "breach" for the life of the process
            self._state = {k: v for k, v in self._state.items()
                           if k[0] in specs}
            if specs:
                # baseline NOW: traffic before this point (warmup
                # compiles!) can never be judged
                self._rebaseline_locked()
                self._last_eval = time.monotonic()

    def add_source(self, fn: Callable[[], Tuple[str, Dict]]):
        """``fn() -> (replica_label, {counter: int})`` with lock-free
        counter reads — the ratio-floor SLOs (prefix hit rate, spec
        accept) are judged from these deltas."""
        self._sources.register(fn)

    def rebaseline(self):
        """Re-snapshot every known series and restart the window clock.
        ``ServingEngine.warmup()``/``ServingRouter.warmup()`` call this
        when they finish, so compile-inflated warmup TTFTs can never be
        judged as a breach — the same discipline the bench's timed
        windows use."""
        if not self.specs:
            return
        with self._lock:
            self._rebaseline_locked()
            self._last_eval = time.monotonic()

    # ---- evaluation -------------------------------------------------------

    def maybe_evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """The cheap tick: one predicate + one time compare until a full
        window has elapsed."""
        if not (_enabled and telemetry.enabled() and self._cfg_on) \
                or not self.specs:
            return []
        now = time.monotonic() if now is None else now
        if self._last_eval is not None \
                and now - self._last_eval < self.window_s:
            return []
        return self.evaluate(now=now)

    def evaluate(self, now: Optional[float] = None) -> List[Dict]:
        """Judge one full window; returns this evaluation's breach
        events. (``maybe_evaluate`` is the public tick — call this
        directly only to force an off-cadence judgement, e.g. tests.)"""
        if not (_enabled and telemetry.enabled() and self._cfg_on) \
                or not self.specs:
            return []
        with self._lock:
            self._last_eval = time.monotonic() if now is None else now
            self.evaluations += 1
            events: List[Dict] = []
            reg = telemetry.registry()
            for name, bound in self.specs.items():
                _n, _knob, direction, kind, keys = self._by_name[name]
                if kind == "hist_p99":
                    self._eval_hist_locked(reg, name, bound, direction,
                                           keys, events)
                else:
                    self._eval_ratio_locked(name, bound, direction,
                                            keys, events)
            return events

    def _eval_hist_locked(self, reg, name, bound, direction, families,
                          events):
        for fam_name in families:
            fam = reg.family(fam_name)
            if fam is None or fam.kind != "histogram":
                continue
            for ch in fam.children():
                labels = dict(ch.labels)
                replica = labels.get("replica",
                                     "fleet" if not labels else "?")
                sid = (name, fam_name, ch.labels)
                snap = (list(ch.counts), ch.count)
                st = self._state.get(sid)
                if st is None:
                    self._state[sid] = _SeriesState(snap, replica)
                    continue
                delta = [a - b for a, b in zip(snap[0], st.snapshot[0])]
                n = snap[1] - st.snapshot[1]
                st.snapshot = snap
                if n <= 0:
                    continue        # empty window: state unchanged
                value = _delta_quantile(ch.bounds, delta, 0.99)
                self._judge_locked(name, replica, value, bound,
                                   direction, st, events, samples=n)

    def _eval_ratio_locked(self, name, bound, direction, keys, events):
        num_key, den_key = keys
        for fn in self._sources.live():
            try:
                replica, counters = fn()
            except Exception:
                continue
            sid = (name, "source", str(replica))
            snap = (int(counters.get(num_key, 0)),
                    int(counters.get(den_key, 0)))
            st = self._state.get(sid)
            if st is None:
                self._state[sid] = _SeriesState(snap, str(replica))
                continue
            d_num = snap[0] - st.snapshot[0]
            d_den = snap[1] - st.snapshot[1]
            st.snapshot = snap
            if d_den <= 0:
                continue            # no traffic this window
            value = d_num / d_den
            self._judge_locked(name, str(replica), value, bound,
                               direction, st, events, samples=d_den)

    def _judge_locked(self, name, replica, value, bound, direction, st,
                      events, samples: int):
        st.windows += 1
        st.last_value = value
        if direction == "ceiling":
            ok = value <= bound
            margin = (bound - value) / bound
        else:
            ok = value >= bound
            margin = (value - bound) / max(bound, 1e-12)
        reg = telemetry.registry()
        reg.gauge("ff_slo_margin",
                  "normalized SLO headroom (positive = within budget)",
                  labels=("slo", "replica")).labels(
            name, replica).set(round(margin, 6))
        if not ok:
            st.breached = True
            st.ok_streak = 0
            self.breaches_fired += 1
            reg.counter("ff_slo_breach_total",
                        "SLO windows judged in breach",
                        labels=("slo", "replica")).labels(
                name, replica).inc()
            ev = {"slo": name, "replica": replica,
                  "value": round(value, 6), "bound": bound,
                  "direction": direction, "samples": samples}
            events.append(ev)
            fflogger.warning(
                "SLO BREACH: %s replica=%s value=%.6g bound=%.6g "
                "(%s, %d samples in window)", name, replica, value,
                bound, direction, samples)
            telemetry.annotate("slo_breach", slo=name, replica=replica,
                               value=round(value, 6), bound=bound)
            if self.trip_recorder:
                _recorder.trip("slo_breach", **ev)
        elif st.breached:
            st.ok_streak += 1
            if st.ok_streak >= self.clear_windows:
                st.breached = False
                st.ok_streak = 0
                fflogger.warning(
                    "SLO clear: %s replica=%s back within budget "
                    "(%d healthy windows)", name, replica,
                    self.clear_windows)
                telemetry.annotate("slo_clear", slo=name,
                                   replica=replica,
                                   value=round(value, 6))
        reg.gauge("ff_slo_status",
                  "1 = within budget, 0 = in breach",
                  labels=("slo", "replica")).labels(
            name, replica).set(0 if st.breached else 1)

    def _rebaseline_locked(self):
        """Snapshot every currently-known series so pre-configure
        history is invisible to the first judgement."""
        reg = telemetry.registry()
        for name in self.specs:
            _n, _k, _d, kind, keys = self._by_name[name]
            if kind != "hist_p99":
                continue
            for fam_name in keys:
                fam = reg.family(fam_name)
                if fam is None:
                    continue
                for ch in fam.children():
                    labels = dict(ch.labels)
                    sid = (name, fam_name, ch.labels)
                    self._state[sid] = _SeriesState(
                        (list(ch.counts), ch.count),
                        labels.get("replica",
                                   "fleet" if not labels else "?"))
        for name in self.specs:
            _n, _k, _d, kind, keys = self._by_name[name]
            if kind != "ratio":
                continue
            for fn in self._sources.live():
                try:
                    replica, counters = fn()
                except Exception:
                    continue
                sid = (name, "source", str(replica))
                self._state[sid] = _SeriesState(
                    (int(counters.get(keys[0], 0)),
                     int(counters.get(keys[1], 0))), str(replica))

    # ---- introspection ----------------------------------------------------

    def breaches(self) -> List[Dict]:
        """Series currently in breach (hysteresis not yet cleared)."""
        with self._lock:
            out = []
            for (name, _src, _key), st in self._state.items():
                if st.breached:
                    out.append({
                        "slo": name,
                        "replica": st.replica,
                        "value": st.last_value,
                        "bound": self.specs.get(name),
                        "ok_streak": st.ok_streak,
                        "windows": st.windows})
            return out

    def describe(self) -> Dict:
        """Full monitor state — the ``/slo.json`` body."""
        with self._lock:
            series = []
            for (name, src, key), st in self._state.items():
                labels = dict(key) if isinstance(key, tuple) \
                    and key and isinstance(key[0], tuple) else \
                    {"replica": str(key)}
                labels["replica"] = st.replica
                series.append({
                    "slo": name, "series": src,
                    "labels": labels,
                    "value": st.last_value,
                    "bound": self.specs.get(name),
                    "breached": st.breached,
                    "ok_streak": st.ok_streak,
                    "windows": st.windows})
            return {
                "window_s": self.window_s,
                "clear_windows": self.clear_windows,
                "trip_recorder": self.trip_recorder,
                "specs": dict(self.specs),
                "evaluations": self.evaluations,
                "breaches_fired": self.breaches_fired,
                "series": series,
                "breaches": [s for s in series if s["breached"]],
            }


# --------------------------------------------------------------- HBM ledger


class HBMLedger:
    """Per-subsystem device-memory accounting. Sources are weakly-held
    callables ``fn() -> (name, {subsystem: bytes})`` (engines: KV pool
    incl. host tier, adapter pool, serving weights; the model: params,
    optimizer state). Published as ``ff_hbm_bytes{source,subsystem}``
    series by a registry collector at every scrape, embedded in every
    post-mortem bundle, and cross-checked against fflint's footprint
    pass (``ff_hbm_lint_estimated_bytes`` — the model stashes the
    ``hbm-footprint`` estimate its compile-time lint already computed)."""

    def __init__(self):
        self._lock = locks.make_lock("hbm-ledger")
        self._sources = _WeakCallables()
        self._registered_on = None
        self.lint_estimated_bytes: Optional[float] = None

    def add_source(self, fn: Callable[[], Tuple[str, Dict[str, int]]]):
        self._sources.register(fn)
        self._ensure_collector()

    def set_lint_estimate(self, est_bytes: Optional[float]):
        with self._lock:
            self.lint_estimated_bytes = (float(est_bytes)
                                         if est_bytes is not None
                                         else None)
        self._ensure_collector()

    def _ensure_collector(self):
        reg = telemetry.registry()
        with self._lock:
            if self._registered_on is reg:
                return
            self._registered_on = reg
        reg.add_collector(self._collect)

    def snapshot(self) -> Dict:
        with self._lock:
            lint = self.lint_estimated_bytes
        sources: Dict[str, Dict[str, int]] = {}
        for fn in self._sources.live():
            try:
                name, subs = fn()
            except Exception:
                continue
            row = sources.setdefault(str(name), {})
            for k, v in subs.items():
                row[str(k)] = int(v)
        total = sum(v for subs in sources.values()
                    for v in subs.values())
        out = {"sources": sources, "total_tracked_bytes": total,
               "device": device_memory_stats()}
        if lint is not None:
            out["lint_estimated_bytes"] = lint
            out["lint_vs_tracked_ratio"] = round(
                lint / max(total, 1), 4)
        return out

    def _collect(self, reg):
        if not _on():
            return
        snap = self.snapshot()
        fam = reg.gauge("ff_hbm_bytes",
                        "tracked device/host memory by subsystem "
                        "(the memory-objective search's per-pool ledger)",
                        labels=("source", "subsystem"))
        for name, subs in snap["sources"].items():
            for k, v in subs.items():
                fam.labels(name, k).set(v)
        reg.gauge("ff_hbm_total_tracked_bytes",
                  "sum of every tracked ff_hbm_bytes subsystem").set(
            snap["total_tracked_bytes"])
        if "lint_estimated_bytes" in snap:
            reg.gauge("ff_hbm_lint_estimated_bytes",
                      "fflint hbm-footprint pass estimate (cross-check "
                      "against the tracked ledger)").set(
                snap["lint_estimated_bytes"])
        dev = reg.gauge("ff_hbm_device_bytes",
                        "backend device_memory_stats, where available",
                        labels=("device", "stat"))
        for d, stats in snap["device"].items():
            for k, v in stats.items():
                dev.labels(d, k).set(v)


def device_memory_stats() -> Dict[str, Dict[str, float]]:
    """Backend memory stats per local device (``Device.memory_stats``),
    where the backend exposes them (TPU/GPU; CPU typically returns
    nothing). Never raises, never initializes a backend that isn't up."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        import jax

        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                continue
            if not ms:
                continue
            out[f"{d.platform}:{d.id}"] = {
                k: float(v) for k, v in ms.items()
                if isinstance(v, (int, float))}
    except Exception:
        pass
    return out


# ---------------------------------------------------------- health rollup

_health_sources = _WeakCallables()


def register_health_source(fn: Callable[[], Dict]):
    """Register a lock-free/cheap health probe (``ServingRouter.health``
    for fleets; an engine's load probe solo) consumed by the
    ``/healthz`` rollup. Weakly referenced."""
    _health_sources.register(fn)


def health_rollup() -> Dict:
    """Fleet health: ``ok`` | ``degraded`` | ``breach`` with per-SLO
    reasons — the ``/healthz`` body. Evaluation rides the SLO monitor's
    own window cadence (``maybe_evaluate``); the probes themselves are
    the lock-free/cheap ones, so this never compiles and never blocks
    behind a mid-tick replica."""
    _slo.maybe_evaluate()
    breaches = _slo.breaches()
    fleet = []
    degraded: List[str] = []
    for fn in _health_sources.live():
        try:
            row = fn()
            if not isinstance(row, dict):
                row = {"value": _jsonable(row)}
        except Exception as e:  # noqa: BLE001
            row = {"error": f"{type(e).__name__}: {e}"}
            degraded.append("health probe failed")
        fleet.append(_jsonable(row))
        if row.get("fenced", 0):
            degraded.append(f"{row['fenced']} replica(s) fenced")
        if row.get("status") in ("dead", "draining"):
            degraded.append(f"fleet status {row['status']}")
        if row.get("deploying"):
            # a rolling deploy is a PLANNED capacity dip: degraded
            # (operators see it), never a breach (nothing is wrong)
            degraded.append("rolling deploy in progress")
        alive, total = row.get("alive"), row.get("replicas")
        if alive is not None and total is not None and alive < total:
            degraded.append(f"{total - alive}/{total} replicas down")
    slos = {name: "ok" for name in _slo.specs}
    for b in breaches:
        name = b["slo"]
        cur = slos.get(name)
        if not isinstance(cur, list):
            slos[name] = []
        slos[name].append({k: b[k] for k in
                           ("replica", "value", "bound")})
    status = ("breach" if breaches
              else "degraded" if degraded else "ok")
    return {
        "status": status,
        "slos": slos,
        "breaches": breaches,
        "degraded_reasons": sorted(set(degraded)),
        "fleet": fleet,
        "recorder": _recorder.stats(),
    }


# ------------------------------------------------------------- process-wide

_recorder = FlightRecorder()
_slo = SLOMonitor()
_hbm = HBMLedger()
_log_ring = LogRing()
# the log window is ALWAYS on (the docstring's contract): a bundle
# written before any configure() — an env-FF_FLIGHT_DIR auto trigger
# during model build, a manual dump in an engine-less process — still
# carries recent logs
_ensure_log_handler()


def recorder() -> FlightRecorder:
    return _recorder


def slo_monitor() -> SLOMonitor:
    return _slo


def hbm_ledger() -> HBMLedger:
    return _hbm


def log_ring() -> LogRing:
    return _log_ring


def configure(cfg):
    """Wire the recorder, SLO monitor and HBM ledger from one FFConfig
    (engines, routers, supervisors and ``fit()`` all call this — last
    configure wins). Also installs the log-ring handler once."""
    _ensure_log_handler()
    _recorder.configure(cfg)
    _slo.configure(cfg)
    _hbm._ensure_collector()


def trip(cause: str, exc: Optional[BaseException] = None, **args):
    """Module-level trigger shorthand (what every trigger site calls)."""
    _recorder.trip(cause, exc=exc, **args)


def dump(cause: str = "manual", directory: Optional[str] = None,
         **args) -> Optional[str]:
    return _recorder.dump(cause, directory=directory, **args)


def reset():
    """Fresh singletons (tests). Sources, pending triggers and SLO state
    registered against the old objects are dropped; the log handler
    stays installed and feeds the new ring."""
    global _recorder, _slo, _hbm, _log_ring, _health_sources, _enabled
    t = _recorder._timer
    if t is not None:
        t.cancel()
    _recorder = FlightRecorder()
    _slo = SLOMonitor()
    _hbm = HBMLedger()
    _log_ring = LogRing()
    _health_sources = _WeakCallables()
    _enabled = True
