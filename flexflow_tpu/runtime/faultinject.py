"""Deterministic fault injection for resilience testing.

The reference has no failure story at all (SURVEY §5.4) — and code paths
that only run during a real outage are code paths that have never run.
This module lets every resilience path (NaN loss, preemption SIGTERM,
checkpoint IO failure, hung step) be triggered deterministically on CPU in
tier-1 tests, driven by one env var:

    FF_FAULT=nan_loss@step:7,sigterm@step:12,io_fail@save:1

Grammar: comma-separated ``kind[(value)]@site:index`` events.

  kind   free-form token consumed by the subsystem that checks it
         (``nan_loss``, ``sigterm``, ``io_fail``, ``hang``,
         ``corrupt_ckpt``, ``shrink`` …), optionally carrying one integer
         parameter in parentheses (``shrink(2)`` = shrink to 2 devices) —
         read back via ``FaultPlan.last_value`` after a match
  site   where the event fires. ``step`` is special: *index* is the 1-based
         global training step (compared against the step counter).
         ``replica`` is identity-indexed: *index* names the serving
         replica (0-based, the router's replica id), so
         ``crash@replica:0`` fells exactly replica 0 — checked with
         ``pending()``/``at_site()``, never occurrence-counted. Every
         other site (``save``, ``load``, ``data``, ``resume``,
         ``serve`` …) is occurrence-counted: *index* is the 1-based call
         count at that site, so ``io_fail@save:1`` fails exactly the
         first checkpoint save.

Duplicate kinds are allowed (``nan_loss@step:3,nan_loss@step:4`` injects
two consecutive NaNs); a range ``nan_loss@step:3-5`` expands to one event
per step.

Consumers:
  * ``TrainSupervisor`` checks ``at_step("nan_loss"|"sigterm"|"hang", n)``
    each step (runtime/resilience.py);
  * ``checkpoint.save_checkpoint``/``restore_checkpoint`` call
    ``maybe_fail("io_fail", "save"|"load")`` inside their retry wrapper;
  * ``checkpoint.save_checkpoint`` checks ``corrupt_ckpt@save:<n>`` AFTER
    the n-th save publishes and flips bytes in its payload (bitrot /
    torn-write drill for the integrity manifest, runtime/elastic story);
  * the launcher and ``runtime/elastic.py`` check ``shrink(<k>)@resume:<n>``
    on the n-th resume and present only ``k`` visible devices
    (``_env.force_cpu_devices`` in a fresh process; a capped count when
    the backend is already up) — the changed-topology drill;
  * ``runtime/router.py`` drives the fleet-failover drills:
    ``crash@replica:<r>`` kills replica *r*'s driver thread and
    ``hang@replica:<r>`` wedges it past the health timeout — both fire at
    the replica's first scheduler tick with live work, or at its
    *value*-th such tick with ``crash(<tick>)@replica:<r>`` (the router
    peeks with ``pending()`` and consumes with ``at_site()`` when its own
    tick counter reaches the trigger);
  * ``ServingEngine._admit`` checks ``slow(<ms>)@serve:<n>`` and stalls
    the n-th admission host-side by ``<ms>`` — the slow-replica drill
    that expires an in-flight deadline deterministically;
  * the tiered prefix cache (``runtime/serving.py RadixPrefixCache``)
    checks ``d2h_fail@migrate:<n>`` on the n-th HBM->host demotion (the
    page dies exactly as it would without a host tier) and
    ``h2d_fail@promote:<n>`` on the n-th host->HBM promotion (the host
    copy is killed and admission falls back to cold prefill) — neither
    may stall the scheduler or mount a corrupt page;
  * the rolling-deploy plane (ISSUE 17) drives three drills:
    ``runtime/deploy.py WeightArtifactRegistry.publish`` checks
    ``corrupt_ckpt@publish:<n>`` AFTER the n-th artifact lands in the
    watch path and flips bytes in it (the torn-artifact drill — the
    deployer's manifest verify must refuse the roll before any replica
    is touched); ``ServingEngine.swap_weights`` checks
    ``swap_fail@deploy:<n>`` via ``maybe_fail`` AFTER installing the new
    weights (the torn mid-swap drill — the engine restores the prior
    version and the deployer rolls the whole deploy back); and
    ``ServingEngine._admit`` checks ``slow(<ms>)@canary:<n>`` ONLY while
    the engine is the deploy canary, stalling its admissions by ``<ms>``
    — the deterministic canary SLO-breach drill that must end in an
    automatic rollback plus a post-mortem bundle naming the breached
    SLO;
  * the elastic fleet (ISSUE 20) drives the preemption drills:
    ``runtime/router.py`` checks ``preempt(<deadline_ms>)@replica:<r>``
    at replica *r*'s first busy tick (identity-indexed, like ``crash``)
    and delivers a SIGTERM-equivalent preemption — the replica races
    the ``<deadline_ms>`` evacuation deadline (FFConfig.
    preempt_deadline_s when omitted); and the evacuation loop checks
    ``slow_evac(<ms>)@evacuate:<n>`` (occurrence-counted) to stall the
    n-th prefix-slab export by ``<ms>``, so the deadline-starved
    fallback (fence + cold resubmit) is deterministically drillable.

The active plan is parsed lazily from ``FF_FAULT`` and re-parsed (with
occurrence counters reset) whenever the env value changes; tests that
reuse a spec should call ``reset()`` between runs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple


def _annotate(kind: str, site: str, index: int,
              value: Optional[int] = None):
    """Report a FIRED injection to the telemetry plane (an instant
    ``fault`` trace annotation + ``ff_fault_fired_total`` counter), so
    every drill's trace shows exactly where the fault landed — asserted
    by router_smoke/disagg_smoke/obs_smoke. Deferred import (telemetry
    never imports this module back) and best-effort: injection must
    work even if telemetry is torn down mid-test."""
    try:
        from flexflow_tpu.runtime import telemetry

        telemetry.annotate("fault", kind=kind, site=site, index=index,
                           value=value)
    except Exception:
        pass
    try:
        # every fired injection is also a flight-recorder trigger
        # (runtime/flightrec.py): a no-op unless a bundle directory is
        # configured, debounced/cooled-down so a drill's fault storm
        # yields one post-mortem bundle naming every cause
        from flexflow_tpu.runtime import flightrec

        flightrec.trip("fault", kind=kind, site=site, index=index,
                       value=value)
    except Exception:
        pass


class InjectedFault(OSError):
    """Raised by ``maybe_fail``: an IO-flavored injected failure (OSError
    subclass so generic retry(retryable=(OSError,)) policies cover it)."""


class FaultPlan:
    def __init__(self, events: List[Tuple[str, str, int]],
                 values: Optional[Dict[Tuple[str, str, int], int]] = None):
        # [(kind, site, index), ...] — index is a step number for
        # site == "step", a 1-based occurrence count otherwise. Events
        # stay 3-tuples (existing consumers pattern-match them); an
        # optional integer parameter (``shrink(2)@resume:1``) rides in
        # `values`, surfaced through `last_value` after a match.
        self.events = list(events)
        self.values: Dict[Tuple[str, str, int], int] = dict(values or {})
        # parameter of the most recent matched event (at_step/fire); None
        # when the event carried no parameter
        self.last_value: Optional[int] = None
        self._counts: Dict[Tuple[str, str], int] = {}
        self._consumed: set = set()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        import re

        events: List[Tuple[str, str, int]] = []
        values: Dict[Tuple[str, str, int], int] = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            kind, at, rest = part.partition("@")
            site, colon, idx = rest.partition(":")
            if not at or not colon or not kind or not site:
                raise ValueError(
                    f"FF_FAULT entry {part!r}: expected 'kind@site:index' "
                    f"(e.g. nan_loss@step:7)")
            value = None
            m = re.fullmatch(r"([A-Za-z_][\w-]*)(?:\((\d+)\))?", kind)
            if not m:
                raise ValueError(
                    f"FF_FAULT entry {part!r}: kind must be a bare token "
                    f"or 'kind(value)' with an integer value "
                    f"(e.g. shrink(2)@resume:1), got {kind!r}")
            kind = m.group(1)
            if m.group(2) is not None:
                value = int(m.group(2))
            lo, dash, hi = idx.partition("-")
            try:
                lo_i = int(lo)
                hi_i = int(hi) if dash else lo_i
            except ValueError:
                raise ValueError(
                    f"FF_FAULT entry {part!r}: index must be an integer "
                    f"or range 'lo-hi', got {idx!r}") from None
            if hi_i < lo_i:
                raise ValueError(f"FF_FAULT entry {part!r}: empty range")
            for i in range(lo_i, hi_i + 1):
                events.append((kind, site, i))
                if value is not None:
                    values[(kind, site, i)] = value
        return cls(events, values)

    def at_site(self, kind: str, site: str, index: int) -> bool:
        """Identity-indexed one-shot check: True when the plan holds
        ``kind@site:<index>`` where *index* names a thing (a step number,
        a replica id) rather than a call count. A fired event is
        consumed, so it happens exactly once; ``last_value`` carries its
        parameter."""
        ev = (kind, site, int(index))
        if ev in self.events and ev not in self._consumed:
            self._consumed.add(ev)
            self.last_value = self.values.get(ev)
            _annotate(kind, site, int(index), self.last_value)
            return True
        return False

    def pending(self, kind: str, site: str,
                index: int) -> Tuple[bool, Optional[int]]:
        """(scheduled, value) for an identity-indexed event WITHOUT
        consuming it. Callers that trigger on their own clock — the
        router fires ``crash@replica:<r>`` at the replica's value-th
        busy tick — peek here each tick and consume with ``at_site()``
        only when their trigger condition is met."""
        ev = (kind, site, int(index))
        if ev in self.events and ev not in self._consumed:
            return True, self.values.get(ev)
        return False, None

    def at_step(self, kind: str, step: int) -> bool:
        """True when the plan holds ``kind@step:<step>``. One-shot: a
        fired event is consumed, so a supervisor rewind that re-executes
        the step does not re-inject (the fault "happened" once)."""
        return self.at_site(kind, "step", step)

    def has_step_events(self, *kinds: str) -> bool:
        """Does the plan schedule any step-site event of these kinds?
        (Unconsumed only.) Callers with chunked step counters use this to
        fall back to per-step execution so injection can actually land."""
        return any(k in kinds and s == "step" and (k, s, i) not in
                   self._consumed for k, s, i in self.events)

    def in_step_range(self, kind: str, lo: int, hi: int) -> bool:
        """True when the plan holds ``kind@step:i`` with lo < i <= hi.
        Needed by callers whose step counter advances in chunks (fit's
        scanned multi-step program jumps scan_steps at a time) — exact
        equality would silently skip events landing inside a chunk.
        Consumes every matched event (one-shot, like at_step)."""
        fired = False
        for ev in self.events:
            k, s, i = ev
            if (k == kind and s == "step" and lo < i <= hi
                    and ev not in self._consumed):
                self._consumed.add(ev)
                _annotate(kind, "step", i)
                fired = True
        return fired

    def fire(self, kind: str, site: str) -> bool:
        """Occurrence-counted sites: increments the (kind, site) call
        counter and reports whether this occurrence is scheduled to fail.
        Only counts when the plan mentions (kind, site) at all, so an
        unrelated plan never accumulates counters."""
        if not any(k == kind and s == site for k, s, _ in self.events):
            return False
        key = (kind, site)
        self._counts[key] = n = self._counts.get(key, 0) + 1
        if (kind, site, n) in self.events:
            self.last_value = self.values.get((kind, site, n))
            _annotate(kind, site, n, self.last_value)
            return True
        return False

    def __bool__(self) -> bool:
        return bool(self.events)

    def __repr__(self) -> str:
        return f"FaultPlan({self.events!r})"


_plan: Optional[FaultPlan] = None
_plan_spec: Optional[str] = None


def active_plan() -> FaultPlan:
    """The process-wide plan from ``FF_FAULT``. Re-parsed (counters reset)
    whenever the env value changes, so monkeypatched tests see fresh
    state; identical spec across tests needs an explicit reset()."""
    global _plan, _plan_spec
    spec = os.environ.get("FF_FAULT", "")
    if _plan is None or spec != _plan_spec:
        _plan = FaultPlan.parse(spec)
        _plan_spec = spec
    return _plan


def reset():
    """Drop the cached plan and its occurrence counters."""
    global _plan, _plan_spec
    _plan = None
    _plan_spec = None


def maybe_fail(kind: str, site: str):
    """Raise InjectedFault when the active plan schedules this occurrence
    of (kind, site). Call sites place this INSIDE their retry wrapper so
    the retry path itself is what gets exercised."""
    if active_plan().fire(kind, site):
        raise InjectedFault(
            f"injected fault: {kind}@{site} (FF_FAULT)")
