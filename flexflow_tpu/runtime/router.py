"""Fleet serving: a router over N ServingEngine replicas.

One ServingEngine is a replica, not a service: nothing survives the loss
of an engine, nothing bounds how long a request can wait, and an
overloaded queue grows without limit. The paper's discipline — drive
placement from MEASURED behavior of the real machine, not static
assignment (PAPERS.md "Beyond Data and Model Parallelism") — applies one
level up: this router routes, sheds and fails over on the live
``health()``/``load()`` signals each replica already exports.

``ServingRouter`` fronts N replicas, each driven by its own thread:

  * FAILOVER — a replica whose driver thread raises (a crashed engine),
    that stops heartbeating past ``health_timeout_s`` (a hung dispatch),
    or whose health probe itself dies is FENCED: its in-flight and
    engine-queued requests are resubmitted to survivors exactly once.
    Greedy decode is deterministic and an un-admitted request keeps no
    cache state (the PR-5 drain/requeue contract), so a resubmitted
    request re-decodes from scratch on the survivor and its final stream
    is token-identical to an uninterrupted single-replica run — the dead
    replica's partial tokens are discarded, never spliced. A request
    whose SECOND replica also dies fails loudly ("replica lost twice")
    instead of ping-ponging.
  * PER-REQUEST DEADLINES — ``submit(..., deadline_s=)``. A request that
    expires while queued (in the router queue OR a replica's engine
    queue) retires as ``"timeout"`` without ever prefilling; an expired
    request found in-flight on a FENCED replica is not resubmitted (the
    work is already worthless); an admitted request on a healthy replica
    is never cancelled mid-batch (cancellation would disturb the
    fixed-shape slot program) — its late completion is delivered and the
    caller may discard it.
  * OVERLOAD SHEDDING — the router queue is bounded by ``max_queue``
    (FFConfig.serve_max_queue; 0 = unbounded). A submit over the bound
    returns immediately with state ``"rejected"``: excess load fails in
    microseconds at the front door, so ACCEPTED requests keep a bounded
    queue wait and the fleet's p99 TTFT stays flat instead of every
    request sharing an ever-growing backlog (bench `router_serving`
    measures exactly this).
  * HEALTH-DRIVEN PLACEMENT — dispatch picks the least-loaded live
    replica by the same counters ``health()`` exports (active slots +
    queued work, read via the router's own outstanding ledger plus the
    engine's lock-free ``load()``), with PREFIX AFFINITY on top: the
    first full KV page of the prompt (exactly the radix trie's first
    edge, so equal keys <=> a guaranteed trie hit) is hashed to the
    replica that last served it. Shared-prompt traffic therefore lands
    where its prefix pages are already cached instead of re-prefilling
    the same system prompt on every replica. Affinity is a preference,
    never a constraint — a fenced or saturated home replica falls back
    to least-loaded, so affinity can neither black-hole nor starve.

  * ROLE-SPLIT DISAGGREGATION (ISSUE 12) — replicas carry a role:
    ``mixed`` (the default: every replica does everything, bit-identical
    to the pre-role fleet), ``prefill`` or ``decode``. The paper's core
    claim — role-specialized placement beats treating every device
    identically (the Operator/Parameter split of "Beyond Data and Model
    Parallelism") — applied to serving: one bursty long-prompt admission
    on a mixed fleet stalls decode slot occupancy fleet-wide, so
    prefill-heavy replicas absorb long-prompt admission
    (``handoff_min_pages`` full pages or more) and HAND OFF the finished
    prompt's KV pages + quantized scales to a decode replica as a
    serialized page slab (ServingEngine.prefill_into_cache ->
    export_prefix_slab -> import_prefix_slab: the paged pool is the
    serialization boundary, decode-side ingestion is a page scatter +
    trie publish through one fixed-shape writer, and the decode
    replica's submit admits as a prefix HIT — the handoff moves pages,
    never tokens, so greedy streams stay token-identical). Placement is
    role- and queue-depth-aware least-loaded; every role preference
    falls back (a dead prefill tier downgrades work to the cold path on
    decode replicas; a fleet with only prefill replicas alive decodes
    there) so the split can never strand work. Prefix affinity gains a
    TIER dimension: the home replica's engine reports depth-1
    demotions/promotions (drain_tier_events), so an affinity entry
    whose pages demoted to the host tier keeps routing home (promotion
    beats recompute) and only drops when the prefix dies in both tiers.

  * ELASTIC MEMBERSHIP (ISSUE 20) — the fleet breathes at runtime:
    ``add_replica()`` builds, adapter-replays and warms a new engine off
    the router lock and admits it atomically; ``remove_replica()``
    retires one, requeueing its never-admitted work (the PR-5 drain
    contract's missing half) and handing its cached prefix paths to
    survivors as page slabs under their original namespaces. Preemption
    is a first-class event: SIGTERM / ``request_preempt()`` / the
    FF_FAULT ``preempt`` drill race a configurable deadline to evacuate
    queued + in-flight requests (clean ownership transfer — no loss
    counted, so "evacuated then failed-over" still completes exactly
    once) and hot prefix slabs; a blown deadline degrades to the
    ordinary fence, resubmitting the remainder cold. runtime/autoscale.py
    drives scale decisions from the SLO monitor's breach windows.

Failure drills are deterministic in CI via FF_FAULT
(runtime/faultinject.py): ``crash@replica:<r>`` kills replica r's driver
at its first busy tick (``crash(<t>)@replica:<r>`` at its t-th),
``hang@replica:<r>`` wedges it until the heartbeat sweep fences it,
``slow(<ms>)@serve:<n>`` stalls an engine admission so an in-flight
deadline expires on cue, ``preempt(<deadline_ms>)@replica:<r>`` delivers
a SIGTERM-equivalent preemption with that evacuation deadline, and
``slow_evac(<ms>)@evacuate:<n>`` stalls the n-th evacuation slab export
so the deadline fallback is deterministically drillable.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.logger import fflogger
from flexflow_tpu.ops import sampling as sampling_ops
from flexflow_tpu.runtime import faultinject, flightrec, locks, telemetry
from flexflow_tpu.runtime.serving import RadixPrefixCache, version_ns


class ReplicaCrash(RuntimeError):
    """Injected replica loss (FF_FAULT ``crash@replica:<r>``): raised on
    the replica's driver thread to simulate the whole engine dying
    mid-dispatch."""


# process-wide router ids: trace ids must be unique across fleets in one
# process (two routers both start their rids at 0)
_ROUTER_IDS = iter(range(1 << 30))


def _slab_nbytes(slab: Dict) -> int:
    """Host bytes a page slab's payload actually moves (the evacuation
    cost the bench stamps and the placement advisor prices)."""
    total = 0
    stack = [slab.get("payload")]
    while stack:
        x = stack.pop()
        if isinstance(x, np.ndarray):
            total += x.nbytes
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
    return total


@dataclass
class FleetRequest:
    """One router-level request and its lifecycle record. The underlying
    engine Request is replaced wholesale on failover — ``tokens`` always
    holds ONE replica's complete stream, never a splice."""

    rid: int
    prompt: np.ndarray              # (S,) int32
    max_new_tokens: int
    # per-request sampling config + LoRA adapter (ISSUE 14): assigned
    # at ROUTER submit (the seed defaults to the fleet rid) so a
    # failover resubmission replays the identical counter-based sample
    # stream on the survivor — sampled streams are as failover-stable
    # as greedy ones
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0
    adapter: Optional[str] = None
    # absolute time.perf_counter() deadline (None = none)
    deadline: Optional[float] = None
    # first full KV page of the prompt (the radix trie's first edge);
    # None when the prompt is shorter than one page
    affinity: Optional[Tuple[int, ...]] = None
    # queued | dispatched | done | failed | timeout | rejected
    state: str = "queued"
    replica: int = -1               # current/last replica
    attempts: int = 0               # dispatches (a clean role-split
    #                                 handoff uses 2: prefill + decode)
    losses: int = 0                 # replicas that died under this
    #                                 request (the exactly-once cap: 2)
    # role-split lifecycle: "direct" = the classic single-dispatch path;
    # "prefill" = headed to a prefill replica for prefill-only + slab
    # export; "decode" = slab in hand, headed to a decode replica
    phase: str = "direct"
    slab: Optional[Dict] = None     # exported page slab (host bytes)
    handoff: bool = False           # ever routed through a prefill tier
    tokens: List[int] = field(default_factory=list)
    error: str = ""
    t_submit: float = 0.0
    ttft: float = 0.0               # router submit -> first token (s)
    t_done: float = 0.0
    # telemetry: the fleet-wide trace id every span of this request
    # carries (it survives resubmission and the prefill->decode
    # handoff), and the open root-span handle closed at settlement
    trace_id: str = ""
    root_span: int = 0

    @property
    def output(self) -> np.ndarray:
        """prompt + emitted tokens (the generate() shape)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def settled(self) -> bool:
        return self.state not in ("queued", "dispatched")


class ServingRouter:
    """Route requests over N ServingEngine replicas of one model.

    Each replica runs on its own daemon thread; the lock order is
    router -> engine, and an engine's lock is only ever taken by its own
    driver thread (plus warmup/drain when the fleet is quiet), so the
    two layers can never deadlock. ``submit()``/``run()`` from any
    thread; ``drain()`` for graceful shutdown, ``close()`` to abandon.

    ``start=False`` builds the fleet without spawning drivers (requests
    queue, shed and expire deterministically — the test hook);
    ``start()``/``run()`` bring the drivers up."""

    # the hang detector cannot distinguish a wedged dispatch from a
    # legitimately long one by wall clock alone, and a COLD tick
    # compiles its program (seconds, minutes on a real TPU pod) — so the
    # default timeout is sized for cold compiles. Latency-sensitive
    # fleets warmup() every replica first, after which a healthy tick is
    # milliseconds and a tight timeout (the drill tests run 0.5 s) is
    # meaningful.
    DEFAULT_HEALTH_TIMEOUT_S = 60.0

    ROLES = ("prefill", "decode", "mixed")

    def __init__(self, model, replicas: int = 2,
                 max_queue: Optional[int] = None,
                 health_timeout_s: Optional[float] = None,
                 dispatch_backlog: Optional[int] = None,
                 roles=None, handoff_min_pages: int = 1,
                 seq_parallel_shards: Optional[int] = None,
                 start: bool = True, **engine_kwargs):
        if health_timeout_s is None:
            health_timeout_s = self.DEFAULT_HEALTH_TIMEOUT_S
        if replicas < 1:
            raise ValueError(f"replicas={replicas}: must be >= 1")
        if health_timeout_s <= 0:
            raise ValueError(
                f"health_timeout_s={health_timeout_s}: must be > 0")
        cfg = model.config
        # adopt FFConfig.sanitize before the replica engines (and
        # this router's own lock) are created — lock proxying is
        # decided at creation time (runtime/locks.py)
        locks.configure(cfg)
        self.model = model
        self.n = int(replicas)
        # replica roles (ISSUE 12): default "mixed" for every replica —
        # bit-identical to the pre-role fleet, so existing tests, benches
        # and smokes measure the same machine. A per-replica list (or
        # FFConfig.serve_replica_roles as "prefill,decode,decode") turns
        # on the disaggregated placement + handoff below.
        raw = (roles if roles is not None
               else getattr(cfg, "serve_replica_roles", "") or "")
        if isinstance(raw, str):
            role_list = [t.strip() for t in raw.split(",") if t.strip()]
        else:
            role_list = [str(t) for t in raw]
        if not role_list:
            role_list = ["mixed"] * self.n
        if len(role_list) != self.n:
            raise ValueError(
                f"roles={role_list}: need one role per replica "
                f"({self.n}), one of {self.ROLES}")
        bad = [t for t in role_list if t not in self.ROLES]
        if bad:
            raise ValueError(
                f"roles={role_list}: unknown role(s) {bad} — each must "
                f"be one of {self.ROLES}")
        if all(t == "prefill" for t in role_list):
            raise ValueError(
                f"roles={role_list}: a fleet of only prefill replicas "
                f"has nowhere to decode — include a 'decode' or "
                f"'mixed' replica")
        self.roles = role_list
        self.handoff_min_pages = int(handoff_min_pages)
        if self.handoff_min_pages < 1:
            raise ValueError(
                f"handoff_min_pages={handoff_min_pages}: must be >= 1")
        # sequence-parallel prefill (ISSUE 18): split a monster prompt's
        # page-aligned prefix into contiguous shards across the prefill
        # tier; the decode replica merges the shard slabs through
        # partial-prefix import_prefix_slab. 0/1 = off.
        self.seq_parallel_shards = int(
            seq_parallel_shards if seq_parallel_shards is not None
            else getattr(cfg, "seq_parallel_shards", 0) or 0)
        if self.seq_parallel_shards < 0 or self.seq_parallel_shards == 1:
            raise ValueError(
                f"seq_parallel_shards={self.seq_parallel_shards}: must "
                f"be 0 (off) or >= 2 (shard count)")
        self.max_queue = int(max_queue if max_queue is not None
                             else getattr(cfg, "serve_max_queue", 0))
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue={self.max_queue}: must be >= 0 (0 = unbounded)")
        self.health_timeout_s = float(health_timeout_s)
        # kept verbatim for live scale-out (ISSUE 20): add_replica()
        # builds its engine with the SAME kwargs the fleet was built
        # with, so a scaled-out replica is indistinguishable from a
        # founding one
        self._engine_kwargs = dict(engine_kwargs)
        self.engines = [model.make_serving_engine(**engine_kwargs)
                        for _ in range(self.n)]
        self.page_size = self.engines[0].page_size
        slots = self.engines[0].slots
        # outstanding-per-replica cap: slots in flight + a short engine
        # queue so admission can pipeline, but deep backlogs stay in the
        # ROUTER queue where deadlines expire before dispatch and a
        # fence requeues cheaply
        self.dispatch_backlog = int(dispatch_backlog
                                    if dispatch_backlog is not None
                                    else slots)
        self._cap = slots + self.dispatch_backlog
        # the role split hands off through the radix trie: without it a
        # prefill replica has nowhere to publish, so the fleet quietly
        # degrades to direct placement (roles still shape placement)
        self._handoff_capable = (
            any(t == "prefill" for t in self.roles)
            and self.engines[0].prefix_cache is not None)

        self._lock = locks.make_rlock("router")
        self._queue: collections.deque = collections.deque()  # FleetRequest
        # rid -> (FleetRequest, engine Request | None): None until the
        # replica's driver hands the request to its engine
        self._outstanding: List[Dict] = [dict() for _ in range(self.n)]
        self._to_submit: List[collections.deque] = [
            collections.deque() for _ in range(self.n)]
        # prefix chunk -> replica that last served it (bounded LRU: the
        # map must not grow with total distinct-prompt traffic)
        self._affinity: "collections.OrderedDict" = collections.OrderedDict()
        self._affinity_cap = 4096
        self._fenced = [False] * self.n
        self._fence_reason = [""] * self.n
        self._heartbeat = [time.monotonic()] * self.n
        self._busy_ticks = [0] * self.n
        self._stop = threading.Event()
        self._draining = False
        # rolling deploy (ISSUE 17): a SUSPENDED replica is alive (its
        # driver keeps ticking, it is never fenced) but receives no new
        # dispatches — the deployer's drain-swap-warmup window. The
        # deploying flag degrades (not breaches) the /healthz rollup
        # while a roll is in progress.
        self._suspended = [False] * self.n
        self._deploying = False
        self._swaps_completed = 0
        self._rollbacks = 0
        # elastic fleet (ISSUE 20): a RETIRED replica left the fleet
        # cleanly (scale-in or evacuated preemption) — indices stay
        # stable (parallel lists never compact), it is excluded from
        # _alive()/dispatch/rollups, and unlike a fence it owes the
        # router nothing: everything it held was handed to survivors
        self._retired = [False] * self.n
        # replica -> evacuation deadline (seconds): set by SIGTERM /
        # request_preempt / FF_FAULT `preempt`, consumed by the
        # replica's own driver tick (the evacuation runs there)
        self._preempt_req: Dict[int, float] = {}
        self._default_preempt_deadline_s = float(
            getattr(cfg, "preempt_deadline_s", 5.0))
        self._sigterm_installed = False
        self._prev_sigterm = None
        # fleet-wide adapter registry replay (ISSUE 20): register_adapter
        # fans out to every live replica at call time; a replica added
        # LATER replays this so survivors and newcomers always share one
        # registry view
        self._adapter_registry: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._warm_prompts = None   # captured by warmup() for add_replica
        self._next_rid = 0
        # router counters (stats()): the fleet-level ledger
        self._submitted = 0
        self._dispatched = 0
        self._completed = 0
        self._failed = 0
        self._timeouts = 0
        self._rejected = 0
        self._fenced_count = 0
        self._resubmitted = 0
        # role-split ledger: completed handoffs (prefill done, slab
        # moved to the decode queue), downgrades to the cold path (no
        # prefill replica alive / prefill-side pressure), and slab
        # imports that fell back cold on the decode side
        self._handoffs = 0
        self._handoff_fallbacks = 0
        # sequence-parallel prefills completed (every shard exported and
        # the request queued for decode with its slab LIST)
        self._seq_parallel = 0
        # elastic-fleet ledger (ISSUE 20): membership changes, and the
        # evacuation half of exactly-once — requests moved OFF a
        # retiring/preempted replica cleanly (ownership transfer, no
        # loss counted; a survivor death afterwards still caps at 2)
        self._scale_outs = 0
        self._scale_ins = 0
        self._preempts = 0
        self._evacuated_requests = 0
        self._evacuated_slabs = 0
        self._evacuated_pages = 0
        self._evacuation_bytes = 0
        self._evac_deadline_misses = 0
        self._preempt_margin_s: Optional[float] = None
        self._ttfts = collections.deque(maxlen=4096)
        # unified telemetry plane (ISSUE 13): fleet identity on every
        # replica's metric labels + trace track, the fleet TTFT
        # histogram, and a scrape-time collector exporting the router
        # ledger (fenced/resubmitted/timeouts/rejected/handoffs) and the
        # fleet rollup as first-class series
        self._tm_on = getattr(cfg, "telemetry", "on") != "off"
        self._tm_uid = next(_ROUTER_IDS)
        for r, eng in enumerate(self.engines):
            eng.set_telemetry_identity(r, self.roles[r])
        self._tm_ttft = None
        # unconditional: configure() is how telemetry="off" reaches the
        # recorder's own gate (an env FF_FLIGHT_DIR must not keep it
        # live under an off config)
        flightrec.configure(cfg)
        if self._tm_on:
            if getattr(cfg, "metrics_port", 0):
                telemetry.start_http_server(cfg.metrics_port)
            # resolve the settle-path histogram child once (the engine's
            # _tm_bind_children discipline): no registry lookup per
            # completion
            self._tm_ttft = telemetry.registry().histogram(
                "ff_router_ttft_seconds",
                "router submit -> first token (queue wait included — "
                "what shedding bounds)").labels()
            telemetry.registry().add_collector(self._tm_collect)
            # flight recorder + SLO health plane (ISSUE 15): the fleet
            # ledger rides every post-mortem bundle, and health() — the
            # probe that never blocks behind a mid-tick replica — feeds
            # the /healthz rollup (ok|degraded|breach)
            flightrec.recorder().attach_source(self._flightrec_source)
            flightrec.register_health_source(self._health_probe)
        self._threads: List[threading.Thread] = []
        self._started = False
        if start:
            self.start()

    # ---- lifecycle ----------------------------------------------------------

    def start(self):
        """Spawn one driver thread per replica (idempotent)."""
        with self._lock:
            if self._started:
                return
            self._started = True
        self._threads = [
            threading.Thread(target=self._replica_main, args=(r,),
                             daemon=True, name=f"ff-router-replica-{r}")
            for r in range(self.n)]
        for t in self._threads:
            t.start()

    def submit(self, prompt, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               temperature: Optional[float] = None,
               top_p: Optional[float] = None,
               top_k: Optional[int] = None,
               seed: Optional[int] = None,
               adapter: Optional[str] = None) -> FleetRequest:
        """Queue one request (validated synchronously against replica
        0's admission rules, so a malformed request raises HERE, not on
        a driver thread). Over ``max_queue``, returns immediately with
        state ``"rejected"`` — shedding is a fast status, not an
        exception, so a loaded front door costs one queue-length check."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be >= 1")
        eng0 = self.engines[0]
        bucket = eng0._bucket(prompt.size)
        if bucket + max_new_tokens > eng0.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {eng0.max_seq_len}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s={deadline_s}: must be >= 0")
        t, p, k = sampling_ops.validate_sampling(
            temperature if temperature is not None
            else eng0.default_temperature,
            top_p if top_p is not None else eng0.default_top_p,
            top_k if top_k is not None else eng0.default_top_k,
            "router.submit")
        if adapter is not None:
            if eng0.lora is None:
                raise ValueError(
                    f"adapter={adapter!r}: this fleet has no adapter "
                    f"pool (build replicas with adapter_pool_pages > 0)")
            if adapter not in eng0.lora.registry:
                raise ValueError(
                    f"adapter {adapter!r} is not registered (known: "
                    f"{sorted(eng0.lora.registry)}) — "
                    f"router.register_adapter first")
        now = time.perf_counter()
        # adapter-aware affinity: the key IS the trie's (namespaced)
        # first edge, so equal key still guarantees a trie hit on the
        # home replica — tenants never alias each other's homes
        affinity = (RadixPrefixCache.first_chunk(
            prompt[:self.page_size], adapter)
            if prompt.size >= self.page_size else None)
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    "ServingRouter is draining: new requests are not "
                    "admitted")
            req = FleetRequest(
                rid=self._next_rid, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=t, top_p=p, top_k=k,
                # the seed is assigned HERE (fleet rid, stable across
                # failover) unless the caller pins one
                seed=(int(seed) if seed is not None
                      else self._next_rid & 0x7FFFFFFF),
                adapter=adapter,
                deadline=(now + deadline_s if deadline_s is not None
                          else None),
                affinity=affinity, t_submit=now)
            req.trace_id = f"req-{self._tm_uid}-{req.rid}"
            self._next_rid += 1
            self._submitted += 1
            if self._tm_on:
                # the fleet-wide root span: open until settlement, so
                # every engine/handoff/failover span nests inside it
                req.root_span = telemetry.tracer().begin(
                    "request", trace_id=req.trace_id, track="router",
                    prompt_tokens=int(prompt.size),
                    max_new_tokens=int(max_new_tokens))
            if self.max_queue and len(self._queue) >= self.max_queue:
                req.state = "rejected"
                req.error = f"router queue full ({self.max_queue})"
                req.t_done = time.perf_counter()
                self._rejected += 1
                telemetry.tracer().end(req.root_span, state="rejected")
                req.root_span = 0
                return req
            self._queue.append(req)
        return req

    def run(self, prompts, max_new_tokens: int = 32,
            deadline_s: Optional[float] = None,
            timeout: Optional[float] = None,
            **submit_kw) -> List[FleetRequest]:
        """Submit ``prompts`` and block until every one settles; returns
        the requests in submission order (rejected/expired included).
        Extra kwargs (temperature/top_p/top_k/seed/adapter) forward to
        submit()."""
        self.start()
        reqs = [self.submit(p, max_new_tokens, deadline_s=deadline_s,
                            **submit_kw)
                for p in prompts]
        self.wait(reqs, timeout=timeout)
        return reqs

    def register_adapter(self, name: str, weights: Dict,
                         alpha: Optional[float] = None) -> None:
        """Register a LoRA adapter on EVERY replica (the fleet shares
        one registry view, so failover and handoff always find the
        adapter wherever a request lands). Replacement is pre-validated
        across the whole fleet BEFORE any replica mutates: if the
        adapter is pinned by live slots anywhere, nothing changes — a
        partial fan-out would serve two weight versions under one name,
        and a failover between them would splice streams. (Quiesce the
        tenant's traffic before replacing an adapter: the pre-check
        races in-flight admissions by design — it closes the ordering
        gap, not the concurrency one.)"""
        pinned = []
        for r, eng in enumerate(self.engines):
            if eng.lora is None:
                raise RuntimeError(
                    "this fleet has no adapter pool: build replicas "
                    "with adapter_pool_pages > 0")
            if self._fenced[r] or self._retired[r]:
                continue
            res = eng.lora.resident.get(name)
            if res is not None and res.ref > 0:
                pinned.append(r)
        if pinned:
            raise ValueError(
                f"adapter {name!r} is pinned by live slots on "
                f"replica(s) {pinned}: drain its traffic before "
                f"replacing it (no replica was modified)")
        for r, eng in enumerate(self.engines):
            if self._fenced[r] or self._retired[r]:
                continue
            eng.register_adapter(name, weights, alpha)
        # replayed onto replicas added later (add_replica), so the whole
        # fleet — newcomers included — shares one registry view and a
        # retiree's tenants keep serving from survivors with no caller
        # re-register (ISSUE 20)
        with self._lock:
            self._adapter_registry[name] = (weights, alpha)

    def wait(self, reqs: Optional[List[FleetRequest]] = None,
             timeout: Optional[float] = None):
        """Block until ``reqs`` (default: everything outstanding) settle.
        This is also where fleet-level liveness runs when the caller's
        thread is the only healthy one left: the hang sweep and the
        no-survivors check. Brings the drivers up if nobody has yet —
        only driver threads move queued work, so waiting on an
        un-started fleet would otherwise spin forever."""
        self.start()
        t0 = time.monotonic()
        while True:
            with self._lock:
                self._sweep_hangs_locked()
                self._fail_if_no_survivors_locked()
                if reqs is None:
                    open_work = (bool(self._queue)
                                 or any(self._outstanding)
                                 or any(self._to_submit))
                else:
                    open_work = any(not r.settled for r in reqs)
            if not open_work:
                return
            if self._stop.is_set():
                raise RuntimeError(
                    "router.wait: the router was closed with work still "
                    "open — close() abandons un-settled requests")
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"router.wait: work still open after {timeout}s "
                    f"(health: {self.health()})")
            time.sleep(0.003)

    def warmup(self, prompts, max_new_tokens: int = 4):
        """Drive ``prompts`` through EVERY replica engine directly
        (bypassing the router queue) via ``ServingEngine.warmup`` — all
        cold-prefill buckets, every (bucket, matched_pages) hit variant
        the set can reach (two passes: publish, then saturated repeat),
        the decode/verify programs, and (for role-split or tiered
        fleets) the shared page-import writer — so failover AND handoff
        traffic later hits only warm programs: the smoke asserts zero
        survivor recompiles through a mid-flight crash of the prefill
        replica. Call while the fleet is quiet (before routed
        traffic)."""
        plist = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        # captured so add_replica() can warm a scaled-out engine to the
        # same program set before it takes traffic (ISSUE 20)
        self._warm_prompts = ([p.copy() for p in plist],
                              int(max_new_tokens))
        for r, eng in enumerate(self.engines):
            if self._fenced[r] or self._retired[r]:
                continue
            eng.warmup(plist, max_new_tokens=max_new_tokens)
        # ANY prefix-cached replica can receive a page slab now — from a
        # prefill handoff or from a retiring/preempted peer's evacuation
        # (ISSUE 20) — so the shared import writer is warmed fleet-wide,
        # not just on role-split fleets: a preemption mid-flood must
        # cost survivors zero compiles
        if any(eng.prefix_cache is not None for eng in self.engines):
            cand = max((p for p in plist if p.size >= self.page_size),
                       key=lambda p: p.size, default=None)
            for r, eng in enumerate(self.engines):
                if (eng.prefix_cache is None or self._fenced[r]
                        or self._retired[r]):
                    continue
                if cand is None or not eng.warm_page_import(cand):
                    fflogger.warning(
                        "router: warmup could not warm replica %d's "
                        "page-import writer — its first handoff will "
                        "compile it", r)
        if self._tm_on:
            # every replica's warmup already rebaselined; one more after
            # the LAST replica restarts the fleet-wide window clock too
            flightrec.slo_monitor().rebaseline()

    def drain(self) -> Dict:
        """Graceful fleet shutdown: stop admitting, let the drivers
        finish everything queued and in flight, stop the threads, drain
        the surviving engines, return a final stats snapshot."""
        with self._lock:
            self._draining = True
        self.start()    # a start=False fleet still owes its queued work
        self.wait(None)
        self.close()
        for r, eng in enumerate(self.engines):
            if not self._fenced[r] and not self._retired[r]:
                eng.drain()
        snap = self.stats()
        snap["drained"] = True
        fflogger.info(
            "router: drained — %d completed, %d failed, %d timeouts, "
            "%d rejected; %d fenced, %d resubmitted",
            snap["completed"], snap["failed"], snap["timeouts"],
            snap["rejected"], snap["fenced"], snap["resubmitted"])
        return snap

    def close(self):
        """Stop the driver threads without waiting for open work (the
        work stays un-settled); idempotent."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    # ---- rolling deploy hooks (runtime/deploy.py drives these) --------------

    def suspend_replica(self, r: int):
        """Stop dispatching NEW work to replica r; its driver keeps
        ticking so in-flight work drains naturally, and the hang sweep
        never fences it (an idle replica has no outstanding work). The
        deployer's drain-swap-warmup window."""
        with self._lock:
            self._suspended[r] = True
            self.engines[r].deploy_state = "draining"

    def resume_replica(self, r: int):
        """Readmit replica r to dispatch after a swap (or an aborted
        one). Affinity entries recorded for it under its PREVIOUS
        version are dropped — the swap flushed those pages."""
        with self._lock:
            self._suspended[r] = False
            self._drop_affinity_locked(r)
            # only the drain gate resets here: "canary" belongs to the
            # deployer, which resumes the canary so it RECEIVES soak
            # traffic while still being judged (and drilled) as canary
            if self.engines[r].deploy_state == "draining":
                self.engines[r].deploy_state = "serving"

    def replica_quiesced(self, r: int) -> bool:
        """True when replica r owes the router nothing: no outstanding
        engine work and nothing assigned-but-not-submitted."""
        with self._lock:
            return (not self._outstanding[r]
                    and not self._to_submit[r])

    def _drop_affinity_locked(self, r: int):
        for key in [k for k, v in self._affinity.items() if v[0] == r]:
            del self._affinity[key]

    def set_deploying(self, on: bool):
        """Mark a roll in progress: /healthz degrades (never breaches)
        while this is set (flightrec.health_rollup)."""
        with self._lock:
            self._deploying = bool(on)

    def note_swap(self):
        with self._lock:
            self._swaps_completed += 1

    def note_rollback(self):
        with self._lock:
            self._rollbacks += 1

    # ---- elastic fleet (ISSUE 20): live membership + preemption -------------

    def add_replica(self, role: str = "mixed", warmup_prompts=None,
                    max_new_tokens: int = 4) -> int:
        """Scale OUT: build one more replica engine and admit it to the
        fleet. The engine is constructed, adapter-replayed and warmed
        entirely OFF the router lock (the live fleet keeps serving
        through the whole build), then joins under one short lock
        acquisition: parallel lists extend, a driver thread spawns, and
        the SLO windows rebaseline so the capacity step does not smear
        into the breach history. Warmup uses ``warmup_prompts`` when
        given, else the prompt set the fleet's own warmup() captured —
        either way the newcomer's programs (page-import writer included,
        so it can receive evacuation/handoff slabs) are warm BEFORE its
        first dispatch: scale-out adds capacity, never a compile stall.
        Returns the new replica index."""
        if role not in self.ROLES:
            raise ValueError(
                f"role={role!r}: must be one of {self.ROLES}")
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    "ServingRouter is draining: the fleet cannot grow")
            registry = list(self._adapter_registry.items())
            warm = self._warm_prompts
        eng = self.model.make_serving_engine(**self._engine_kwargs)
        if eng.lora is not None:
            for name, (weights, alpha) in registry:
                eng.register_adapter(name, weights, alpha)
        if warmup_prompts is not None:
            warm = ([np.asarray(p, np.int32).reshape(-1)
                     for p in warmup_prompts], int(max_new_tokens))
        if warm is not None:
            plist, mnt = warm
            eng.warmup(plist, max_new_tokens=mnt)
            if eng.prefix_cache is not None:
                cand = max((p for p in plist
                            if p.size >= self.page_size),
                           key=lambda p: p.size, default=None)
                if cand is not None:
                    eng.warm_page_import(cand)
        with self._lock:
            r = self.n
            self.engines.append(eng)
            self.roles.append(role)
            self._outstanding.append({})
            self._to_submit.append(collections.deque())
            self._fenced.append(False)
            self._fence_reason.append("")
            self._retired.append(False)
            self._suspended.append(False)
            self._heartbeat.append(time.monotonic())
            self._busy_ticks.append(0)
            self.n += 1
            self._scale_outs += 1
            self._handoff_capable = (
                any(t == "prefill" for t in self.roles)
                and self.engines[0].prefix_cache is not None)
            eng.set_telemetry_identity(r, role)
            thread = None
            if self._started:
                thread = threading.Thread(
                    target=self._replica_main, args=(r,), daemon=True,
                    name=f"ff-router-replica-{r}")
                self._threads.append(thread)
        if thread is not None:
            thread.start()
        if self._tm_on:
            telemetry.tracer().instant("scale_out", track="router",
                                       replica=r, role=role,
                                       warmed=warm is not None)
            flightrec.slo_monitor().rebaseline()
        fflogger.info(
            "router: scaled OUT to replica %d (role %s, warmed=%s, "
            "%d adapters replayed)", r, role, warm is not None,
            len(registry))
        return r

    def remove_replica(self, r: int, timeout_s: float = 60.0) -> Dict:
        """Scale IN: retire replica r without losing a request or a
        cached prefix. The replica is first suspended (no new
        dispatches), its never-admitted work — the engine queue drain()
        deliberately parks (the PR-5 contract) plus anything assigned
        but not yet handed over — is requeued to survivors, in-flight
        requests finish in place (bounded by ``timeout_s``; a replica
        that cannot quiesce is fenced, which resubmits exactly-once),
        the engine drains, and its cached prefix paths are exported as
        page slabs into the least-loaded survivors under their original
        per-version/per-adapter namespaces. Resident adapters already
        live fleet-wide (register_adapter fans out; add_replica
        replays), so tenants keep serving with no caller action.
        Returns an evacuation summary dict."""
        with self._lock:
            self._check_member_locked(r)
            survivors = [s for s in self._alive()
                         if s != r and not self._suspended[s]]
            if not survivors:
                raise RuntimeError(
                    f"remove_replica({r}): no live survivor to inherit "
                    f"its work — the fleet cannot scale below 1")
            if all(self.roles[s] == "prefill" for s in survivors):
                raise RuntimeError(
                    f"remove_replica({r}): the survivors are all "
                    f"prefill replicas — nowhere to decode")
            self._suspended[r] = True
        eng = self.engines[r]
        # pull back un-admitted work, then wait for in-flight slots to
        # retire on the replica's own driver; re-reclaim each pass —
        # racing submissions that were mid-handoff when we suspended
        # land in the engine queue one driver tick later
        pending: Dict[int, object] = {}
        requeued = 0
        t0 = time.monotonic()
        while True:
            for ereq in eng.reclaim_queued():
                pending[id(ereq)] = ereq
            with self._lock:
                requeued += self._pull_unadmitted_locked(
                    r, pending, "scale_in")
                open_work = (bool(self._outstanding[r])
                             or bool(self._to_submit[r]))
                fenced = self._fenced[r]
            if fenced or not open_work:
                break
            if time.monotonic() - t0 > timeout_s:
                with self._lock:
                    self._fence_locked(
                        r, f"scale-in: failed to quiesce in "
                           f"{timeout_s}s")
                    fenced = True
                break
            time.sleep(0.003)
        evac = {"slabs": 0, "pages": 0, "bytes": 0, "paths": 0,
                "deadline_missed": False}
        if not fenced:
            eng.drain()
            evac = self._evacuate_prefixes(r, deadline_t=None)
        with self._lock:
            self._retired[r] = True
            self._scale_ins += 1
            self._drop_affinity_locked(r)
        if self._tm_on:
            telemetry.tracer().instant(
                "scale_in", track="router", replica=r,
                requeued=requeued, slabs=evac["slabs"],
                pages=evac["pages"])
            flightrec.slo_monitor().rebaseline()
        fflogger.info(
            "router: scaled IN replica %d — %d never-admitted requests "
            "requeued, %d prefix slabs (%d pages, %d bytes) inherited "
            "by survivors", r, requeued, evac["slabs"], evac["pages"],
            evac["bytes"])
        return {"replica": r, "requeued": requeued, "fenced": fenced,
                **evac}

    def request_preempt(self, r: int,
                        deadline_s: Optional[float] = None):
        """Preemption notice for replica r (the programmatic SIGTERM,
        resilience.py's request_preempt applied to the fleet): flag the
        replica for evacuation; its own driver runs the deadline race on
        its next tick. ``deadline_s`` defaults to
        FFConfig.preempt_deadline_s."""
        with self._lock:
            self._check_member_locked(r)
            self._preempt_req[r] = float(
                deadline_s if deadline_s is not None
                else self._default_preempt_deadline_s)

    def install_preempt_handler(self, replica: int = 0,
                                deadline_s: Optional[float] = None):
        """Route a real SIGTERM (the cloud's preemption notice) to
        ``request_preempt(replica, deadline_s)`` — the serving half of
        resilience.py's handler path. Main thread only (signal module
        rule); off the main thread this warns and the owner calls
        request_preempt() itself. Idempotent."""
        if self._sigterm_installed:
            return
        from flexflow_tpu.runtime import resilience

        def _on_sigterm(signum, frame):
            self.request_preempt(replica, deadline_s)

        ok, prev = resilience.install_sigterm(_on_sigterm)
        if ok:
            self._sigterm_installed = True
            self._prev_sigterm = prev
        else:
            fflogger.warning(
                "router: cannot install SIGTERM handler outside the "
                "main thread; call request_preempt() instead")

    def _check_member_locked(self, r: int):
        if r < 0 or r >= self.n:
            raise ValueError(f"replica {r}: not in [0, {self.n})")
        if self._retired[r]:
            raise ValueError(f"replica {r} already retired")
        if self._fenced[r]:
            raise ValueError(
                f"replica {r} is fenced ({self._fence_reason[r]})")

    def _preempt_scheduled(self, r: int) -> bool:
        """Driver-tick check: a pending request_preempt/SIGTERM notice,
        or the FF_FAULT drill ``preempt(<deadline_ms>)@replica:<r>``
        (fires at the replica's first busy tick; the value is the
        evacuation deadline, defaulting to preempt_deadline_s)."""
        if r in self._preempt_req:
            return True
        plan = faultinject.active_plan()
        scheduled, value = plan.pending("preempt", "replica", r)
        if scheduled and self._busy_ticks[r] >= 1:
            plan.at_site("preempt", "replica", r)
            self._preempt_req[r] = (
                value / 1e3 if value is not None
                else self._default_preempt_deadline_s)
            return True
        return False

    def _preempt_now(self, r: int):
        """The evacuation race (runs on replica r's own driver thread,
        which exits right after): against ``deadline_s``, (1) requeue
        every never-admitted request (cheap — host memory), (2) export
        hot prefix paths as page slabs into survivors, hottest first,
        checking the deadline between slabs (FF_FAULT ``slow_evac``
        stalls here), (3) transfer in-flight requests to the router
        queue under one lock acquisition — ownership flips, so the dying
        replica's late completions are discarded by _collect's owner
        check and the survivor's re-decode is the request's ONE stream.
        Evacuated requests count no loss (clean transfer: a survivor
        death afterwards still fails over normally). A blown deadline
        degrades to _fence_locked — whatever was not yet evacuated
        resubmits cold with a loss counted, the existing exactly-once
        path. Either way the replica ends retired."""
        with self._lock:
            if self._fenced[r] or self._retired[r]:
                self._preempt_req.pop(r, None)
                return
            deadline_s = self._preempt_req.pop(
                r, self._default_preempt_deadline_s)
            self._suspended[r] = True
            self._preempts += 1
        deadline_t = time.perf_counter() + deadline_s
        if self._tm_on:
            telemetry.tracer().instant(
                "preempt", track="router", replica=r,
                deadline_s=deadline_s)
        fflogger.warning(
            "router: replica %d PREEMPTED — evacuating against a "
            "%.3fs deadline", r, deadline_s)
        eng = self.engines[r]
        pending = {id(e): e for e in eng.reclaim_queued()}
        with self._lock:
            evacuated = self._pull_unadmitted_locked(
                r, pending, "preempt")
        evac = self._evacuate_prefixes(r, deadline_t)
        missed = (evac["deadline_missed"]
                  or time.perf_counter() >= deadline_t)
        with self._lock:
            if not missed:
                evacuated += self._evacuate_inflight_locked(r)
            if self._outstanding[r] or self._to_submit[r]:
                # hard-deadline fallback: a clean fence — remaining
                # work resubmits cold through the exactly-once path
                self._evac_deadline_misses += 1
                self._fence_locked(
                    r, f"preempt deadline ({deadline_s:.3f}s) expired "
                       f"mid-evacuation")
            self._retired[r] = True
            self._drop_affinity_locked(r)
            margin = deadline_t - time.perf_counter()
            # last drill's deadline headroom (negative = starved) — the
            # bench stamps it next to evacuation_bytes
            self._preempt_margin_s = round(margin, 4)
        if self._tm_on:
            flightrec.trip(
                "preempt", replica=r, deadline_s=deadline_s,
                evacuated_requests=evacuated, slabs=evac["slabs"],
                pages=evac["pages"], bytes=evac["bytes"],
                deadline_missed=missed,
                deadline_margin_s=round(margin, 4))
            flightrec.slo_monitor().rebaseline()
        fflogger.warning(
            "router: replica %d preemption %s — %d requests evacuated, "
            "%d slabs / %d pages / %d bytes inherited, %.3fs deadline "
            "margin", r, "DEADLINE-STARVED (fenced)" if missed
            else "evacuated cleanly", evacuated, evac["slabs"],
            evac["pages"], evac["bytes"], margin)

    def _pull_unadmitted_locked(self, r: int, pending: Dict,
                                reason: str) -> int:
        """Requeue replica r's never-admitted work: everything still on
        the hand-off deque, plus engine-queue requests the caller
        reclaimed (matched by engine-Request identity — ``pending`` maps
        id(ereq) -> ereq and unmatched entries stay for the caller's
        next pass, closing the race where reclaim beats the driver's
        outstanding-ledger write). No loss is counted: the engine never
        admitted these, so requeue is a pure ownership transfer."""
        moved = []
        while self._to_submit[r]:
            req = self._to_submit[r].pop()
            self._outstanding[r].pop(req.rid, None)
            if req.state == "dispatched" and req.replica == r:
                moved.append(req)
        for rid in list(self._outstanding[r].keys()):
            req, ereq = self._outstanding[r][rid]
            if ereq is None or id(ereq) not in pending:
                continue
            del pending[id(ereq)]
            del self._outstanding[r][rid]
            if req.state == "dispatched" and req.replica == r:
                moved.append(req)
        moved.sort(key=lambda q: q.rid)
        now = time.perf_counter()
        for req in moved:
            if req.deadline is not None and now >= req.deadline:
                self._finalize_locked(
                    req, "timeout",
                    f"deadline expired while queued on retiring "
                    f"replica {r}")
                continue
            req.state = "queued"
            req.replica = -1
            req.tokens = []
            self._evacuated_requests += 1
            if self._tm_on:
                telemetry.tracer().instant(
                    "evacuate", trace_id=req.trace_id, track="router",
                    from_replica=r, reason=reason, admitted=False)
        for req in reversed([q for q in moved if q.state == "queued"]):
            self._queue.appendleft(req)
        return sum(1 for q in moved if q.state == "queued")

    def _evacuate_inflight_locked(self, r: int) -> int:
        """Clean ownership transfer of replica r's admitted in-flight
        requests back to the router queue (the preemption path: the
        hardware is going away, so their decode cannot finish here).
        Tokens are discarded — the survivor re-decodes the identical
        stream from scratch — and NO loss is counted: this is an
        evacuation, not a death, so a survivor crash afterwards still
        gets its one failover before the cap."""
        out = self._outstanding[r]
        self._outstanding[r] = {}
        self._to_submit[r].clear()
        now = time.perf_counter()
        moved = []
        for _, (req, _ereq) in sorted(out.items()):
            if req.state != "dispatched" or req.replica != r:
                continue
            if req.deadline is not None and now >= req.deadline:
                self._finalize_locked(
                    req, "timeout",
                    f"deadline expired in flight on preempted "
                    f"replica {r}")
                continue
            req.state = "queued"
            req.replica = -1
            req.tokens = []
            moved.append(req)
            self._evacuated_requests += 1
            if self._tm_on:
                telemetry.tracer().instant(
                    "evacuate", trace_id=req.trace_id, track="router",
                    from_replica=r, reason="preempt", admitted=True)
        for req in reversed(moved):
            self._queue.appendleft(req)
        return len(moved)

    def _evacuate_prefixes(self, r: int,
                           deadline_t: Optional[float]) -> Dict:
        """Export replica r's cached prefix paths as page slabs and
        import each into the least-loaded live survivor, hottest path
        first. ``deadline_t`` (absolute perf_counter, or None for
        unbounded scale-in) is checked BETWEEN slabs — a preemption
        deadline can starve the tail, never wedge mid-transfer. The
        FF_FAULT drill ``slow_evac(<ms>)@evacuate:<n>`` stalls the n-th
        export to make the starved path deterministic. Namespaces ride
        each slab verbatim, so per-version/per-adapter prefixes land on
        survivors under the exact keys they were cached under, and the
        importer's dedupe makes shared interior pages free."""
        eng = self.engines[r]
        stats = {"slabs": 0, "pages": 0, "bytes": 0, "paths": 0,
                 "deadline_missed": False}
        if eng.prefix_cache is None:
            return stats
        manifest = eng.cached_prefix_manifest()
        stats["paths"] = len(manifest)
        plan = faultinject.active_plan()
        for tokens, ns in manifest:
            if (deadline_t is not None
                    and time.perf_counter() >= deadline_t):
                stats["deadline_missed"] = True
                break
            if plan.fire("slow_evac", "evacuate"):
                time.sleep((plan.last_value or 0) / 1e3)
                if (deadline_t is not None
                        and time.perf_counter() >= deadline_t):
                    stats["deadline_missed"] = True
                    break
            slab = eng.export_prefix_path(tokens, ns)
            if slab is None:
                continue        # evicted since the manifest walk
            with self._lock:
                cands = [s for s in self._alive()
                         if s != r and not self._suspended[s]
                         and self.engines[s].prefix_cache is not None]
            if not cands:
                break           # nobody can inherit: stop exporting
            dest = min(cands, key=lambda s: (
                self._load(s), self.engines[s].load()["queued"], s))
            try:
                self.engines[dest].import_prefix_slab(slab)
            except Exception as e:  # noqa: BLE001 — a survivor that
                #   cannot ingest must not abort the whole evacuation
                fflogger.warning(
                    "router: evacuation import on replica %d failed "
                    "(%s) — slab dropped", dest, e)
                continue
            nbytes = _slab_nbytes(slab)
            stats["slabs"] += 1
            # pages CARRIED by the slab (like `bytes`): the importer
            # dedupes pages the survivor already holds, and a dedup is
            # still a successful evacuation, not a smaller one
            stats["pages"] += len(slab["payload"])
            stats["bytes"] += nbytes
            with self._lock:
                self._evacuated_slabs += 1
                self._evacuated_pages += len(slab["payload"])
                self._evacuation_bytes += nbytes
        return stats

    # ---- dispatch (router lock held) ----------------------------------------

    def _alive(self) -> List[int]:
        return [r for r in range(self.n)
                if not self._fenced[r] and not self._retired[r]]

    def _load(self, r: int) -> int:
        # the health() counters, via the router's exact outstanding
        # ledger: dispatched minus settled == active + engine-queued +
        # assigned-but-not-yet-handed-over (the hand-off deque is a
        # SUBSET of outstanding — never add the two)
        return len(self._outstanding[r])

    def _eligible_locked(self, phase: str) -> List[int]:
        """Live replicas whose role fits the request phase. Roles are a
        preference, never a constraint: with the decode side gone,
        prefill replicas decode (the fleet degrades to mixed); with the
        prefill side gone, _classify_locked already downgraded the work
        to the cold path."""
        alive = [r for r in self._alive() if not self._suspended[r]]
        if phase == "prefill":
            return [r for r in alive if self.roles[r] == "prefill"]
        cands = [r for r in alive if self.roles[r] != "prefill"]
        return cands or alive

    def _classify_locked(self, req: FleetRequest):
        """Pick the request's phase at dispatch time (roles and liveness
        change between submit and dispatch): long prompts (>=
        handoff_min_pages matchable full pages) route through a live
        prefill replica for prefill-only + slab handoff — unless their
        prefix is already homed on a live decode-side replica, where a
        direct dispatch is a guaranteed trie hit and the handoff would
        move bytes for nothing. Everything else (and every downgrade
        when the prefill tier is dead or failed) takes the classic
        direct path."""
        if req.phase == "decode":
            return                  # slab in hand, decode placement only
        was_prefill = req.phase == "prefill"
        req.phase = "direct"
        if not self._handoff_capable:
            return
        matchable = (req.prompt.size - 1) // self.page_size
        if matchable < self.handoff_min_pages:
            return
        if not any(self.roles[r] == "prefill" for r in self._alive()):
            if was_prefill:
                # the prefill tier died under this request: cold-path
                # fallback on the decode side, never stranded
                self._handoff_fallbacks += 1
            return
        entry = self._home_locked(req)
        if entry is not None and self.roles[entry[0]] != "prefill":
            return                  # warm home: direct hit beats handoff
        req.phase = "prefill"

    def _affinity_key(self, req: FleetRequest, version: str):
        """The affinity-map key for this request under weight
        ``version``: exactly the trie's version-salted first edge
        (serving.version_ns), so equal key still guarantees a trie hit
        on the home replica. At the default version this is bit-
        identical to the pre-deploy adapter-namespaced key."""
        if req.affinity is None:
            return None
        ns = version_ns(version, req.adapter)
        if ns == req.adapter:
            return req.affinity     # default version: the precomputed key
        return RadixPrefixCache.first_chunk(
            req.prompt[:self.page_size], ns)

    def _home_locked(self, req: FleetRequest):
        """The live (replica, tier) whose trie is guaranteed to hold
        this request's first-page prefix. Affinity entries are keyed by
        the VERSION-SALTED trie edge, so mid-roll the lookup tries each
        live weight version (<= 2 during a roll, 1 otherwise) and only
        trusts an entry whose replica still serves the version it was
        recorded under — a swapped replica's old-version pages are
        flushed, so its stale entries must not steer."""
        if req.affinity is None:
            return None
        seen = set()
        for r0 in self._alive():
            v = self.engines[r0].weight_version
            if v in seen:
                continue
            seen.add(v)
            entry = self._affinity.get(self._affinity_key(req, v))
            if entry is None:
                continue
            home = entry[0]
            if (not self._fenced[home]
                    and self.engines[home].weight_version == v):
                return entry
        return None

    def _pick_replica_locked(self, req: FleetRequest) -> Optional[int]:
        cands = self._eligible_locked(req.phase)
        if not cands:
            return None
        if req.affinity is not None and req.phase != "prefill":
            entry = self._home_locked(req)
            if entry is not None:
                home, _tier = entry
                if home in cands and self._load(home) < self._cap:
                    return home
        cands = [r for r in cands if self._load(r) < self._cap]
        if not cands:
            return None
        # role- and queue-depth-aware least-loaded: the router's exact
        # outstanding ledger first, the engine's live queue depth (the
        # lock-free probe) as the tie-break
        return min(cands, key=lambda r: (
            self._load(r), self.engines[r].load()["queued"], r))

    def _dispatch_locked(self):
        """Assign queued work: expired requests retire as timeout
        BEFORE placement (never dispatched), the rest go to the affinity
        home when it is live and has room, else the least-loaded
        role-eligible replica with room. Assignment only moves the
        request onto the replica's hand-off deque — the driver thread
        performs the actual engine.submit on its own lock, so dispatch
        never blocks behind a replica mid-tick.

        FIFO is per ROLE TIER, not fleet-wide: a phase-"prefill" head
        that cannot place (prefill tier saturated) is SKIPPED — direct
        and decode work behind it still flows to the decode side (one
        full role tier must not stall the whole fleet; prefill requests
        stay FIFO among themselves). A direct/decode request that
        cannot place stops the scan — the decode side is genuinely
        full, which is the pre-role blocking rule."""
        now = time.perf_counter()
        prefill_blocked = False
        i = 0
        while i < len(self._queue):
            req = self._queue[i]
            if req.deadline is not None and now >= req.deadline:
                del self._queue[i]
                self._finalize_locked(
                    req, "timeout", "deadline expired in router queue")
                continue
            self._classify_locked(req)
            if prefill_blocked and req.phase == "prefill":
                i += 1
                continue
            r = self._pick_replica_locked(req)
            if r is None:
                if req.phase == "prefill":
                    prefill_blocked = True
                    i += 1
                    continue
                return
            del self._queue[i]
            req.state = "dispatched"
            req.replica = r
            req.attempts += 1
            self._dispatched += 1
            if self._tm_on:
                telemetry.tracer().instant(
                    "dispatch", trace_id=req.trace_id, track="router",
                    replica=r, phase=req.phase, attempt=req.attempts)
            if req.affinity is not None and req.phase != "prefill":
                # the affinity home is where the prefix DECODES (and
                # therefore publishes); a prefill dispatch must not
                # steal the key from the decode side. Tier starts hbm;
                # the replica's tier events keep it current. The key is
                # salted with the DISPATCHED replica's weight version —
                # the namespace its trie will file the prefix under.
                key = self._affinity_key(
                    req, self.engines[r].weight_version)
                self._affinity[key] = (r, "hbm")
                self._affinity.move_to_end(key)
                while len(self._affinity) > self._affinity_cap:
                    self._affinity.popitem(last=False)
            self._outstanding[r][req.rid] = (req, None)
            self._to_submit[r].append(req)

    def _finalize_locked(self, req: FleetRequest, state: str,
                         error: str = ""):
        req.state = state
        req.error = error
        req.t_done = time.perf_counter()
        if state == "done":
            self._completed += 1
            if req.ttft:
                self._ttfts.append(req.ttft)
                if self._tm_ttft is not None:
                    self._tm_ttft.observe(req.ttft)
        elif state == "timeout":
            self._timeouts += 1
        else:
            self._failed += 1
        telemetry.tracer().end(
            req.root_span, state=state, replica=req.replica,
            attempts=req.attempts, handoff=req.handoff,
            **({"error": error} if error else {}))
        req.root_span = 0

    def _fence_locked(self, r: int, reason: str):
        """Fence replica r: mark it dead, requeue its outstanding work.
        Exactly-once resubmission: a request is resubmitted only from
        state "dispatched" on THIS replica, at most once overall (the
        cap counts replica LOSSES, not dispatches: ``losses`` caps at 2,
        since a clean role-split handoff legitimately dispatches twice
        — prefill then decode — and a failed-over handoff three times),
        and never after its deadline — an expired
        in-flight request is already worthless, so it retires as timeout
        instead of burning survivor capacity."""
        if self._fenced[r]:
            return
        self._fenced[r] = True
        self._fence_reason[r] = reason
        self._fenced_count += 1
        if self._tm_on:
            telemetry.tracer().instant("fence", track="router",
                                       replica=r, reason=reason)
            # a fence IS the incident the flight recorder exists for:
            # snapshot the window (debounced — the crash that caused
            # this fence already opened the pending bundle, so the two
            # triggers merge into one)
            flightrec.trip("replica_fence", replica=r, reason=reason,
                           role=self.roles[r])
        out = self._outstanding[r]
        self._outstanding[r] = {}
        self._to_submit[r].clear()
        now = time.perf_counter()
        requeued = []
        for _, (req, _ereq) in sorted(out.items()):
            if req.state != "dispatched" or req.replica != r:
                continue
            req.losses += 1     # a replica died under this request —
            #                     the exactly-once cap counts LOSSES,
            #                     not dispatches (a clean role-split
            #                     handoff legitimately dispatches twice)
            if req.deadline is not None and now >= req.deadline:
                self._finalize_locked(
                    req, "timeout",
                    f"deadline expired in flight on fenced replica {r}")
            elif req.losses >= 2:
                self._finalize_locked(
                    req, "failed",
                    f"replica lost twice (last: {reason})")
            else:
                req.state = "queued"
                req.replica = -1
                req.tokens = []   # discard the dead replica's partial
                #                   stream: the survivor re-decodes the
                #                   identical greedy tokens from scratch
                #                   (a phase-"prefill" victim re-
                #                   classifies at dispatch: with the
                #                   prefill tier gone it downgrades to
                #                   the cold path on a decode replica)
                requeued.append(req)
                self._resubmitted += 1
                if self._tm_on:
                    # the trace context SURVIVES resubmission: the same
                    # trace_id rides the requeued request, so its spans
                    # on the survivor join the original tree
                    telemetry.tracer().instant(
                        "resubmit", trace_id=req.trace_id,
                        track="router", from_replica=r, reason=reason)
        # front of the queue, original order: failover work has waited
        # longest
        for req in reversed(requeued):
            self._queue.appendleft(req)
        # shared-prefix homes pointing at the corpse re-home on next use
        for key in [k for k, v in self._affinity.items() if v[0] == r]:
            del self._affinity[key]
        fflogger.warning(
            "router: replica %d FENCED (%s) — %d requests resubmitted, "
            "%d survivors", r, reason, len(requeued), len(self._alive()))
        self._fail_if_no_survivors_locked()

    def _sweep_hangs_locked(self):
        """Fence any replica with outstanding work whose driver has not
        heartbeaten within health_timeout_s — run by every healthy
        driver's tick and by wait(), so one wedged replica cannot take
        the detector down with it."""
        if not self._started:
            return
        now = time.monotonic()
        for r in range(self.n):
            if self._fenced[r] or self._retired[r]:
                continue
            if not self._outstanding[r] and not self._to_submit[r]:
                continue
            if now - self._heartbeat[r] > self.health_timeout_s:
                self._fence_locked(
                    r, f"hang: no heartbeat for {self.health_timeout_s}s")

    def _fail_if_no_survivors_locked(self):
        if self._started and not self._alive():
            while self._queue:
                req = self._queue.popleft()
                self._finalize_locked(
                    req, "failed", "no live replicas")

    # ---- the replica driver thread ------------------------------------------

    def _maybe_injected_fault(self, r: int) -> bool:
        """FF_FAULT fleet drills, checked each busy tick: crash raises
        ReplicaCrash (the driver's except fences and requeues — the real
        crash path end to end); hang stops heartbeating and spins until
        the sweep fences this replica (returns True: exit the driver)."""
        plan = faultinject.active_plan()
        scheduled, value = plan.pending("crash", "replica", r)
        if scheduled and self._busy_ticks[r] >= (value or 1):
            plan.at_site("crash", "replica", r)
            raise ReplicaCrash(f"injected crash@replica:{r} "
                               f"(busy tick {self._busy_ticks[r]})")
        scheduled, value = plan.pending("hang", "replica", r)
        if scheduled and self._busy_ticks[r] >= (value or 1):
            plan.at_site("hang", "replica", r)
            fflogger.warning(
                "router: replica %d injected hang — waiting for the "
                "health sweep to fence it", r)
            while not self._fenced[r] and not self._stop.is_set():
                time.sleep(0.005)
            return True
        return False

    def _replica_main(self, r: int):
        eng = self.engines[r]
        while not self._stop.is_set():
            with self._lock:
                if self._fenced[r] or self._retired[r]:
                    return
                self._sweep_hangs_locked()
                self._dispatch_locked()
                assigned = []
                while self._to_submit[r]:
                    assigned.append(self._to_submit[r].popleft())
                busy = bool(self._outstanding[r])
            # heartbeat BEFORE the tick too: the sweep then measures one
            # tick's duration, not dispatch-wait + tick
            self._heartbeat[r] = time.monotonic()
            try:
                if busy:
                    self._busy_ticks[r] += 1
                    if self._maybe_injected_fault(r):
                        return
                if self._preempt_scheduled(r):
                    # the evacuation runs HERE, on the replica's own
                    # driver thread, then the driver exits. `assigned`
                    # is safe to drop: dispatch already recorded every
                    # entry in the outstanding ledger, and the
                    # evacuation requeues from there.
                    self._preempt_now(r)
                    return
                for req in assigned:
                    if req.phase == "prefill":
                        # prefill-replica half of the handoff: prefill
                        # only, export the slab, bounce the request back
                        # to the router queue for decode placement. An
                        # engine death in here propagates to the fence
                        # below — the exactly-once machinery requeues.
                        self._handoff_prefill(r, eng, req)
                        continue
                    if req.slab is not None:
                        # decode-side ingestion: page scatter + trie
                        # publish; the submit below then admits as a
                        # prefix HIT. A sequence-parallel handoff
                        # carries a LIST of shard slabs, merged in
                        # order through partial-prefix import (ISSUE
                        # 18). Any import problem falls back to the
                        # cold path — always correct, never lost.
                        slabs = (req.slab if isinstance(req.slab, list)
                                 else [req.slab])
                        try:
                            with telemetry.tracer().span(
                                    "handoff_import",
                                    trace_id=req.trace_id,
                                    track=f"replica{r}",
                                    shards=len(slabs),
                                    pages=sum(len(s.get("payload", []))
                                              for s in slabs)):
                                for sl in slabs:
                                    eng.import_prefix_slab(sl)
                        except Exception as e:  # noqa: BLE001
                            fflogger.warning(
                                "router: slab import on replica %d "
                                "failed (%s) — cold-path fallback", r, e)
                            with self._lock:
                                self._handoff_fallbacks += 1
                        req.slab = None
                    ereq = eng.submit(req.prompt, req.max_new_tokens,
                                      deadline=req.deadline,
                                      trace_id=req.trace_id,
                                      temperature=req.temperature,
                                      top_p=req.top_p, top_k=req.top_k,
                                      seed=req.seed,
                                      adapter=req.adapter)
                    with self._lock:
                        if self._fenced[r]:     # fenced mid-hand-off
                            return
                        self._outstanding[r][req.rid] = (req, ereq)
                progressed = eng.step() if busy else False
            except Exception as e:  # noqa: BLE001 — ANY driver/engine
                #   death is a replica loss; classification happens in
                #   the fence reason
                with self._lock:
                    self._fence_locked(r, f"{type(e).__name__}: {e}")
                return
            self._heartbeat[r] = time.monotonic()
            self._collect(r)
            self._collect_tier_events(r)
            if self._tm_on:
                # fleet-side SLO tick: returns at one time compare
                # until a full window has elapsed
                flightrec.slo_monitor().maybe_evaluate()
            if not progressed and not assigned:
                time.sleep(0.002)   # idle: don't spin the host

    def _handoff_prefill(self, r: int, eng, req: FleetRequest):
        """Prefill-replica half of the role split: run the prefill-only
        admission through the replica's warm bucket programs, export the
        finished prompt's KV pages (+ quantized scales, draft pool
        included) as a host-memory slab, and move the request — slab in
        hand — to the FRONT of the router queue for decode placement
        (handoff work has waited longest). Pool pressure or a failed
        export downgrades to the cold path on a decode replica; an
        engine death propagates to the driver's fence handler, whose
        exactly-once requeue re-classifies the request at its next
        dispatch."""
        slab = None
        sharded = False
        if self.seq_parallel_shards >= 2:
            # monster-prompt path: fan the prefix out across the prefill
            # tier; any problem (too small, lone replica, pressure,
            # export miss) falls through to the single-replica export
            slab = self._seq_parallel_prefill(r, eng, req)
            sharded = slab is not None
        if slab is None:
            with telemetry.tracer().span("handoff_export",
                                         trace_id=req.trace_id,
                                         track=f"replica{r}") as sp:
                if eng.prefill_into_cache(req.prompt,
                                          adapter=req.adapter) is not None:
                    slab = eng.export_prefix_slab(req.prompt,
                                                  adapter=req.adapter)
                sp.annotate(exported=slab is not None)
        with self._lock:
            if self._fenced[r]:
                return          # the fence already requeued this request
            if req.state != "dispatched" or req.replica != r:
                return          # stale: resubmitted elsewhere meanwhile
            self._outstanding[r].pop(req.rid, None)
            req.state = "queued"
            req.replica = -1
            req.phase = "decode" if slab is not None else "direct"
            req.slab = slab
            if slab is not None:
                req.handoff = True
                self._handoffs += 1
                if sharded:
                    self._seq_parallel += 1
            else:
                self._handoff_fallbacks += 1
            self._queue.appendleft(req)

    def _seq_parallel_prefill(self, r: int, eng, req: FleetRequest):
        """Sequence-parallel prefill (ISSUE 18): split the prompt's
        page-aligned prefix into ``seq_parallel_shards`` contiguous
        page ranges and compute each on a prefill-capable replica —
        shard 0 on THIS replica, later shards on round-robin peers that
        first import the earlier shards' slabs (their shard is then a
        prefix-HIT tail compute, attending real KV for everything
        before it — the causal dependency sequence sharding must
        honor). Each shard exports a partial-prefix slab
        (``export_prefix_slab(start_page=shard start)``); the decode
        replica merges the LIST in order through partial-prefix
        ``import_prefix_slab``, bitwise the single-replica pages
        (tests/test_seq_parallel.py). Returns the slab list, or None —
        prompt too small (< shards * handoff_min_pages full pages),
        no peer alive, pool pressure anywhere, or any shard error —
        and the caller falls back to the single-replica export."""
        shards = self.seq_parallel_shards
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        last = prompt.size // self.page_size
        if last < shards * self.handoff_min_pages:
            return None
        with self._lock:
            cands = [i for i in range(self.n)
                     if not self._fenced[i] and not self._suspended[i]
                     and self.roles[i] in ("prefill", "mixed")]
        if r not in cands or len(cands) < 2:
            return None         # sharding needs a live peer to pay off
        cands.remove(r)
        cands.insert(0, r)      # shard 0 stays home (its KV is local)
        # contiguous page ranges, remainder spread over the front shards
        base, rem = divmod(last, shards)
        bounds = [0]
        for i in range(shards):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        slabs = []
        try:
            with telemetry.tracer().span("seq_parallel_prefill",
                                         trace_id=req.trace_id,
                                         track=f"replica{r}",
                                         shards=shards,
                                         pages=last) as sp:
                for i in range(shards):
                    s_pg, e_pg = bounds[i], bounds[i + 1]
                    eng_i = self.engines[cands[i % len(cands)]]
                    for sl in slabs:
                        # cumulative merge: already-cached chunks are
                        # skipped, so re-imports on a reused replica
                        # are cheap no-ops
                        eng_i.import_prefix_slab(sl)
                    sub = prompt[:e_pg * self.page_size]
                    if eng_i.prefill_into_cache(
                            sub, adapter=req.adapter) is None:
                        sp.annotate(aborted=f"shard{i}_pressure")
                        return None
                    sl = eng_i.export_prefix_slab(
                        sub, adapter=req.adapter, start_page=s_pg)
                    if sl is None:
                        sp.annotate(aborted=f"shard{i}_export")
                        return None
                    slabs.append(sl)
        except Exception as e:  # noqa: BLE001 — any shard failure
            #   downgrades; the single-replica path is always correct
            fflogger.warning(
                "router: sequence-parallel prefill failed (%s) — "
                "single-replica fallback", e)
            return None
        return slabs

    def _collect_tier_events(self, r: int):
        """Fold the replica's depth-1 tier transitions into the affinity
        map's TIER dimension: a demoted prefix keeps routing home (the
        host copy + H2D promotion beats a cold re-prefill anywhere
        else), and a prefix dead in BOTH tiers drops its entry so
        cold-prefix traffic stops chasing a page that no longer
        exists."""
        events = self.engines[r].drain_tier_events()
        if not events:
            return
        with self._lock:
            for key, tier in events:
                entry = self._affinity.get(key)
                if entry is None or entry[0] != r:
                    continue
                if tier is None:
                    del self._affinity[key]
                else:
                    self._affinity[key] = (r, tier)

    def _collect(self, r: int):
        """Finalize engine requests that settled on replica r. Runs on
        r's own driver thread after its step(), so the engine states it
        reads are final; the router lock makes finalize exactly-once
        even against a concurrent fence (state must still be
        "dispatched" and owned by r)."""
        with self._lock:
            out = self._outstanding[r]
            for rid in list(out.keys()):
                req, ereq = out[rid]
                if ereq is None or ereq.state in ("queued", "running"):
                    continue
                del out[rid]
                if req.state != "dispatched" or req.replica != r:
                    continue    # fenced + resubmitted elsewhere: stale
                if ereq.state == "done":
                    req.tokens = list(ereq.tokens)
                    # engine TTFT measures from ENGINE submit; the
                    # router's adds the dispatch wait
                    req.ttft = (ereq.t_submit - req.t_submit) + ereq.ttft
                    self._finalize_locked(req, "done")
                elif ereq.state == "timeout":
                    self._finalize_locked(
                        req, "timeout",
                        ereq.error or "deadline expired in engine queue")
                else:
                    self._finalize_locked(
                        req, "failed", ereq.error or "engine failure")

    # ---- observability ------------------------------------------------------

    def _flightrec_source(self):
        """Post-mortem bundle payload: the fleet ledger + per-replica
        engine rows (stats() reads each engine outside the router lock;
        the recorder's per-source timeout bounds a wedged replica)."""
        return ("router", {"stats": self.stats(),
                           "health": self.health()})

    def _health_probe(self):
        """The /healthz fleet row — health() never takes an engine
        lock, so the rollup answers mid-tick."""
        return {"kind": "router", **self.health()}

    def dump_flight_record(self, directory: Optional[str] = None,
                           **note) -> Optional[str]:
        """Manual post-mortem bundle (the router half of the ISSUE-15
        trigger API): synchronous, always writes (merging any pending
        debounced triggers), returns the bundle path — or None when
        telemetry is off. Raises without a configured
        ``FFConfig.flight_recorder_dir`` and no ``directory``."""
        return flightrec.dump("manual", directory=directory,
                              source="router", **note)

    def recent_traces(self, n: int = 32) -> List[Dict]:
        """Span trees of the most recent fleet requests still in the
        bounded trace ring (newest last): per request the root span,
        every child span across replicas (handoff/failover included —
        the trace id survives both), the instant annotations
        (dispatch/resubmit/fault), and a ``complete`` verdict. Export
        the raw ring with ``telemetry.export_chrome_trace()``."""
        tr = telemetry.tracer()
        mine = f"req-{self._tm_uid}-"
        ids = [t for t in tr.trace_ids() if t.startswith(mine)]
        return [tr.trace_tree(t) for t in ids[-n:]]

    def _tm_collect(self, reg):
        """Scrape-time collector: the fleet ledger as ``ff_router_*``
        series (the failure-drill acceptance surface: fenced,
        resubmitted, timeouts, rejected, handoffs), the fleet rollup as
        ``ff_fleet_*``, and per-replica liveness/load labeled
        (replica, role). Engine collectors export their own series."""
        st = self.stats()
        for k, v in st.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            reg.gauge(f"ff_router_{k}",
                      f"ServingRouter stats()['{k}']").set(v)
        for k, v in st["fleet"].items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            reg.gauge(f"ff_fleet_{k}",
                      f"fleet rollup stats()['fleet']['{k}']").set(v)
        for tier, pages in st["fleet"]["pages_by_tier"].items():
            reg.gauge("ff_fleet_kv_pages", "fleet KV pages by tier",
                      labels=("tier",)).labels(tier).set(pages)
        # elastic fleet (ISSUE 20): the replica-count gauge the
        # autoscaler and dashboards watch, plus the preemption ledger
        reg.gauge("ff_fleet_replica_count",
                  "live (non-fenced, non-retired) replicas"
                  ).set(st["alive"])
        reg.gauge("ff_preempt_total",
                  "replica preemptions handled").set(st["preempts"])
        reg.gauge("ff_preempt_evacuated_requests",
                  "requests cleanly evacuated off retiring/preempted "
                  "replicas (no loss counted)"
                  ).set(st["evacuated_requests"])
        reg.gauge("ff_preempt_evacuated_pages",
                  "prefix-cache pages inherited by survivors"
                  ).set(st["evacuated_pages"])
        reg.gauge("ff_preempt_evacuation_bytes",
                  "host bytes moved by prefix evacuation"
                  ).set(st["evacuation_bytes"])
        reg.gauge("ff_preempt_deadline_misses",
                  "evacuations that blew their deadline and fell back "
                  "to a fence").set(st["evac_deadline_misses"])
        live = reg.gauge("ff_router_replica_up",
                         "1 = replica live, 0 = fenced",
                         labels=("replica", "role"))
        outg = reg.gauge("ff_router_replica_outstanding",
                         "router outstanding ledger per replica",
                         labels=("replica", "role"))
        for row in st["per_replica"]:
            lab = (str(row["replica"]), row["role"])
            live.labels(*lab).set(0 if row["fenced"] else 1)
            outg.labels(*lab).set(row["outstanding"])

    def stats(self) -> Dict:
        """Fleet ledger + per-replica engine stats + the FLEET ROLLUP
        (the ISSUE-12 satellite): per-replica ``ServingEngine.stats()``
        merged into one ``"fleet"`` dict — aggregate prefix hit rate,
        pages by tier (hbm/host), handoff and migration counters, and
        per-role queue depths — so callers stop looping replicas and
        re-deriving rates by hand. The router counters (fenced,
        resubmitted, timeouts, rejected) are the failure-drill
        acceptance surface; TTFT percentiles cover COMPLETED requests
        and measure router-submit -> first token (queue wait included —
        that is what shedding bounds). Engine snapshots are taken
        OUTSIDE the router lock (each serializes behind its own
        replica's tick only)."""
        eng_stats = [eng.stats() for eng in self.engines]
        with self._lock:
            ttfts = sorted(self._ttfts)

            def pct(p):
                if not ttfts:
                    return 0.0
                return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

            per_replica = []
            for r, eng in enumerate(self.engines):
                row = {"replica": r, "role": self.roles[r],
                       "fenced": self._fenced[r],
                       "fence_reason": self._fence_reason[r],
                       "retired": self._retired[r],
                       "outstanding": self._load(r),
                       "weight_version": eng.weight_version,
                       "deploy_state": eng.deploy_state,
                       "suspended": self._suspended[r],
                       **eng.load()}
                per_replica.append(row)
            retired = sum(self._retired)
            return {
                # "replicas" is the CURRENT fleet size (retirees left
                # cleanly — they are not capacity and not down);
                # "replicas_total" counts every index ever created
                "replicas": self.n - retired,
                "replicas_total": self.n,
                "retired": retired,
                "alive": len(self._alive()),
                "roles": list(self.roles),
                "submitted": self._submitted,
                "dispatched": self._dispatched,
                "completed": self._completed,
                "failed": self._failed,
                "timeouts": self._timeouts,
                "rejected": self._rejected,
                "fenced": self._fenced_count,
                "resubmitted": self._resubmitted,
                "handoffs": self._handoffs,
                "handoff_fallbacks": self._handoff_fallbacks,
                # rolling-deploy ledger (ISSUE 17, keys pinned):
                # completed per-replica swaps, automatic rollbacks, and
                # whether a roll is in progress right now
                "swaps_completed": self._swaps_completed,
                "rollbacks": self._rollbacks,
                "deploying": self._deploying,
                # elastic-fleet ledger (ISSUE 20, keys pinned):
                # membership changes + the evacuation half of
                # exactly-once (clean transfers, losses NOT counted)
                "scale_outs": self._scale_outs,
                "scale_ins": self._scale_ins,
                "preempts": self._preempts,
                "evacuated_requests": self._evacuated_requests,
                "evacuated_slabs": self._evacuated_slabs,
                "evacuated_pages": self._evacuated_pages,
                "evacuation_bytes": self._evacuation_bytes,
                "evac_deadline_misses": self._evac_deadline_misses,
                "preempt_margin_s": self._preempt_margin_s,
                "queued": len(self._queue),
                "max_queue": self.max_queue,
                "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
                "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
                "affinity_keys": len(self._affinity),
                "affinity_host_keys": sum(
                    1 for v in self._affinity.values() if v[1] == "host"),
                "per_replica": per_replica,
                "fleet": self._fleet_rollup_locked(eng_stats),
            }

    def _fleet_rollup_locked(self, eng_stats: List[Dict]) -> Dict:
        """Merge per-replica engine stats into ONE fleet dict."""
        agg = {k: sum(s[k] for s in eng_stats)
               for k in ("requests", "completed", "failed", "timeouts",
                         "tokens_generated", "recompiles",
                         "prefix_lookups", "prefix_hits",
                         "prefill_tokens_saved", "prefix_evictions",
                         "kv_pages_hbm", "kv_pages_host",
                         "tier_demotions", "tier_promotions",
                         "tier_demote_failures", "tier_promote_failures",
                         "tier_host_evictions", "tier_pending_migrations",
                         "prefill_only_requests", "prefix_slab_exports",
                         "prefix_slab_imports", "prefix_pages_imported",
                         "partial_slab_imports",
                         "prefill_chunks_interleaved",
                         "prefill_preempted_ticks",
                         "spec_proposed", "spec_accepted",
                         "sampled_requests", "adapter_faults",
                         "adapter_evictions", "adapter_pages_in_use",
                         "adapters_resident")}
        agg["prefix_hit_rate"] = round(
            agg["prefix_hits"] / max(1, agg["prefix_lookups"]), 4)
        agg["spec_accept_rate"] = round(
            agg["spec_accepted"] / max(1, agg["spec_proposed"]), 4)
        agg["pages_by_tier"] = {"hbm": agg.pop("kv_pages_hbm"),
                                "host": agg.pop("kv_pages_host")}
        agg["handoffs"] = self._handoffs
        agg["handoff_fallbacks"] = self._handoff_fallbacks
        agg["seq_parallel_prefills"] = self._seq_parallel
        per_role: Dict[str, Dict] = {}
        for r, role in enumerate(self.roles):
            if self._retired[r]:
                continue    # a retiree is not capacity (its historical
                #             counters still ride the aggregate above)
            row = per_role.setdefault(role, {
                "replicas": 0, "alive": 0, "outstanding": 0,
                "queued": 0, "active_slots": 0})
            row["replicas"] += 1
            if not self._fenced[r]:
                load = self.engines[r].load()
                row["alive"] += 1
                row["outstanding"] += self._load(r)
                row["queued"] += load["queued"]
                row["active_slots"] += load["active_slots"]
        agg["per_role"] = per_role
        return agg

    def health(self) -> Dict:
        """Cheap fleet probe: never takes an engine lock (per-replica
        load rides the lock-free ``load()``), so it answers even while
        every replica is mid-dispatch."""
        with self._lock:
            alive = self._alive()
            open_work = (bool(self._queue) or any(self._outstanding)
                         or any(self._to_submit))
            if self._draining:
                status = "draining" if open_work else "drained"
            elif not alive:
                status = "dead"
            elif open_work:
                status = "busy"
            else:
                status = "idle"
            return {
                "status": status,
                "admitting": not self._draining and bool(alive),
                "alive": len(alive),
                # current fleet size: retirees left cleanly and are not
                # "down" — the /healthz rollup compares alive against
                # this, so a finished scale-in reads ok, not degraded
                "replicas": self.n - sum(self._retired),
                "retired": sum(self._retired),
                "queued": len(self._queue),
                "outstanding": sum(self._load(r) for r in self._alive()),
                "fenced": self._fenced_count,
                "max_queue": self.max_queue,
                # rolling deploy (ISSUE 17): /healthz reports every
                # replica's weight version, and `deploying` degrades
                # (never breaches) the rollup while a roll is live
                "deploying": self._deploying,
                "weight_versions": [eng.weight_version
                                    for eng in self.engines],
                "deploy_states": [eng.deploy_state
                                  for eng in self.engines],
            }
