"""Fleet serving: a router over N ServingEngine replicas.

One ServingEngine is a replica, not a service: nothing survives the loss
of an engine, nothing bounds how long a request can wait, and an
overloaded queue grows without limit. The paper's discipline — drive
placement from MEASURED behavior of the real machine, not static
assignment (PAPERS.md "Beyond Data and Model Parallelism") — applies one
level up: this router routes, sheds and fails over on the live
``health()``/``load()`` signals each replica already exports.

``ServingRouter`` fronts N replicas, each driven by its own thread:

  * FAILOVER — a replica whose driver thread raises (a crashed engine),
    that stops heartbeating past ``health_timeout_s`` (a hung dispatch),
    or whose health probe itself dies is FENCED: its in-flight and
    engine-queued requests are resubmitted to survivors exactly once.
    Greedy decode is deterministic and an un-admitted request keeps no
    cache state (the PR-5 drain/requeue contract), so a resubmitted
    request re-decodes from scratch on the survivor and its final stream
    is token-identical to an uninterrupted single-replica run — the dead
    replica's partial tokens are discarded, never spliced. A request
    whose SECOND replica also dies fails loudly ("replica lost twice")
    instead of ping-ponging.
  * PER-REQUEST DEADLINES — ``submit(..., deadline_s=)``. A request that
    expires while queued (in the router queue OR a replica's engine
    queue) retires as ``"timeout"`` without ever prefilling; an expired
    request found in-flight on a FENCED replica is not resubmitted (the
    work is already worthless); an admitted request on a healthy replica
    is never cancelled mid-batch (cancellation would disturb the
    fixed-shape slot program) — its late completion is delivered and the
    caller may discard it.
  * OVERLOAD SHEDDING — the router queue is bounded by ``max_queue``
    (FFConfig.serve_max_queue; 0 = unbounded). A submit over the bound
    returns immediately with state ``"rejected"``: excess load fails in
    microseconds at the front door, so ACCEPTED requests keep a bounded
    queue wait and the fleet's p99 TTFT stays flat instead of every
    request sharing an ever-growing backlog (bench `router_serving`
    measures exactly this).
  * HEALTH-DRIVEN PLACEMENT — dispatch picks the least-loaded live
    replica by the same counters ``health()`` exports (active slots +
    queued work, read via the router's own outstanding ledger plus the
    engine's lock-free ``load()``), with PREFIX AFFINITY on top: the
    first full KV page of the prompt (exactly the radix trie's first
    edge, so equal keys <=> a guaranteed trie hit) is hashed to the
    replica that last served it. Shared-prompt traffic therefore lands
    where its prefix pages are already cached instead of re-prefilling
    the same system prompt on every replica. Affinity is a preference,
    never a constraint — a fenced or saturated home replica falls back
    to least-loaded, so affinity can neither black-hole nor starve.

Failure drills are deterministic in CI via FF_FAULT
(runtime/faultinject.py): ``crash@replica:<r>`` kills replica r's driver
at its first busy tick (``crash(<t>)@replica:<r>`` at its t-th),
``hang@replica:<r>`` wedges it until the heartbeat sweep fences it, and
``slow(<ms>)@serve:<n>`` stalls an engine admission so an in-flight
deadline expires on cue.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject


class ReplicaCrash(RuntimeError):
    """Injected replica loss (FF_FAULT ``crash@replica:<r>``): raised on
    the replica's driver thread to simulate the whole engine dying
    mid-dispatch."""


@dataclass
class FleetRequest:
    """One router-level request and its lifecycle record. The underlying
    engine Request is replaced wholesale on failover — ``tokens`` always
    holds ONE replica's complete stream, never a splice."""

    rid: int
    prompt: np.ndarray              # (S,) int32
    max_new_tokens: int
    # absolute time.perf_counter() deadline (None = none)
    deadline: Optional[float] = None
    # first full KV page of the prompt (the radix trie's first edge);
    # None when the prompt is shorter than one page
    affinity: Optional[Tuple[int, ...]] = None
    # queued | dispatched | done | failed | timeout | rejected
    state: str = "queued"
    replica: int = -1               # current/last replica
    attempts: int = 0               # dispatches (attempts-1 = failovers)
    tokens: List[int] = field(default_factory=list)
    error: str = ""
    t_submit: float = 0.0
    ttft: float = 0.0               # router submit -> first token (s)
    t_done: float = 0.0

    @property
    def output(self) -> np.ndarray:
        """prompt + emitted tokens (the generate() shape)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def settled(self) -> bool:
        return self.state not in ("queued", "dispatched")


class ServingRouter:
    """Route requests over N ServingEngine replicas of one model.

    Each replica runs on its own daemon thread; the lock order is
    router -> engine, and an engine's lock is only ever taken by its own
    driver thread (plus warmup/drain when the fleet is quiet), so the
    two layers can never deadlock. ``submit()``/``run()`` from any
    thread; ``drain()`` for graceful shutdown, ``close()`` to abandon.

    ``start=False`` builds the fleet without spawning drivers (requests
    queue, shed and expire deterministically — the test hook);
    ``start()``/``run()`` bring the drivers up."""

    # the hang detector cannot distinguish a wedged dispatch from a
    # legitimately long one by wall clock alone, and a COLD tick
    # compiles its program (seconds, minutes on a real TPU pod) — so the
    # default timeout is sized for cold compiles. Latency-sensitive
    # fleets warmup() every replica first, after which a healthy tick is
    # milliseconds and a tight timeout (the drill tests run 0.5 s) is
    # meaningful.
    DEFAULT_HEALTH_TIMEOUT_S = 60.0

    def __init__(self, model, replicas: int = 2,
                 max_queue: Optional[int] = None,
                 health_timeout_s: Optional[float] = None,
                 dispatch_backlog: Optional[int] = None,
                 start: bool = True, **engine_kwargs):
        if health_timeout_s is None:
            health_timeout_s = self.DEFAULT_HEALTH_TIMEOUT_S
        if replicas < 1:
            raise ValueError(f"replicas={replicas}: must be >= 1")
        if health_timeout_s <= 0:
            raise ValueError(
                f"health_timeout_s={health_timeout_s}: must be > 0")
        cfg = model.config
        self.model = model
        self.n = int(replicas)
        self.max_queue = int(max_queue if max_queue is not None
                             else getattr(cfg, "serve_max_queue", 0))
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue={self.max_queue}: must be >= 0 (0 = unbounded)")
        self.health_timeout_s = float(health_timeout_s)
        self.engines = [model.make_serving_engine(**engine_kwargs)
                        for _ in range(self.n)]
        self.page_size = self.engines[0].page_size
        slots = self.engines[0].slots
        # outstanding-per-replica cap: slots in flight + a short engine
        # queue so admission can pipeline, but deep backlogs stay in the
        # ROUTER queue where deadlines expire before dispatch and a
        # fence requeues cheaply
        self.dispatch_backlog = int(dispatch_backlog
                                    if dispatch_backlog is not None
                                    else slots)
        self._cap = slots + self.dispatch_backlog

        self._lock = threading.RLock()
        self._queue: collections.deque = collections.deque()  # FleetRequest
        # rid -> (FleetRequest, engine Request | None): None until the
        # replica's driver hands the request to its engine
        self._outstanding: List[Dict] = [dict() for _ in range(self.n)]
        self._to_submit: List[collections.deque] = [
            collections.deque() for _ in range(self.n)]
        # prefix chunk -> replica that last served it (bounded LRU: the
        # map must not grow with total distinct-prompt traffic)
        self._affinity: "collections.OrderedDict" = collections.OrderedDict()
        self._affinity_cap = 4096
        self._fenced = [False] * self.n
        self._fence_reason = [""] * self.n
        self._heartbeat = [time.monotonic()] * self.n
        self._busy_ticks = [0] * self.n
        self._stop = threading.Event()
        self._draining = False
        self._next_rid = 0
        # router counters (stats()): the fleet-level ledger
        self._submitted = 0
        self._dispatched = 0
        self._completed = 0
        self._failed = 0
        self._timeouts = 0
        self._rejected = 0
        self._fenced_count = 0
        self._resubmitted = 0
        self._ttfts = collections.deque(maxlen=4096)
        self._threads: List[threading.Thread] = []
        self._started = False
        if start:
            self.start()

    # ---- lifecycle ----------------------------------------------------------

    def start(self):
        """Spawn one driver thread per replica (idempotent)."""
        with self._lock:
            if self._started:
                return
            self._started = True
        self._threads = [
            threading.Thread(target=self._replica_main, args=(r,),
                             daemon=True, name=f"ff-router-replica-{r}")
            for r in range(self.n)]
        for t in self._threads:
            t.start()

    def submit(self, prompt, max_new_tokens: int,
               deadline_s: Optional[float] = None) -> FleetRequest:
        """Queue one request (validated synchronously against replica
        0's admission rules, so a malformed request raises HERE, not on
        a driver thread). Over ``max_queue``, returns immediately with
        state ``"rejected"`` — shedding is a fast status, not an
        exception, so a loaded front door costs one queue-length check."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be >= 1")
        eng0 = self.engines[0]
        bucket = eng0._bucket(prompt.size)
        if bucket + max_new_tokens > eng0.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({bucket}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len {eng0.max_seq_len}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s={deadline_s}: must be >= 0")
        now = time.perf_counter()
        affinity = (tuple(int(t) for t in prompt[:self.page_size])
                    if prompt.size >= self.page_size else None)
        with self._lock:
            if self._draining:
                raise RuntimeError(
                    "ServingRouter is draining: new requests are not "
                    "admitted")
            req = FleetRequest(
                rid=self._next_rid, prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                deadline=(now + deadline_s if deadline_s is not None
                          else None),
                affinity=affinity, t_submit=now)
            self._next_rid += 1
            self._submitted += 1
            if self.max_queue and len(self._queue) >= self.max_queue:
                req.state = "rejected"
                req.error = f"router queue full ({self.max_queue})"
                req.t_done = time.perf_counter()
                self._rejected += 1
                return req
            self._queue.append(req)
        return req

    def run(self, prompts, max_new_tokens: int = 32,
            deadline_s: Optional[float] = None,
            timeout: Optional[float] = None) -> List[FleetRequest]:
        """Submit ``prompts`` and block until every one settles; returns
        the requests in submission order (rejected/expired included)."""
        self.start()
        reqs = [self.submit(p, max_new_tokens, deadline_s=deadline_s)
                for p in prompts]
        self.wait(reqs, timeout=timeout)
        return reqs

    def wait(self, reqs: Optional[List[FleetRequest]] = None,
             timeout: Optional[float] = None):
        """Block until ``reqs`` (default: everything outstanding) settle.
        This is also where fleet-level liveness runs when the caller's
        thread is the only healthy one left: the hang sweep and the
        no-survivors check. Brings the drivers up if nobody has yet —
        only driver threads move queued work, so waiting on an
        un-started fleet would otherwise spin forever."""
        self.start()
        t0 = time.monotonic()
        while True:
            with self._lock:
                self._sweep_hangs_locked()
                self._fail_if_no_survivors_locked()
                if reqs is None:
                    open_work = (bool(self._queue)
                                 or any(self._outstanding)
                                 or any(self._to_submit))
                else:
                    open_work = any(not r.settled for r in reqs)
            if not open_work:
                return
            if self._stop.is_set():
                raise RuntimeError(
                    "router.wait: the router was closed with work still "
                    "open — close() abandons un-settled requests")
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"router.wait: work still open after {timeout}s "
                    f"(health: {self.health()})")
            time.sleep(0.003)

    def warmup(self, prompts, max_new_tokens: int = 4):
        """Drive ``prompts`` through EVERY replica engine directly
        (bypassing the router queue) so all replicas compile the same
        program set before measured traffic: failover traffic onto a
        survivor then hits only warm programs — the smoke asserts zero
        survivor recompiles through a mid-flight crash. Call while the
        fleet is quiet (before submitting routed traffic)."""
        for eng in self.engines:
            eng.run([np.asarray(p, np.int32) for p in prompts],
                    max_new_tokens=max_new_tokens)

    def drain(self) -> Dict:
        """Graceful fleet shutdown: stop admitting, let the drivers
        finish everything queued and in flight, stop the threads, drain
        the surviving engines, return a final stats snapshot."""
        with self._lock:
            self._draining = True
        self.start()    # a start=False fleet still owes its queued work
        self.wait(None)
        self.close()
        for r, eng in enumerate(self.engines):
            if not self._fenced[r]:
                eng.drain()
        snap = self.stats()
        snap["drained"] = True
        fflogger.info(
            "router: drained — %d completed, %d failed, %d timeouts, "
            "%d rejected; %d fenced, %d resubmitted",
            snap["completed"], snap["failed"], snap["timeouts"],
            snap["rejected"], snap["fenced"], snap["resubmitted"])
        return snap

    def close(self):
        """Stop the driver threads without waiting for open work (the
        work stays un-settled); idempotent."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)

    # ---- dispatch (router lock held) ----------------------------------------

    def _alive(self) -> List[int]:
        return [r for r in range(self.n) if not self._fenced[r]]

    def _load(self, r: int) -> int:
        # the health() counters, via the router's exact outstanding
        # ledger: dispatched minus settled == active + engine-queued +
        # assigned-but-not-yet-handed-over (the hand-off deque is a
        # SUBSET of outstanding — never add the two)
        return len(self._outstanding[r])

    def _pick_replica_locked(self, req: FleetRequest) -> Optional[int]:
        alive = self._alive()
        if not alive:
            return None
        if req.affinity is not None:
            home = self._affinity.get(req.affinity)
            if home is not None and not self._fenced[home] \
                    and self._load(home) < self._cap:
                return home
        cands = [r for r in alive if self._load(r) < self._cap]
        if not cands:
            return None
        return min(cands, key=lambda r: (self._load(r), r))

    def _dispatch_locked(self):
        """Assign queued work: expired requests retire as timeout
        BEFORE placement (never dispatched), the rest go to the affinity
        home when it is live and has room, else the least-loaded live
        replica with room. Assignment only moves the request onto the
        replica's hand-off deque — the driver thread performs the actual
        engine.submit on its own lock, so dispatch never blocks behind a
        replica mid-tick."""
        now = time.perf_counter()
        while self._queue:
            req = self._queue[0]
            if req.deadline is not None and now >= req.deadline:
                self._queue.popleft()
                self._finalize_locked(
                    req, "timeout", "deadline expired in router queue")
                continue
            r = self._pick_replica_locked(req)
            if r is None:
                return
            self._queue.popleft()
            req.state = "dispatched"
            req.replica = r
            req.attempts += 1
            self._dispatched += 1
            if req.affinity is not None:
                self._affinity[req.affinity] = r
                self._affinity.move_to_end(req.affinity)
                while len(self._affinity) > self._affinity_cap:
                    self._affinity.popitem(last=False)
            self._outstanding[r][req.rid] = (req, None)
            self._to_submit[r].append(req)

    def _finalize_locked(self, req: FleetRequest, state: str,
                         error: str = ""):
        req.state = state
        req.error = error
        req.t_done = time.perf_counter()
        if state == "done":
            self._completed += 1
            if req.ttft:
                self._ttfts.append(req.ttft)
        elif state == "timeout":
            self._timeouts += 1
        else:
            self._failed += 1

    def _fence_locked(self, r: int, reason: str):
        """Fence replica r: mark it dead, requeue its outstanding work.
        Exactly-once resubmission: a request is resubmitted only from
        state "dispatched" on THIS replica, at most once overall
        (attempts caps at 2), and never after its deadline — an expired
        in-flight request is already worthless, so it retires as timeout
        instead of burning survivor capacity."""
        if self._fenced[r]:
            return
        self._fenced[r] = True
        self._fence_reason[r] = reason
        self._fenced_count += 1
        out = self._outstanding[r]
        self._outstanding[r] = {}
        self._to_submit[r].clear()
        now = time.perf_counter()
        requeued = []
        for _, (req, _ereq) in sorted(out.items()):
            if req.state != "dispatched" or req.replica != r:
                continue
            if req.deadline is not None and now >= req.deadline:
                self._finalize_locked(
                    req, "timeout",
                    f"deadline expired in flight on fenced replica {r}")
            elif req.attempts >= 2:
                self._finalize_locked(
                    req, "failed",
                    f"replica lost twice (last: {reason})")
            else:
                req.state = "queued"
                req.replica = -1
                req.tokens = []   # discard the dead replica's partial
                #                   stream: the survivor re-decodes the
                #                   identical greedy tokens from scratch
                requeued.append(req)
                self._resubmitted += 1
        # front of the queue, original order: failover work has waited
        # longest
        for req in reversed(requeued):
            self._queue.appendleft(req)
        # shared-prefix homes pointing at the corpse re-home on next use
        for key in [k for k, v in self._affinity.items() if v == r]:
            del self._affinity[key]
        fflogger.warning(
            "router: replica %d FENCED (%s) — %d requests resubmitted, "
            "%d survivors", r, reason, len(requeued), len(self._alive()))
        self._fail_if_no_survivors_locked()

    def _sweep_hangs_locked(self):
        """Fence any replica with outstanding work whose driver has not
        heartbeaten within health_timeout_s — run by every healthy
        driver's tick and by wait(), so one wedged replica cannot take
        the detector down with it."""
        if not self._started:
            return
        now = time.monotonic()
        for r in range(self.n):
            if self._fenced[r]:
                continue
            if not self._outstanding[r] and not self._to_submit[r]:
                continue
            if now - self._heartbeat[r] > self.health_timeout_s:
                self._fence_locked(
                    r, f"hang: no heartbeat for {self.health_timeout_s}s")

    def _fail_if_no_survivors_locked(self):
        if self._started and not self._alive():
            while self._queue:
                req = self._queue.popleft()
                self._finalize_locked(
                    req, "failed", "no live replicas")

    # ---- the replica driver thread ------------------------------------------

    def _maybe_injected_fault(self, r: int) -> bool:
        """FF_FAULT fleet drills, checked each busy tick: crash raises
        ReplicaCrash (the driver's except fences and requeues — the real
        crash path end to end); hang stops heartbeating and spins until
        the sweep fences this replica (returns True: exit the driver)."""
        plan = faultinject.active_plan()
        scheduled, value = plan.pending("crash", "replica", r)
        if scheduled and self._busy_ticks[r] >= (value or 1):
            plan.at_site("crash", "replica", r)
            raise ReplicaCrash(f"injected crash@replica:{r} "
                               f"(busy tick {self._busy_ticks[r]})")
        scheduled, value = plan.pending("hang", "replica", r)
        if scheduled and self._busy_ticks[r] >= (value or 1):
            plan.at_site("hang", "replica", r)
            fflogger.warning(
                "router: replica %d injected hang — waiting for the "
                "health sweep to fence it", r)
            while not self._fenced[r] and not self._stop.is_set():
                time.sleep(0.005)
            return True
        return False

    def _replica_main(self, r: int):
        eng = self.engines[r]
        while not self._stop.is_set():
            with self._lock:
                if self._fenced[r]:
                    return
                self._sweep_hangs_locked()
                self._dispatch_locked()
                assigned = []
                while self._to_submit[r]:
                    assigned.append(self._to_submit[r].popleft())
                busy = bool(self._outstanding[r])
            # heartbeat BEFORE the tick too: the sweep then measures one
            # tick's duration, not dispatch-wait + tick
            self._heartbeat[r] = time.monotonic()
            try:
                if busy:
                    self._busy_ticks[r] += 1
                    if self._maybe_injected_fault(r):
                        return
                for req in assigned:
                    ereq = eng.submit(req.prompt, req.max_new_tokens,
                                      deadline=req.deadline)
                    with self._lock:
                        if self._fenced[r]:     # fenced mid-hand-off
                            return
                        self._outstanding[r][req.rid] = (req, ereq)
                progressed = eng.step() if busy else False
            except Exception as e:  # noqa: BLE001 — ANY driver/engine
                #   death is a replica loss; classification happens in
                #   the fence reason
                with self._lock:
                    self._fence_locked(r, f"{type(e).__name__}: {e}")
                return
            self._heartbeat[r] = time.monotonic()
            self._collect(r)
            if not progressed and not assigned:
                time.sleep(0.002)   # idle: don't spin the host

    def _collect(self, r: int):
        """Finalize engine requests that settled on replica r. Runs on
        r's own driver thread after its step(), so the engine states it
        reads are final; the router lock makes finalize exactly-once
        even against a concurrent fence (state must still be
        "dispatched" and owned by r)."""
        with self._lock:
            out = self._outstanding[r]
            for rid in list(out.keys()):
                req, ereq = out[rid]
                if ereq is None or ereq.state in ("queued", "running"):
                    continue
                del out[rid]
                if req.state != "dispatched" or req.replica != r:
                    continue    # fenced + resubmitted elsewhere: stale
                if ereq.state == "done":
                    req.tokens = list(ereq.tokens)
                    # engine TTFT measures from ENGINE submit; the
                    # router's adds the dispatch wait
                    req.ttft = (ereq.t_submit - req.t_submit) + ereq.ttft
                    self._finalize_locked(req, "done")
                elif ereq.state == "timeout":
                    self._finalize_locked(
                        req, "timeout",
                        ereq.error or "deadline expired in engine queue")
                else:
                    self._finalize_locked(
                        req, "failed", ereq.error or "engine failure")

    # ---- observability ------------------------------------------------------

    def stats(self) -> Dict:
        """Fleet ledger + per-replica engine stats. The router counters
        (fenced, resubmitted, timeouts, rejected) are the failure-drill
        acceptance surface; TTFT percentiles cover COMPLETED requests
        and measure router-submit -> first token (queue wait included —
        that is what shedding bounds)."""
        with self._lock:
            ttfts = sorted(self._ttfts)

            def pct(p):
                if not ttfts:
                    return 0.0
                return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

            per_replica = []
            for r, eng in enumerate(self.engines):
                row = {"replica": r, "fenced": self._fenced[r],
                       "fence_reason": self._fence_reason[r],
                       "outstanding": self._load(r),
                       **eng.load()}
                per_replica.append(row)
            return {
                "replicas": self.n,
                "alive": len(self._alive()),
                "submitted": self._submitted,
                "dispatched": self._dispatched,
                "completed": self._completed,
                "failed": self._failed,
                "timeouts": self._timeouts,
                "rejected": self._rejected,
                "fenced": self._fenced_count,
                "resubmitted": self._resubmitted,
                "queued": len(self._queue),
                "max_queue": self.max_queue,
                "ttft_p50_ms": round(pct(0.50) * 1e3, 3),
                "ttft_p99_ms": round(pct(0.99) * 1e3, 3),
                "affinity_keys": len(self._affinity),
                "per_replica": per_replica,
            }

    def health(self) -> Dict:
        """Cheap fleet probe: never takes an engine lock (per-replica
        load rides the lock-free ``load()``), so it answers even while
        every replica is mid-dispatch."""
        with self._lock:
            alive = self._alive()
            open_work = (bool(self._queue) or any(self._outstanding)
                         or any(self._to_submit))
            if self._draining:
                status = "draining" if open_work else "drained"
            elif not alive:
                status = "dead"
            elif open_work:
                status = "busy"
            else:
                status = "idle"
            return {
                "status": status,
                "admitting": not self._draining and bool(alive),
                "alive": len(alive),
                "replicas": self.n,
                "queued": len(self._queue),
                "outstanding": sum(self._load(r) for r in range(self.n)
                                   if not self._fenced[r]),
                "fenced": self._fenced_count,
                "max_queue": self.max_queue,
            }
