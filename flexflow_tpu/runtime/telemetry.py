"""Unified telemetry plane: metrics registry + per-request tracing.

The paper's whole premise is choosing strategies from MEASURED costs, and
the fleet's runtime signals were scattered across ad-hoc dicts
(``ServingEngine.stats()``, ``ServingRouter.stats()``,
``model.last_step_breakdown``, ``kernel_tune.stats()``) with no time
dimension, no export format, and no way to reconstruct what happened to
ONE request as it crossed router -> prefill replica -> KV-page handoff ->
decode replica -> retirement. TensorFlow's system paper made timeline
tracing a first-class subsystem because distributed dataflow is
undebuggable without it; this module is that subsystem for both the
serving fleet and the training loop.

Three pieces, one process-wide substrate:

  * **Metrics registry** — thread-safe counters, gauges and fixed-memory
    log-bucket histograms with labeled series (replica, role, tier,
    dtype, impl). Export as Prometheus text exposition
    (``registry().to_prometheus()``, served by ``start_http_server`` /
    ``FFConfig.metrics_port`` on ``/metrics``) or a JSON snapshot
    (``registry().snapshot()``, ``/metrics.json``). Engines, routers and
    the kernel-tune table register *collectors* — weakly-referenced
    callbacks that publish their ``stats()`` dicts as gauges at scrape
    time — so every counter the ad-hoc dicts already carried (hit rates,
    handoffs, demotions/promotions, fenced/resubmitted/timeouts/rejected,
    recompile_count, kernel_tune hits) is a first-class series without a
    second bookkeeping path: the dict IS the collector's source, the
    registry is the export plane both share.

  * **Per-request tracing** — ``span()`` / ``begin()``+``end()`` /
    ``complete()`` record into one bounded in-memory ring (fixed memory:
    old events fall off; ``TRACE_RING_CAP`` events). Every event carries
    a ``trace_id`` that rides the request across threads, replicas,
    resubmission and the prefill->decode page handoff, so the span tree
    for one request is reconstructible fleet-wide
    (``trace_tree(trace_id)``, ``ServingRouter.recent_traces()``).
    Export as Chrome trace-event JSON (``export_chrome_trace`` —
    perfetto-loadable: pid = replica/subsystem track, tid = thread).

  * **Fault annotations** — ``runtime/faultinject.py`` reports every
    fired FF_FAULT event here (``annotate("fault", ...)``), so a fault
    drill's trace shows exactly where the fault landed
    (``fault_events()``; asserted by router_smoke/disagg_smoke).

Overhead discipline (the budget the bench stamps as
``telemetry_overhead_pct``): every hot-path emit is one lock-cheap
dict/deque op and a ``perf_counter()`` call; histograms are fixed arrays
(no per-observation allocation); ``set_enabled(False)`` (or
``FFConfig.telemetry="off"``) turns ``span()`` into a shared no-op and
short-circuits ``observe``/``inc``/``emit`` at one predicate.
"""

from __future__ import annotations

import bisect
import collections
import json
import math
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from flexflow_tpu.runtime import locks

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Tracer",
    "registry", "tracer", "reset", "set_enabled", "enabled",
    "annotate", "fault_events", "export_chrome_trace", "trace_tree",
    "start_http_server", "stop_http_server", "current_trace_id",
    "DEFAULT_LATENCY_BOUNDS", "log_bounds", "now_us", "bucket_quantile",
]

# ---------------------------------------------------------------- switch

_enabled = True


def set_enabled(on: bool) -> bool:
    """Flip the process-wide telemetry switch; returns the previous
    value. Off = ``span()`` yields a shared no-op, ``observe``/``inc``
    return at one predicate, the trace ring stops growing. Registered
    series keep their accumulated values (they just stop moving)."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    return prev


def enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------- metrics


def log_bounds(lo: float, hi: float, growth: float = 2.0) -> Tuple[float, ...]:
    """Geometric bucket bounds ``lo, lo*growth, ... >= hi`` — fixed
    memory whatever the value range, resolution a constant factor."""
    if lo <= 0 or hi <= lo or growth <= 1:
        raise ValueError(f"log_bounds({lo}, {hi}, {growth}): need "
                         f"0 < lo < hi and growth > 1")
    out = []
    b = float(lo)
    while b < hi:
        out.append(b)
        b *= growth
    out.append(b)
    return tuple(out)


# 100us .. ~210s in x2 steps: wide enough for TTFT on a cold CPU compile
# and tight enough for inter-token latency — 22 buckets, fixed memory
DEFAULT_LATENCY_BOUNDS = log_bounds(1e-4, 200.0)


def bucket_quantile(bounds: Tuple[float, ...], counts, q: float) -> float:
    """The one bucket-interpolated quantile estimator — shared by live
    histogram children and the SLO monitor's window deltas, so the
    windowed p99 an SLO judges can never diverge from the exported p99
    operators compare it against. ``counts`` has ``len(bounds) + 1``
    entries (the +Inf bucket last); 0.0 when empty; values past the
    last bound clamp to it (the +Inf bucket has no upper edge to
    interpolate against)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if seen + c >= rank and c > 0:
            if i >= len(bounds):            # +Inf bucket
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return bounds[-1]


class _Series:
    """One labeled child of a family. All mutation under the family
    lock (increments are nanoseconds; contention is the registry's
    problem, not the caller's)."""

    __slots__ = ("labels", "value", "_lock")

    def __init__(self, labels: Tuple[Tuple[str, str], ...],
                 lock: threading.Lock):
        self.labels = labels
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0):
        if not _enabled:
            return
        with self._lock:
            self.value += n

    def set(self, v: float):
        if not _enabled:
            return
        with self._lock:
            self.value = float(v)

    def get(self) -> float:
        return self.value


class _HistSeries:
    """Fixed-memory log-bucket histogram child: one count per bucket
    (cumulative at export, per-bucket in storage), running sum, count.
    Quantiles are estimated by linear interpolation inside the owning
    bucket — exact to a bucket width, which log buckets keep to a
    constant relative error."""

    __slots__ = ("labels", "bounds", "counts", "sum", "count", "_lock")

    def __init__(self, labels, bounds: Tuple[float, ...],
                 lock: threading.Lock):
        self.labels = labels
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, v: float):
        if not _enabled:
            return
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0..1) from the buckets (the shared
        ``bucket_quantile`` estimator)."""
        with self._lock:
            counts = list(self.counts)
        return bucket_quantile(self.bounds, counts, q)


class _Family:
    """One named metric family: children keyed by label values."""

    def __init__(self, name: str, help_: str, kind: str,
                 labelnames: Tuple[str, ...],
                 bounds: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.help = help_
        self.kind = kind                    # counter | gauge | histogram
        self.labelnames = labelnames
        self.bounds = bounds
        self._lock = locks.make_lock("telemetry-family")
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values, **kv):
        if kv:
            values = tuple(str(kv[k]) for k in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(values)} label values for "
                f"labels {self.labelnames}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    pairs = tuple(zip(self.labelnames, values))
                    child = (_HistSeries(pairs, self.bounds, self._lock)
                             if self.kind == "histogram"
                             else _Series(pairs, self._lock))
                    self._children[values] = child
        return child

    # label-free families act as their own single child
    def _solo(self):
        return self.labels()

    def inc(self, n: float = 1.0):
        self._solo().inc(n)

    def set(self, v: float):
        self._solo().set(v)

    def observe(self, v: float):
        self._solo().observe(v)

    def children(self):
        with self._lock:
            return list(self._children.values())


def _fmt_labels(pairs) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class Registry:
    """Process-wide metric registry. ``counter``/``gauge``/``histogram``
    get-or-create a family (idempotent by name; kind/labels must match —
    two subsystems registering the same name differently is a bug worth
    raising on). ``add_collector`` registers a weakly-referenced callback
    run before every export so live objects (engines, routers, the
    kernel-tune table) can publish their stats dicts as gauges exactly
    when someone is looking."""

    def __init__(self):
        self._lock = locks.make_lock("telemetry-registry")
        self._families: "collections.OrderedDict[str, _Family]" = \
            collections.OrderedDict()
        self._collectors: List[weakref.ref] = []

    # ---- family constructors -------------------------------------------

    def _family(self, name, help_, kind, labelnames, bounds=None):
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames \
                        or (kind == "histogram"
                            and fam.bounds != tuple(bounds or ())):
                    raise ValueError(
                        f"metric {name!r} re-registered as {kind} "
                        f"{labelnames} bounds={bounds} but exists as "
                        f"{fam.kind} {fam.labelnames} "
                        f"bounds={fam.bounds}")
                return fam
            fam = _Family(name, help_, kind, labelnames, bounds)
            self._families[name] = fam
            return fam

    def family(self, name: str) -> Optional[_Family]:
        """Look up an existing family by name (None when absent) — the
        SLO monitor windows registered histograms without creating
        them."""
        with self._lock:
            return self._families.get(name)

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> _Family:
        return self._family(name, help, "counter", labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> _Family:
        return self._family(name, help, "gauge", labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  bounds: Tuple[float, ...] = DEFAULT_LATENCY_BOUNDS
                  ) -> _Family:
        return self._family(name, help, "histogram", labels,
                            bounds=tuple(bounds))

    # ---- collectors -----------------------------------------------------

    def add_collector(self, fn: Callable[["Registry"], None]):
        """``fn(registry)`` runs before every export. Bound methods are
        held via WeakMethod so registering an engine's collector never
        keeps the engine alive; dead refs are pruned at export."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._lock:
            self._collectors.append(ref)

    def _run_collectors(self):
        with self._lock:
            refs = list(self._collectors)
        dead = []
        for ref in refs:
            fn = ref()
            if fn is None:
                dead.append(ref)
                continue
            try:
                fn(self)
            except Exception:   # a sick collector must not kill a scrape
                pass
        if dead:
            with self._lock:
                self._collectors = [r for r in self._collectors
                                    if r not in dead]

    # ---- export ---------------------------------------------------------

    def describe(self) -> Dict[str, int]:
        """Registry shape for bench honesty stamps: how many families /
        labeled series / histogram series exist right now."""
        with self._lock:
            fams = list(self._families.values())
        series = sum(len(f.children()) for f in fams)
        hists = sum(len(f.children()) for f in fams
                    if f.kind == "histogram")
        return {"families": len(fams), "series": series,
                "histograms": hists}

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        self._run_collectors()
        with self._lock:
            fams = list(self._families.values())
        out: List[str] = []
        for fam in fams:
            children = fam.children()
            if not children:
                continue
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for ch in children:
                if fam.kind == "histogram":
                    cum = 0
                    for bound, c in zip(ch.bounds, ch.counts):
                        cum += c
                        pairs = ch.labels + (("le", _fmt_value(bound)),)
                        out.append(f"{fam.name}_bucket"
                                   f"{_fmt_labels(pairs)} {cum}")
                    pairs = ch.labels + (("le", "+Inf"),)
                    out.append(f"{fam.name}_bucket{_fmt_labels(pairs)} "
                               f"{ch.count}")
                    out.append(f"{fam.name}_sum{_fmt_labels(ch.labels)} "
                               f"{_fmt_value(ch.sum)}")
                    out.append(f"{fam.name}_count"
                               f"{_fmt_labels(ch.labels)} {ch.count}")
                else:
                    out.append(f"{fam.name}{_fmt_labels(ch.labels)} "
                               f"{_fmt_value(ch.value)}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict:
        """JSON-ready snapshot of every family and series (the API
        ``stats()``-style callers consume programmatically)."""
        self._run_collectors()
        with self._lock:
            fams = list(self._families.values())
        snap: Dict[str, Dict] = {}
        for fam in fams:
            rows = []
            for ch in fam.children():
                labels = dict(ch.labels)
                if fam.kind == "histogram":
                    rows.append({
                        "labels": labels, "count": ch.count,
                        "sum": round(ch.sum, 9),
                        "buckets": {_fmt_value(b): c for b, c
                                    in zip(ch.bounds, ch.counts)},
                        "inf": ch.counts[-1],
                        "p50": round(ch.quantile(0.50), 9),
                        "p99": round(ch.quantile(0.99), 9),
                    })
                else:
                    rows.append({"labels": labels, "value": ch.value})
            snap[fam.name] = {"type": fam.kind, "help": fam.help,
                              "series": rows}
        return snap


# ---------------------------------------------------------------- tracing

TRACE_RING_CAP = 16384      # events; fixed memory, old spans fall off

# perf_counter origin shared by every event so cross-thread timestamps
# are comparable; exported as microseconds since this epoch
_EPOCH = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def now_us() -> float:
    """Microseconds since the trace epoch — the ``ts`` clock every ring
    event carries (the flight recorder windows the ring against it)."""
    return _now_us()


_tls = threading.local()


def current_trace_id() -> Optional[str]:
    """The innermost active span's trace id on THIS thread (for log
    correlation — logger.py's JSON format stamps it on every line)."""
    stack = getattr(_tls, "trace_stack", None)
    return stack[-1] if stack else None


class _NullSpan:
    """Shared no-op span: telemetry off, or tracing not wanted here."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kv):
        return self


NULL_SPAN = _NullSpan()


class _Span:
    """Context-manager span: records one Chrome "X" (complete) event at
    exit. Pushes its trace id on the thread-local stack so nested spans
    and log lines inherit it."""

    __slots__ = ("tracer", "name", "trace_id", "track", "args", "_t0")

    def __init__(self, tracer, name, trace_id, track, args):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.track = track
        self.args = args
        self._t0 = 0.0

    def annotate(self, **kv):
        self.args.update(kv)
        return self

    def __enter__(self):
        self._t0 = _now_us()
        stack = getattr(_tls, "trace_stack", None)
        if stack is None:
            stack = _tls.trace_stack = []
        stack.append(self.trace_id)
        return self

    def __exit__(self, etype, evalue, tb):
        stack = getattr(_tls, "trace_stack", None)
        if stack:
            stack.pop()
        if etype is not None:
            self.args.setdefault("error", f"{etype.__name__}: {evalue}")
        t1 = _now_us()
        self.tracer._emit(self.name, "X", self._t0, t1 - self._t0,
                          self.trace_id, self.track, self.args)
        return False


class Tracer:
    """Bounded-ring trace recorder. Events are plain dicts in Chrome
    trace-event shape: ``ph`` "X" (complete, with ``dur``) or "i"
    (instant). ``pid`` is a logical track ("replica0", "train",
    "router"); ``tid`` the OS thread id; ``args`` always carries
    ``trace_id`` when the event belongs to a request."""

    def __init__(self, cap: int = TRACE_RING_CAP):
        self._lock = locks.make_lock("telemetry-tracer")
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._open: Dict[int, Dict] = {}    # begin() handles awaiting end()
        self._next_handle = 0

    # ---- recording ------------------------------------------------------

    def _emit(self, name, ph, ts, dur, trace_id, track, args):
        ev = {"name": name, "ph": ph, "ts": round(ts, 1),
              "pid": track or "proc",
              "tid": threading.get_ident() & 0xffff}
        if ph == "X":
            ev["dur"] = round(dur, 1)
        a = dict(args) if args else {}
        if trace_id is not None:
            a["trace_id"] = trace_id
        if a:
            ev["args"] = a
        with self._lock:
            self._ring.append(ev)
        return ev

    def span(self, name: str, trace_id: Optional[str] = None,
             track: Optional[str] = None, **args):
        """Context-manager span (same-thread begin/end). Returns the
        shared no-op span when telemetry is off."""
        if not _enabled:
            return NULL_SPAN
        if trace_id is None:
            trace_id = current_trace_id()
        return _Span(self, name, trace_id, track, args)

    def begin(self, name: str, trace_id: Optional[str] = None,
              track: Optional[str] = None, **args) -> int:
        """Explicit span open for lifecycles that cross threads (a
        request decodes on a different thread than it was submitted
        from). Returns a handle for ``end()``; handle 0 = telemetry was
        off (end() ignores it)."""
        if not _enabled:
            return 0
        with self._lock:
            self._next_handle += 1
            h = self._next_handle
            self._open[h] = {"name": name, "t0": _now_us(),
                             "trace_id": trace_id, "track": track,
                             "args": dict(args)}
            # fixed memory even when spans are abandoned (a fenced
            # replica never end()s its open decode spans): drop the
            # oldest open record past the cap — its end() becomes a
            # no-op, exactly like a span that fell off the ring
            while len(self._open) > 8192:
                self._open.pop(next(iter(self._open)))
        return h

    def end(self, handle: int, track: Optional[str] = None, **args):
        """Close a ``begin()`` span; extra args merge in (the retire
        state, the token count). Unknown/zero handles are ignored —
        telemetry may have been off, or the ring may have been reset
        mid-request. A span closed while telemetry is OFF is dropped
        without emitting (the off contract: the ring stops growing —
        a request straddling the toggle loses its span, deliberately)."""
        if not handle:
            return
        with self._lock:
            rec = self._open.pop(handle, None)
        if rec is None or not _enabled:
            return
        rec["args"].update(args)
        self._emit(rec["name"], "X", rec["t0"], _now_us() - rec["t0"],
                   rec["trace_id"], track or rec["track"], rec["args"])

    def complete(self, name: str, t0_s: float, dur_s: float,
                 trace_id: Optional[str] = None,
                 track: Optional[str] = None, **args):
        """Record a span retrospectively from perf_counter() instants —
        for phases measured anyway (fit's host_wait/h2d/dispatch) where
        a live span would double the clock reads."""
        if not _enabled:
            return
        self._emit(name, "X", (t0_s - _EPOCH) * 1e6, dur_s * 1e6,
                   trace_id, track, args)

    def instant(self, name: str, trace_id: Optional[str] = None,
                track: Optional[str] = None, **args):
        """Zero-duration annotation (fault landed, replica fenced,
        checkpoint published, watchdog fired)."""
        if not _enabled:
            return
        if trace_id is None:
            trace_id = current_trace_id()
        self._emit(name, "i", _now_us(), 0.0, trace_id, track, args)

    # ---- query ----------------------------------------------------------

    def events(self, name: Optional[str] = None,
               trace_id: Optional[str] = None) -> List[Dict]:
        """Ring contents (oldest first), optionally filtered."""
        with self._lock:
            evs = list(self._ring)
        if name is not None:
            evs = [e for e in evs if e["name"] == name]
        if trace_id is not None:
            evs = [e for e in evs
                   if e.get("args", {}).get("trace_id") == trace_id]
        return evs

    def trace_ids(self) -> List[str]:
        """Distinct request trace ids present in the ring, oldest
        first."""
        seen: "collections.OrderedDict[str, None]" = collections.OrderedDict()
        for e in self.events():
            tid = e.get("args", {}).get("trace_id")
            if tid is not None:
                seen.setdefault(tid, None)
        return list(seen)

    def trace_tree(self, trace_id: str) -> Dict:
        """Everything the ring holds for one request, as a span tree
        summary: the root span (the widest), children sorted by start,
        the tracks (replicas/subsystems) it crossed, and the instant
        annotations (faults, resubmissions) that fired under it."""
        evs = self.events(trace_id=trace_id)
        spans = [e for e in evs if e["ph"] == "X"]
        marks = [e for e in evs if e["ph"] == "i"]
        spans.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        root = max(spans, key=lambda e: e.get("dur", 0.0), default=None)
        return {
            "trace_id": trace_id,
            "root": root,
            "spans": spans,
            "annotations": marks,
            "names": [e["name"] for e in spans],
            "tracks": sorted({e["pid"] for e in evs}),
            "complete": _tree_complete(root, spans),
        }

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._open.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)


def _tree_complete(root, spans) -> bool:
    """A request's span tree is COMPLETE when a root exists and every
    other span nests inside it (start within [root, root+dur] — end may
    trail by scheduler granularity; a span from a replica fenced
    mid-request still STARTED inside its request)."""
    if root is None:
        return False
    t0 = root["ts"]
    t1 = t0 + root.get("dur", 0.0)
    slack = 1.0  # us: perf_counter rounding at export
    return all(t0 - slack <= e["ts"] <= t1 + slack
               for e in spans if e is not root)


# ------------------------------------------------------------- process-wide

_registry = Registry()
_tracer = Tracer()
_lock = locks.make_lock("telemetry-server")


def registry() -> Registry:
    return _registry


def tracer() -> Tracer:
    return _tracer


def reset():
    """Fresh process-wide registry + tracer (tests). Collectors, series
    and cached histogram children registered against the OLD registry
    are dropped — live engines/routers created BEFORE a reset stop
    exporting (they hold handles into the old registry). Construct
    engines after reset, or don't reset mid-fleet."""
    global _registry, _tracer
    with _lock:
        _registry = Registry()
        _tracer = Tracer()


def trace_tree(trace_id: str) -> Dict:
    return _tracer.trace_tree(trace_id)


# ------------------------------------------------------------ fault marks


def annotate(name: str, trace_id: Optional[str] = None,
             track: Optional[str] = None, **args):
    """Instant annotation + a counter bump when it is a fault mark.
    runtime/faultinject.py calls this at every fired FF_FAULT event so
    the drill's trace shows exactly where the fault landed."""
    _tracer.instant(name, trace_id=trace_id, track=track, **args)
    if name == "fault":
        _registry.counter(
            "ff_fault_fired_total",
            "FF_FAULT injections fired, by kind and site",
            labels=("kind", "site")).labels(
                args.get("kind", "?"), args.get("site", "?")).inc()


def fault_events() -> List[Dict]:
    """Every FF_FAULT annotation currently in the trace ring (the
    router/disagg smoke assertion surface)."""
    return _tracer.events(name="fault")


# ------------------------------------------------------- chrome trace file


def export_chrome_trace(path: str, extra: Optional[List[Dict]] = None
                        ) -> int:
    """Write the trace ring as Chrome trace-event JSON (perfetto /
    chrome://tracing loadable). Returns the event count written."""
    evs = _tracer.events()
    if extra:
        evs = evs + list(extra)
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return len(evs)


# --------------------------------------------------------- scrape endpoint

_server = None
_server_thread = None


def start_http_server(port: int) -> int:
    """Serve ``/metrics`` (Prometheus text), ``/metrics.json`` (registry
    snapshot) and ``/trace.json`` (the ring, Chrome format) on a stdlib
    http.server daemon thread. Idempotent — one server per process; the
    ACTUAL bound port is returned (pass 0 for an ephemeral port).
    Loopback only: this is an operator scrape endpoint, not an API."""
    global _server, _server_thread
    import http.server

    with _lock:
        if _server is not None:
            return _server.server_address[1]

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                code = 200
                if self.path.startswith("/metrics.json"):
                    body = json.dumps(_registry.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = _registry.to_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/trace.json"):
                    body = json.dumps(
                        {"traceEvents": _tracer.events()}).encode()
                    ctype = "application/json"
                elif self.path.startswith("/healthz"):
                    # fleet health rollup (ok|degraded|breach with
                    # per-SLO reasons) from the lock-free probes —
                    # never compiles, never blocks behind a mid-tick
                    # replica (runtime/flightrec.py; deferred import:
                    # flightrec imports this module at top)
                    from flexflow_tpu.runtime import flightrec

                    roll = flightrec.health_rollup()
                    body = json.dumps(roll).encode()
                    ctype = "application/json"
                    # an alerting scraper keys on the status code: only
                    # a BREACH is load-shed-worthy; degraded still
                    # serves
                    code = 503 if roll["status"] == "breach" else 200
                elif self.path.startswith("/slo.json"):
                    from flexflow_tpu.runtime import flightrec

                    body = json.dumps(
                        flightrec.slo_monitor().describe()).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):    # stderr chatter is not telemetry
                pass

        _server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", int(port)), Handler)
        _server.daemon_threads = True
        _server_thread = threading.Thread(
            target=_server.serve_forever, daemon=True,
            name="ff-metrics-http")
        _server_thread.start()
        from flexflow_tpu.logger import fflogger

        fflogger.info("telemetry: /metrics on 127.0.0.1:%d",
                      _server.server_address[1])
        return _server.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _lock:
        if _server is None:
            return
        _server.shutdown()
        _server.server_close()
        _server = None
        _server_thread = None
