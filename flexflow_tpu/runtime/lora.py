"""Paged LoRA adapter pool — host-side allocator/LRU (ISSUE 14).

The device pool (ops/lora.py) is fixed geometry; this module is the
pure-host state machine that decides WHICH adapter lives in WHICH page
— the RadixPrefixCache discipline applied to adapters:

  * a REGISTRY of adapters (host-RAM weights, the fault-in source) that
    can be far larger than the device pool;
  * a page ALLOCATOR with per-page refcounts of the live slots applying
    the adapter: a referenced page is pinned (evicting it mid-decode
    would corrupt a tenant's stream);
  * refcount-0 pages stay RESIDENT (warm for the tenant's next request)
    until pool pressure evicts them LRU-first, exactly the trie's
    evict-at-zero rule;
  * ``checkout`` of a non-resident adapter FAULTS it in: the caller
    (ServingEngine) runs the one fixed-shape writer program with the
    registry payload; a full pool with every page pinned returns None
    and the request waits queued — the same head-of-line rule as KV
    pool pressure (progress is guaranteed: retirements release pages).

Pure host state, injectable-IO-free (the engine owns the device
writes), so the whole allocator is unit-testable without a model
(tests/test_tenancy.py)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from flexflow_tpu.runtime import locks


class _Resident:
    __slots__ = ("page", "ref", "last_use")

    def __init__(self, page: int):
        self.page = page
        self.ref = 0
        self.last_use = 0


class LoraAdapterPool:
    """Host allocator for ``pages`` usable adapter pages (device page 0
    is the reserved null adapter and never allocated here)."""

    def __init__(self, pages: int, rank: int, targets: List):
        if pages < 1:
            raise ValueError(f"adapter pool pages={pages}: must be >= 1")
        if rank < 1:
            raise ValueError(f"lora rank={rank}: must be >= 1")
        self.pages = int(pages)
        self.rank = int(rank)
        # The engine lock (rank 20) already serializes every caller;
        # the pool's own ranked lock (rank 40, nested inner to the
        # engine's) exists so multi-engine sharing stays safe and so
        # the sanitizer sees the engine->adapter-pool edge by name.
        self._lock = locks.make_rlock("adapter-pool")
        # op name -> (in_dim, out_dim): the fixed page geometry every
        # registered adapter must match
        self.geometry = {op.name: (op.in_dim, op.out_dim)
                         for op in targets}
        self.registry: Dict[str, Dict] = {}   # name -> {"payload","scale"}
        self.resident: Dict[str, _Resident] = {}
        self._free = list(range(self.pages, 0, -1))   # pages N..1
        self._tick = 0
        # counters (stats()/telemetry): lookups = checkouts, hits =
        # checkouts served without a device write, faults = pool writes
        # (first load AND every re-fault after an eviction), evictions =
        # resident ref-0 adapters displaced under pool pressure
        self.lookups = 0
        self.hits = 0
        self.faults = 0
        self.evictions = 0
        self._live_refs = 0

    # ---- registry -----------------------------------------------------------

    def register(self, name: str, weights: Dict, alpha: Optional[float]
                 = None) -> None:
        """Validate + store an adapter's host weights. ``weights`` maps
        target-op name -> {"a": (in, rank), "b": (rank, out)}; ops not
        named get a zero delta. ``alpha`` defaults to the rank (scale
        1.0); the applied scale is alpha / rank. Re-registering
        REPLACES the weights: a resident-but-unpinned device copy is
        dropped (its page frees — the next checkout re-faults the NEW
        weights), while a PINNED name (live slots decoding under it) is
        rejected, since swapping weights under a running request would
        corrupt its stream. The caller (ServingEngine.register_adapter)
        also flushes the adapter's prefix-cache namespace — cached KV
        was computed under the old weights."""
        with self._lock:
            if not name:
                raise ValueError("adapter name must be non-empty")
            res = self.resident.get(name)
            if res is not None:
                if res.ref > 0:
                    raise ValueError(
                        f"adapter {name!r} is pinned by {res.ref} live "
                        f"slot(s): re-registering would swap weights under "
                        f"a running request — drain its users first")
                # unpinned resident copy: drop it so the next checkout
                # faults the NEW weights (not counted as a pressure
                # eviction — that counter is a pool signal)
                del self.resident[name]
                self._free.append(res.page)
            if not isinstance(weights, dict) or not weights:
                raise ValueError(
                    f"adapter {name!r}: weights must be a non-empty dict of "
                    f"op name -> {{'a', 'b'}}")
            clean = {}
            for op_name, sub in weights.items():
                geo = self.geometry.get(op_name)
                if geo is None:
                    raise ValueError(
                        f"adapter {name!r} targets op {op_name!r}, which is "
                        f"not a LoRA-targeted Linear op (targets: "
                        f"{sorted(self.geometry)})")
                a = np.asarray(sub["a"], np.float32)
                b = np.asarray(sub["b"], np.float32)
                want_a = (geo[0], self.rank)
                want_b = (self.rank, geo[1])
                if a.shape != want_a or b.shape != want_b:
                    raise ValueError(
                        f"adapter {name!r} op {op_name!r}: a{a.shape}/"
                        f"b{b.shape} do not match the pool geometry "
                        f"a{want_a}/b{want_b} (rank is fixed per pool)")
                clean[op_name] = {"a": a, "b": b}
            scale = (float(alpha) if alpha is not None else float(self.rank)) \
                / float(self.rank)
            self.registry[name] = {"payload": clean, "scale": scale}

    # ---- checkout / release -------------------------------------------------

    def checkout(self, name: str):
        """Pin ``name`` into a page for one more live slot. Returns
        (page, payload_or_None): payload is None on a residency HIT
        (no device write needed) and the registry entry on a FAULT (the
        caller must run the writer before dispatching the slot). Returns
        None when the pool is full of pinned pages — the caller leaves
        the request queued (KV-pool-pressure semantics)."""
        with self._lock:
            ent = self.registry.get(name)
            if ent is None:
                raise KeyError(
                    f"adapter {name!r} is not registered "
                    f"(known: {sorted(self.registry)})")
            self._tick += 1
            self.lookups += 1
            res = self.resident.get(name)
            if res is not None:
                res.ref += 1
                res.last_use = self._tick
                self._live_refs += 1
                self.hits += 1
                return res.page, None
            page = self._allocate()
            if page is None:
                self.lookups -= 1   # an un-placeable checkout retries every
                #                     tick — it must not skew the hit rate
                return None
            res = _Resident(page)
            res.ref = 1
            res.last_use = self._tick
            self.resident[name] = res
            self._live_refs += 1
            self.faults += 1
            return page, ent

    def release(self, name: str) -> None:
        with self._lock:
            res = self.resident.get(name)
            if res is None or res.ref <= 0:
                raise AssertionError(
                    f"adapter refcount underflow on {name!r}")
            res.ref -= 1
            self._live_refs -= 1

    def _allocate(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        # LRU among refcount-0 residents; every page pinned -> None
        victim = None
        for name, res in self.resident.items():
            if res.ref == 0 and (victim is None
                                 or res.last_use < victim[1].last_use):
                victim = (name, res)
        if victim is None:
            return None
        del self.resident[victim[0]]
        self.evictions += 1
        return victim[1].page

    # ---- observability ------------------------------------------------------

    def lookup_page(self, name: str) -> Optional[int]:
        res = self.resident.get(name)
        return res.page if res is not None else None

    def live_refs(self) -> int:
        return self._live_refs

    def pages_in_use(self) -> int:
        """Pages pinned by live slots right now (ref > 0)."""
        return sum(1 for r in self.resident.values() if r.ref > 0)

    def stats(self) -> Dict:
        return {
            "adapter_pool_pages": self.pages,
            "adapters_registered": len(self.registry),
            "adapters_resident": len(self.resident),
            "adapter_pages_in_use": self.pages_in_use(),
            "adapter_pool_occupancy": round(
                len(self.resident) / max(1, self.pages), 4),
            "adapter_lookups": self.lookups,
            "adapter_hits": self.hits,
            "adapter_faults": self.faults,
            "adapter_evictions": self.evictions,
            "adapter_refs_live": self._live_refs,
        }
