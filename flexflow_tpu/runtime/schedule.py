"""Learning-rate schedules.

Net-new vs the reference (its SGD/Adam learning rate is a fixed scalar
for the whole run, src/runtime/optimizer.cc:93-358). Schedules are pure
functions of the traced step counter — they compile into the jitted
train step, so changing the schedule never adds a host->device transfer.

Each schedule maps step t (0-based int scalar, traced) -> multiplicative
scale on the optimizer's base lr. Compose with any optimizer:

    SGDOptimizer(lr=0.1, schedule=WarmupCosine(warmup_steps=100,
                                               total_steps=10_000))
"""

from __future__ import annotations

import jax.numpy as jnp


class Schedule:
    def __call__(self, t):
        raise NotImplementedError


class ConstantSchedule(Schedule):
    def __call__(self, t):
        return jnp.float32(1.0)


class _WarmupDecay(Schedule):
    """Linear warmup 0->1 over `warmup_steps`, then `_decay(frac)` from 1
    to `final_scale` as frac runs 0->1 at `total_steps` (held after)."""

    def __init__(self, warmup_steps: int, total_steps: int,
                 final_scale: float = 0.0):
        assert total_steps > warmup_steps >= 0, \
            f"need total_steps > warmup_steps >= 0, got " \
            f"{total_steps} / {warmup_steps}"
        self.warmup_steps = warmup_steps
        self.total_steps = total_steps
        self.final_scale = final_scale

    def _decay(self, frac):
        raise NotImplementedError

    def __call__(self, t):
        t = jnp.asarray(t, jnp.float32)
        warm = t / jnp.maximum(self.warmup_steps, 1)
        frac = (t - self.warmup_steps) / (self.total_steps - self.warmup_steps)
        frac = jnp.clip(frac, 0.0, 1.0)
        return jnp.where(t < self.warmup_steps, warm, self._decay(frac))


class WarmupCosine(_WarmupDecay):
    """Linear warmup, cosine decay to `final_scale`."""

    def _decay(self, frac):
        return self.final_scale + (1.0 - self.final_scale) \
            * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))


class WarmupLinear(_WarmupDecay):
    """Linear warmup, linear decay to `final_scale`."""

    def _decay(self, frac):
        return 1.0 + (self.final_scale - 1.0) * frac


class StepDecay(Schedule):
    """scale = gamma^(t // step_size) — the classic ResNet 0.1x drops."""

    def __init__(self, step_size: int, gamma: float = 0.1):
        assert step_size > 0
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, t):
        k = jnp.asarray(t, jnp.int32) // self.step_size
        return jnp.power(jnp.float32(self.gamma), k.astype(jnp.float32))


class ExponentialDecay(Schedule):
    """scale = gamma^t."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def __call__(self, t):
        return jnp.power(jnp.float32(self.gamma),
                         jnp.asarray(t, jnp.float32))


def resolve(schedule) -> Schedule:
    """None -> constant; a Schedule instance or any callable passes
    through. Rejects an uninstantiated class (a forgotten-parens
    `schedule=WarmupCosine` would otherwise fail deep inside jit
    tracing with an unrelated-looking message)."""
    if schedule is None:
        return ConstantSchedule()
    if isinstance(schedule, type):
        raise TypeError(
            f"schedule must be an instance, got the class {schedule.__name__}"
            f" — did you mean {schedule.__name__}(...)?")
    if callable(schedule):
        return schedule
    raise TypeError(f"schedule must be callable or None, got {schedule!r}")
