"""Profiling / tracing.

Reference observability (SURVEY §5.1): per-op cudaEvent timing behind
--profiling (linear.cu:526-553), simulator DOT export (--taskgraph), Legion
-lg:prof logs. TPU equivalents:

  * IN-SITU attribution: the executors trace every op under
    jax.named_scope(op.name), so each instruction of the PRODUCTION jitted
    program carries the op name in its HLO metadata — Perfetto spans from
    xla_trace attribute back to graph ops, and in_situ_op_summary reads the
    optimized program's per-op instruction breakdown without running
    anything unfused
  * profile_step: op-by-op eager execution with wall timers — the analog of
    the per-op printf path, for wall-clock per op at the price of fusion
  * xla_trace: jax.profiler context writing a Perfetto/TensorBoard trace dir
    (the -lg:prof analog; spans carry the named_scope op names)
  * export_taskgraph: the op graph + strategy as Graphviz DOT (the
    simulator's DotFile analog, simulator.h:78-131)
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List

import jax

from flexflow_tpu.runtime.executor import resolve_tied_params


def profile_step(model, batch: Dict, iters: int = 3) -> List[dict]:
    """Run the forward graph op-by-op (unfused) and time each op.
    Returns [{op, type, ms, output_shape}] sorted by cost."""
    from flexflow_tpu.ops.base import InputOp

    ex = model.executor
    sharded = ex.shard_batch(batch)
    input_ops = {op.name: op for op in model.ops if isinstance(op, InputOp)}
    vals = {}
    for name, op in input_ops.items():
        if name in sharded:
            vals[op.outputs[0]] = sharded[name]
    rows = []
    rng = jax.random.PRNGKey(0)
    for idx, op in enumerate(model.ops):
        if isinstance(op, InputOp):
            continue
        xs = [vals[t] for t in op.inputs]
        p = resolve_tied_params(model, model.params, op.name,
                                model.params.get(op.name, {}))
        op_rng = jax.random.fold_in(rng, idx) if op.needs_rng else None

        def run():
            if op.stateful:
                outs, _ = op.forward_stateful(
                    p, model.bn_state.get(op.name, {}), xs,
                    training=False, rng=op_rng)
            else:
                kwargs = {}
                if getattr(op, "wants_shard_ctx", False):
                    kwargs["shard_ctx"] = {
                        "mesh": ex.mesh,
                        "axis_map": ex._op_axis_maps.get(op.name, {}),
                        "sp_mode": getattr(model.config, "sp_mode", "ring")}
                outs = op.forward(p, xs, training=False, rng=op_rng, **kwargs)
            return outs

        outs = run()  # warmup/compile
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(iters):
            outs = run()
        jax.block_until_ready(outs)
        ms = (time.perf_counter() - t0) / iters * 1e3
        for i, t in enumerate(op.outputs):
            vals[t] = outs[i]
        rows.append({"op": op.name, "type": type(op).__name__, "ms": ms,
                     "output_shape": op.outputs[0].dims})
    rows.sort(key=lambda r: -r["ms"])
    return rows


def in_situ_op_summary(model, batch: Dict) -> List[dict]:
    """Per-op breakdown of the PRODUCTION train-step program: lowers and
    compiles the exact jitted step the training loop runs, then attributes
    every optimized-HLO instruction to its graph op via the named_scope
    metadata (`jvp(op)` = forward, `transpose(jvp(op))` = backward).
    Returns [{op, fwd_instructions, bwd_instructions}], heaviest first —
    the in-situ analog of the reference's --profiling per-op event timers
    (linear.cu:526-553), without de-fusing the program.

    Requires a compiled model with a train step (model.compile + loaders).
    """
    import re

    import jax as _jax

    step = model._train_step
    lowered = step.lower(model.params, model.opt_state, model.bn_state,
                         batch, _jax.random.PRNGKey(0))
    txt = lowered.compile().as_text()
    op_names = sorted((op.name for op in model.ops), key=len, reverse=True)
    fwd: Dict[str, int] = {}
    bwd: Dict[str, int] = {}
    for path in re.findall(r'op_name="([^"]+)"', txt):
        for name in op_names:
            if f"jvp({name})" in path or f"/{name}/" in path \
                    or path.endswith(f"/{name}"):
                side = bwd if "transpose(" in path else fwd
                side[name] = side.get(name, 0) + 1
                break
    rows = [{"op": n,
             "fwd_instructions": fwd.get(n, 0),
             "bwd_instructions": bwd.get(n, 0)}
            for n in {**fwd, **bwd}]
    rows.sort(key=lambda r: -(r["fwd_instructions"] + r["bwd_instructions"]))
    return rows


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Perfetto/TensorBoard trace of whatever runs inside the context."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def export_taskgraph(model, filename: str):
    """Op graph + strategies as Graphviz DOT (reference DotFile analog)."""
    from flexflow_tpu.ops.base import InputOp

    lines = ["digraph taskgraph {", "  rankdir=LR;"]
    for op in model.ops:
        am = {}
        if model.executor is not None:
            am = model.executor._op_axis_maps.get(op.name, {})
        label = f"{op.name}\\n{type(op).__name__}"
        used = {a: d for a, d in am.items() if d is not None}
        if used:
            label += f"\\n{used}"
        shape = "box" if isinstance(op, InputOp) else "ellipse"
        lines.append(f'  "{op.name}" [label="{label}", shape={shape}];')
    for op in model.ops:
        for t in op.inputs:
            if t.owner_op is not None:
                lines.append(f'  "{t.owner_op.name}" -> "{op.name}";')
    lines.append("}")
    with open(filename, "w") as f:
        f.write("\n".join(lines))
    return filename


def export_sim_taskgraph(model, filename: str, mesh_shape=None):
    """Simulated schedule as Graphviz DOT with per-task start/end times
    (reference: --taskgraph, the simulator's DotFile dump used at
    simulator.cc:496-545). Uses the model's resolved strategy (compile()
    first) and the C++ event-driven simulator's timeline."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import get_search_problem

    mesh_shape = mesh_shape or model.config.mesh_shape
    cost = CostModel(model, mesh_shape)
    prob = get_search_problem(model, cost, mesh_shape)
    strategy = {}
    if model.executor is not None:
        strategy = {name: am
                    for name, am in model.executor._op_axis_maps.items()}
    choices = prob.choices_for(strategy)
    # honor op placement: the strategy's device blocks shape the timeline
    places = {name: (min(pc.device_ids) if pc.device_ids else 0)
              for name, pc in model.config.strategies.items()}
    total, rows = prob.simulate_timeline(choices, places)

    lines = ["digraph sim_taskgraph {", "  rankdir=LR;",
             f'  label="simulated iteration: {total * 1e3:.3f} ms";']
    for r in rows:
        if r["kind"] == "compute":
            lines.append(
                f'  "{r["name"]}" [shape=ellipse, label="{r["name"]}\\n'
                f'[{r["start"] * 1e3:.3f}, {r["finish"] * 1e3:.3f}] ms"];')
        elif r["kind"] == "grad_sync":
            node = f'{r["name"]}_sync'
            lines.append(
                f'  "{node}" [shape=diamond, label="sync {r["name"]}\\n'
                f'[{r["start"] * 1e3:.3f}, {r["finish"] * 1e3:.3f}] ms"];')
            lines.append(f'  "{r["name"]}" -> "{node}" [style=dashed];')
    for r in rows:
        if r["kind"] == "comm":
            lines.append(
                f'  "{r["src"]}" -> "{r["dst"]}" [color=red, '
                f'label="[{r["start"] * 1e3:.3f}, '
                f'{r["finish"] * 1e3:.3f}] ms"];')
    comm_edges = {(r["src"], r["dst"]) for r in rows if r["kind"] == "comm"}
    for op in prob.ops:
        for t in op.inputs:
            if t.owner_op is not None and t.owner_op.name in prob.op_index:
                if (t.owner_op.name, op.name) not in comm_edges:
                    lines.append(f'  "{t.owner_op.name}" -> "{op.name}";')
    lines.append("}")
    with open(filename, "w") as f:
        f.write("\n".join(lines))
    return total, filename
