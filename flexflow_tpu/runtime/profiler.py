"""Profiling / tracing.

Reference observability (SURVEY §5.1): per-op cudaEvent timing behind
--profiling (linear.cu:526-553), simulator DOT export (--taskgraph), Legion
-lg:prof logs. TPU equivalents:

  * IN-SITU attribution: the executors trace every op under
    jax.named_scope(op.name), so each instruction of the PRODUCTION jitted
    program carries the op name in its HLO metadata — Perfetto spans from
    xla_trace attribute back to graph ops, and in_situ_op_summary reads the
    optimized program's per-op instruction breakdown without running
    anything unfused
  * profile_step: op-by-op eager execution with wall timers — the analog of
    the per-op printf path, for wall-clock per op at the price of fusion
  * xla_trace: jax.profiler context writing a Perfetto/TensorBoard trace dir
    (the -lg:prof analog; spans carry the named_scope op names)
  * export_taskgraph: the op graph + strategy as Graphviz DOT (the
    simulator's DotFile analog, simulator.h:78-131)
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import jax

from flexflow_tpu.runtime.executor import resolve_tied_params


def profile_step(model, batch: Dict, iters: int = 3) -> List[dict]:
    """Run the forward graph op-by-op (unfused) and time each op.
    Returns [{op, type, ms, output_shape}] sorted by cost."""
    from flexflow_tpu.ops.base import InputOp

    ex = model.executor
    sharded = ex.shard_batch(batch)
    input_ops = {op.name: op for op in model.ops if isinstance(op, InputOp)}
    vals = {}
    for name, op in input_ops.items():
        if name in sharded:
            vals[op.outputs[0]] = sharded[name]
    rows = []
    rng = jax.random.PRNGKey(0)
    for idx, op in enumerate(model.ops):
        if isinstance(op, InputOp):
            continue
        xs = [vals[t] for t in op.inputs]
        p = resolve_tied_params(model, model.params, op.name,
                                model.params.get(op.name, {}))
        op_rng = jax.random.fold_in(rng, idx) if op.needs_rng else None

        def run():
            if op.stateful:
                outs, _ = op.forward_stateful(
                    p, model.bn_state.get(op.name, {}), xs,
                    training=False, rng=op_rng)
            else:
                kwargs = {}
                if getattr(op, "wants_shard_ctx", False):
                    kwargs["shard_ctx"] = {
                        "mesh": ex.mesh,
                        "axis_map": ex._op_axis_maps.get(op.name, {}),
                        "sp_mode": getattr(model.config, "sp_mode", "ring")}
                outs = op.forward(p, xs, training=False, rng=op_rng, **kwargs)
            return outs

        outs = run()  # warmup/compile
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(iters):
            outs = run()
        jax.block_until_ready(outs)
        ms = (time.perf_counter() - t0) / iters * 1e3
        for i, t in enumerate(op.outputs):
            vals[t] = outs[i]
        rows.append({"op": op.name, "type": type(op).__name__, "ms": ms,
                     "output_shape": op.outputs[0].dims})
    rows.sort(key=lambda r: -r["ms"])
    return rows


def in_situ_op_summary(model, batch: Dict) -> List[dict]:
    """Per-op breakdown of the PRODUCTION train-step program: lowers and
    compiles the exact jitted step the training loop runs, then attributes
    every optimized-HLO instruction to its graph op via the named_scope
    metadata (`jvp(op)` = forward, `transpose(jvp(op))` = backward).
    Returns [{op, fwd_instructions, bwd_instructions}], heaviest first —
    the in-situ analog of the reference's --profiling per-op event timers
    (linear.cu:526-553), without de-fusing the program.

    Requires a compiled model with a train step (model.compile + loaders).
    """
    import re

    import jax as _jax

    step = model._train_step
    lowered = step.lower(model.params, model.opt_state, model.bn_state,
                         batch, _jax.random.PRNGKey(0))
    txt = lowered.compile().as_text()
    op_names = sorted((op.name for op in model.ops), key=len, reverse=True)
    fwd: Dict[str, int] = {}
    bwd: Dict[str, int] = {}
    for path in re.findall(r'op_name="([^"]+)"', txt):
        for name in op_names:
            if f"jvp({name})" in path or f"/{name}/" in path \
                    or path.endswith(f"/{name}"):
                side = bwd if "transpose(" in path else fwd
                side[name] = side.get(name, 0) + 1
                break
    rows = [{"op": n,
             "fwd_instructions": fwd.get(n, 0),
             "bwd_instructions": bwd.get(n, 0)}
            for n in {**fwd, **bwd}]
    rows.sort(key=lambda r: -(r["fwd_instructions"] + r["bwd_instructions"]))
    return rows


_COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                   "collective-permute", "all-to-all")
_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
                "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
                "u64": 8, "f64": 8, "c64": 8, "c128": 16}


def hlo_collective_stats(hlo_text: str) -> Dict[str, float]:
    """Count the collective instructions of an optimized-HLO dump and sum
    their output bytes — the static half of the compute/collective
    breakdown. Async pairs count once (the ``-start`` op; its ``-done``
    is the same transfer completing)."""
    import re

    count = 0
    nbytes = 0.0
    per_kind: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = re.search(r"=\s+(.*?)\s+(%?)("
                      + "|".join(_COLLECTIVE_OPS)
                      + r")(-start)?\(", line)
        if m is None or "-done(" in line:
            continue
        kind = m.group(3)
        count += 1
        per_kind[kind] = per_kind.get(kind, 0) + 1
        shapes = re.findall(r"([a-z]\d*\w*)\[([0-9,]*)\]", m.group(1))
        if m.group(4) and len(shapes) > 1:
            # async '-start' lowering: the tuple result carries the
            # operand alias buffers alongside the result — counting them
            # all would report ~2x the sync-lowered equivalent. The
            # RESULT is the last element.
            shapes = shapes[-1:]
        for dt, dims in shapes:
            b = _DTYPE_BYTES.get(dt)
            if b is None:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * b
    out: Dict[str, float] = {"collective_instructions": count,
                             "collective_bytes": nbytes}
    for kind, n in per_kind.items():
        out[f"collective_{kind.replace('-', '_')}"] = n
    return out


def step_phase_breakdown(model, batch: Optional[Dict] = None,
                         iters: int = 3) -> Dict[str, float]:
    """Per-step compute/collective/epilogue breakdown of the train step —
    the observability for the in-graph overlap work (ROADMAP item 4):

      * ``device_step_ms`` — measured wall of the full fused step, run
        through an UNDONATED re-jit of the production step body (the
        model's own params/opt-state are never consumed, so this is safe
        to call mid-training);
      * ``epilogue_ms`` / ``epilogue_fraction`` — measured wall of the
        optimizer update alone (zero gradients; elementwise update time
        is value-independent): the scan epilogue that bucketed grad sync
        + the ZeRO-1 sharded update shrink;
      * ``collective_instructions`` / ``collective_bytes`` (+ per-kind
        counts) — optimized-HLO collective ops of the PRODUCTION compiled
        program, so an overlap regression (all-reduce where a
        reduce-scatter should be) is visible without tracing;
      * ``grad_sync_overlapped`` — whether FFConfig.overlap_grad_sync was
        compiled in.

    Surfaced through ``FFModel.step_breakdown`` which merges the result
    into ``model.last_step_breakdown`` alongside fit()'s host-side
    numbers."""
    import jax.numpy as jnp

    ex = model.executor
    if getattr(ex, "jits_per_group", False):
        raise RuntimeError(
            "step_phase_breakdown needs the single-program executor "
            "(operator-placement strategies jit per sub-mesh group)")
    if model._train_step is None or model.optimizer is None:
        raise RuntimeError("compile() with an optimizer first")
    if batch is None:
        batch = model._current_batch or model._stage_batch()
    sharded = ex.shard_batch(batch)
    rng = jax.random.PRNGKey(0)

    # full step, re-jitted WITHOUT donation so the timing loop can feed
    # the same (still-live) arguments every iteration
    body = ex._train_step_body(model.optimizer, model.loss_type,
                               model.metric_types, model._loss_tensor)
    step = jax.jit(body)
    args = (model.params, model.opt_state, model.bn_state, sharded, rng)
    jax.block_until_ready(step(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(*args)
    jax.block_until_ready(out)
    device_step_ms = (time.perf_counter() - t0) / iters * 1e3

    # epilogue: the optimizer update alone (what the serial scan epilogue
    # pays after the last microbatch's backward)
    zeros_g = jax.tree_util.tree_map(jnp.zeros_like, model.params)
    upd = jax.jit(model.optimizer.update)
    jax.block_until_ready(upd(model.params, zeros_g, model.opt_state))
    t0 = time.perf_counter()
    for _ in range(iters):
        res = upd(model.params, zeros_g, model.opt_state)
    jax.block_until_ready(res)
    epilogue_ms = (time.perf_counter() - t0) / iters * 1e3

    rows: Dict[str, float] = {
        "device_step_ms": round(device_step_ms, 4),
        "epilogue_ms": round(epilogue_ms, 4),
        "compute_ms": round(max(device_step_ms - epilogue_ms, 0.0), 4),
        "epilogue_fraction": round(
            min(epilogue_ms / max(device_step_ms, 1e-9), 1.0), 4),
        "grad_sync_overlapped": bool(
            getattr(model.config, "overlap_grad_sync", False)),
    }
    try:
        txt = model._train_step.lower(*args).compile().as_text()
        rows.update(hlo_collective_stats(txt))
    except Exception:  # pragma: no cover — HLO text is best-effort
        rows.update({"collective_instructions": -1,
                     "collective_bytes": -1.0})
    return rows


@contextlib.contextmanager
def xla_trace(logdir: str):
    """Perfetto/TensorBoard trace of whatever runs inside the context."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def export_taskgraph(model, filename: str):
    """Op graph + strategies as Graphviz DOT (reference DotFile analog)."""
    from flexflow_tpu.ops.base import InputOp

    lines = ["digraph taskgraph {", "  rankdir=LR;"]
    for op in model.ops:
        am = {}
        if model.executor is not None:
            am = model.executor._op_axis_maps.get(op.name, {})
        label = f"{op.name}\\n{type(op).__name__}"
        used = {a: d for a, d in am.items() if d is not None}
        if used:
            label += f"\\n{used}"
        shape = "box" if isinstance(op, InputOp) else "ellipse"
        lines.append(f'  "{op.name}" [label="{label}", shape={shape}];')
    for op in model.ops:
        for t in op.inputs:
            if t.owner_op is not None:
                lines.append(f'  "{t.owner_op.name}" -> "{op.name}";')
    lines.append("}")
    with open(filename, "w") as f:
        f.write("\n".join(lines))
    return filename


def export_sim_taskgraph(model, filename: str, mesh_shape=None):
    """Simulated schedule as Graphviz DOT with per-task start/end times
    (reference: --taskgraph, the simulator's DotFile dump used at
    simulator.cc:496-545). Uses the model's resolved strategy (compile()
    first) and the C++ event-driven simulator's timeline."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.csim import get_search_problem

    mesh_shape = mesh_shape or model.config.mesh_shape
    cost = CostModel(model, mesh_shape)
    prob = get_search_problem(model, cost, mesh_shape)
    strategy = {}
    if model.executor is not None:
        strategy = {name: am
                    for name, am in model.executor._op_axis_maps.items()}
    choices = prob.choices_for(strategy)
    # honor op placement: the strategy's device blocks shape the timeline
    places = {name: (min(pc.device_ids) if pc.device_ids else 0)
              for name, pc in model.config.strategies.items()}
    total, rows = prob.simulate_timeline(choices, places)

    lines = ["digraph sim_taskgraph {", "  rankdir=LR;",
             f'  label="simulated iteration: {total * 1e3:.3f} ms";']
    for r in rows:
        if r["kind"] == "compute":
            lines.append(
                f'  "{r["name"]}" [shape=ellipse, label="{r["name"]}\\n'
                f'[{r["start"] * 1e3:.3f}, {r["finish"] * 1e3:.3f}] ms"];')
        elif r["kind"] == "grad_sync":
            node = f'{r["name"]}_sync'
            lines.append(
                f'  "{node}" [shape=diamond, label="sync {r["name"]}\\n'
                f'[{r["start"] * 1e3:.3f}, {r["finish"] * 1e3:.3f}] ms"];')
            lines.append(f'  "{r["name"]}" -> "{node}" [style=dashed];')
    for r in rows:
        if r["kind"] == "comm":
            lines.append(
                f'  "{r["src"]}" -> "{r["dst"]}" [color=red, '
                f'label="[{r["start"] * 1e3:.3f}, '
                f'{r["finish"] * 1e3:.3f}] ms"];')
    comm_edges = {(r["src"], r["dst"]) for r in rows if r["kind"] == "comm"}
    for op in prob.ops:
        for t in op.inputs:
            if t.owner_op is not None and t.owner_op.name in prob.op_index:
                if (t.owner_op.name, op.name) not in comm_edges:
                    lines.append(f'  "{t.owner_op.name}" -> "{op.name}";')
    lines.append("}")
    with open(filename, "w") as f:
        f.write("\n".join(lines))
    return total, filename
