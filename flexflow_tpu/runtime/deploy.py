"""Rolling deployment: weight-version registry + SLO-gated fleet roll.

The fleet (runtime/router.py) serves exactly the weights it was
constructed with; publishing a new checkpoint used to mean killing it.
This module closes the train-and-serve loop (ISSUE 17, ROADMAP open
item 1): a ``WeightArtifactRegistry`` watches the directory async
checkpointing (runtime/checkpoint.py) publishes manifest-verified
artifacts into, and a ``RollingDeployer`` rolls the fleet onto a new
version one replica at a time — the fleet never drops below N-1
capacity and in-flight requests are never dropped.

The per-replica swap sequence (docs/serving.md "Rolling deployment"):

  1. SUSPEND — the router stops dispatching new work to the replica;
     its driver keeps ticking, so in-flight work drains naturally (no
     fence, no resubmission).
  2. QUIESCE + DRAIN — wait until the router's outstanding ledger for
     the replica is empty, then ``engine.drain()`` (idempotent; the
     engine owes nothing at this point).
  3. SWAP — ``engine.swap_weights(tree, version)``: the weights install
     as a per-generator override (same geometry, so every warm
     fixed-shape program stays valid — ZERO retraces), quantized tiers
     re-quantize exactly once, and the drained prefix cache flushes
     (every page is refcount-0).
  4. REOPEN + RE-WARMUP — ``reopen()`` lifts the admission gate,
     ``warmup()`` re-runs the program set under the new weights and
     REBASELINES the replica's SLO windows (a warmup-inflated TTFT must
     never be judged a breach).
  5. RESUME — the router readmits the replica to dispatch and drops its
     stale old-version affinity entries.

The FIRST swapped replica is a CANARY: it serves live traffic under its
own rebaselined PR-15 SLO windows for ``deploy_canary_windows`` full
windows before any other replica is touched. A breach attributed to the
canary inside the soak triggers AUTOMATIC ROLLBACK — every swapped
replica swaps back to the prior version — plus a flight-recorder bundle
naming the offending SLO. A corrupt or torn artifact (manifest verify
fails) REFUSES the deploy before any replica is touched.

Two weight versions A/B-serve behind one router during the roll with
zero stale-KV hits: prefix-cache trie namespaces and router affinity
keys carry a weight-version salt (serving.version_ns — the ISSUE-14
``("ns", adapter)`` mechanism extended to ``(version, adapter)``).

Deterministic drills (FF_FAULT, runtime/faultinject.py):
``corrupt_ckpt@publish:<n>`` tears the n-th published artifact (the
registry verify must refuse it); ``swap_fail@deploy:<n>`` dies mid-swap
(the deploy rolls back); ``slow(<ms>)@canary:<n>`` stalls canary
admissions (the deterministic SLO breach).
"""

from __future__ import annotations

import os
import re
import time
from typing import Dict, List, Optional

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import checkpoint, faultinject, flightrec, locks
from flexflow_tpu.runtime.serving import DEFAULT_WEIGHT_VERSION

_VERSION_RE = re.compile(r"v(\d+)")


def _version_step(version: str) -> int:
    m = _VERSION_RE.fullmatch(str(version))
    if not m:
        raise ValueError(
            f"weight version {version!r}: registry versions are "
            f"'v<step>' (one per published checkpoint step)")
    return int(m.group(1))


class WeightArtifactRegistry:
    """Manifest-verified weight artifacts keyed by version, in one watch
    directory. The layout IS the checkpoint layout (``step_<N>`` dirs
    with ``ff_manifest.json``), so async checkpointing publishes into
    the watch path DIRECTLY — ``save_checkpoint(model, watch_dir,
    async_save=True)`` from a training loop makes version ``v<N>``
    appear here with no copy, no export step, and the same atomicity
    story (a kill mid-save can never tear an artifact; a torn one fails
    ``verify`` and the deployer refuses it)."""

    def __init__(self, watch_dir: str):
        if not watch_dir:
            raise ValueError(
                "WeightArtifactRegistry needs a watch directory "
                "(FFConfig.deploy_watch_dir or an explicit path)")
        self.watch_dir = os.path.abspath(watch_dir)

    # ---- discovery ----------------------------------------------------------

    def versions(self) -> List[str]:
        """Published versions, oldest first (published = the atomic
        rename landed; a mid-save tmp dir is not a version)."""
        return [f"v{s}"
                for s in sorted(checkpoint._step_dirs(self.watch_dir))]

    def latest(self) -> Optional[str]:
        vs = self.versions()
        return vs[-1] if vs else None

    def latest_intact(self) -> Optional[str]:
        """Newest version whose manifest verifies — what a deploy with
        no explicit version targets when the newest artifact is torn."""
        s = checkpoint.latest_intact_step(self.watch_dir)
        return None if s is None else f"v{s}"

    def step_dir(self, version: str) -> str:
        return os.path.join(self.watch_dir,
                            f"step_{_version_step(version)}")

    # ---- publish / verify / load --------------------------------------------

    def publish(self, model, step: Optional[int] = None,
                async_save: bool = False) -> str:
        """Publish the model's current weights as a new version (the
        serving-side convenience; a training loop pointed at the watch
        dir needs no registry at all). Returns the version string once
        the artifact is live.

        FF_FAULT=corrupt_ckpt@publish:<n> flips bytes in the n-th
        published artifact AFTER it lands — the torn-artifact drill the
        deployer's verify-first refusal exists for."""
        step = int(step if step is not None else model._step_count)
        version = f"v{step}"
        if version == DEFAULT_WEIGHT_VERSION:
            raise ValueError(
                f"cannot publish as {version!r}: that is the reserved "
                f"construction-weights version every engine starts on — "
                f"publish at step >= 1")
        checkpoint.save_checkpoint(model, self.watch_dir, step=step,
                                   async_save=async_save)
        if async_save:
            # publish() promises a LIVE artifact: quiesce the ordered
            # publisher (the save itself already overlapped the caller)
            checkpoint.wait_pending_saves(self.watch_dir)
        if faultinject.active_plan().fire("corrupt_ckpt", "publish"):
            checkpoint._inject_corruption(self.step_dir(version))
        return version

    def verify(self, version: str):
        """Recompute the artifact's manifest hashes; raises
        ``CheckpointCorruptError`` naming the first mismatching file.
        The deployer calls this BEFORE touching any replica."""
        checkpoint.verify_checkpoint(self.watch_dir,
                                     _version_step(version))

    def load_params(self, version: str):
        """The artifact's parameter tree as host arrays (the caller
        reshards onto its own mesh — artifacts are topology-free)."""
        restored = checkpoint._orbax_restore(self.step_dir(version))
        return restored["params"]


class RollingDeployer:
    """Drive a fleet roll through the router: verify, then per replica
    suspend -> quiesce -> drain -> swap -> warmup -> resume, with the
    first replica as the SLO-judged canary. Outcomes come back as a
    report dict (state ``completed`` | ``noop`` | ``refused`` |
    ``rolled_back`` | ``failed``) rather than exceptions — a refused or
    rolled-back deploy is a *result* the caller inspects, not a crash.

    One roll at a time per deployer (the "deploy" lock, outermost in
    the hierarchy: a roll step takes router and engine locks beneath
    it)."""

    def __init__(self, router, registry: Optional[WeightArtifactRegistry]
                 = None, canary_windows: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None):
        cfg = router.model.config
        if registry is None:
            registry = WeightArtifactRegistry(
                getattr(cfg, "deploy_watch_dir", "") or "")
        self.router = router
        self.registry = registry
        self.canary_windows = int(
            canary_windows if canary_windows is not None
            else getattr(cfg, "deploy_canary_windows", 2))
        self.drain_timeout_s = float(
            drain_timeout_s if drain_timeout_s is not None
            else getattr(cfg, "deploy_drain_timeout_s", 120.0))
        self._window_s = float(getattr(cfg, "slo_window_s", 10.0))
        self._lock = locks.make_lock("deploy")
        self.history: List[Dict] = []

    # ---- the roll -----------------------------------------------------------

    def deploy(self, version: Optional[str] = None, warmup_prompts=None,
               max_new_tokens: int = 4) -> Dict:
        """Roll every live replica onto ``version`` (default: the
        registry's newest artifact). ``warmup_prompts`` re-warm each
        swapped replica exactly like router.warmup (pass the same set);
        None skips the engine warmup but still rebaselines the SLO
        windows."""
        with self._lock:
            report = self._deploy_locked(version, warmup_prompts,
                                         max_new_tokens)
        self.history.append(report)
        del self.history[:-16]
        return report

    def _deploy_locked(self, version, warmup_prompts, max_new) -> Dict:
        r = self.router
        t0 = time.monotonic()
        if version is None:
            version = self.registry.latest()
            if version is None:
                raise ValueError(
                    f"deploy: no published versions in "
                    f"{self.registry.watch_dir}")
        prior = [eng.weight_version for eng in r.engines]
        report: Dict = {"state": "completed", "version": version,
                        "prior_versions": prior, "swapped": [],
                        "canary": None, "breach": None, "bundle": None,
                        "error": "", "rollback_s": 0.0}
        targets = [i for i in range(r.n) if not r._fenced[i]
                   and r.engines[i].weight_version != version]
        if not targets:
            report["state"] = "noop"
            report["duration_s"] = round(time.monotonic() - t0, 3)
            return report

        # 1. verify FIRST: a corrupt/torn artifact refuses the whole
        # deploy before any replica is touched
        try:
            self.registry.verify(version)
        except checkpoint.CheckpointCorruptError as e:
            report["state"] = "refused"
            report["error"] = str(e)
            report["duration_s"] = round(time.monotonic() - t0, 3)
            fflogger.error(
                "deploy: REFUSED %s — artifact failed manifest verify "
                "(%s); no replica was touched", version, e)
            return report

        # 2. load + reshard ONCE: every replica shares the model's mesh,
        # so one committed device tree serves all swaps (and the
        # recorded shardings keep warm pjit programs retrace-free)
        host = self.registry.load_params(version)
        tree = r.model.executor.reshard_params(host)

        r.set_deploying(True)
        # the report's list IS the working list: a rolled_back report
        # then names the replicas that were swapped (and rolled back)
        swapped: List[int] = report["swapped"]
        try:
            for n_done, i in enumerate(targets):
                try:
                    self._swap_one(i, tree, version, warmup_prompts,
                                   max_new)
                except Exception as e:  # noqa: BLE001 — swap_fail drill
                    #   or a real mid-swap death: the engine already
                    #   restored its prior weights; roll everything back
                    report["error"] = (f"swap on replica {i} failed: "
                                       f"{type(e).__name__}: {e}")
                    self._recover_replica(i)
                    self._rollback(swapped, prior, report,
                                   cause="swap_fail")
                    report["state"] = "rolled_back"
                    report["duration_s"] = round(
                        time.monotonic() - t0, 3)
                    return report
                swapped.append(i)
                r.note_swap()
                if n_done == 0 and self.canary_windows > 0:
                    report["canary"] = i
                    breach = self._canary_soak(i)
                    if breach is not None:
                        report["breach"] = breach
                        report["error"] = (
                            f"canary SLO breach: {breach['slo']} = "
                            f"{breach['value']} vs bound "
                            f"{breach['bound']}")
                        self._rollback(swapped, prior, report,
                                       cause="canary_rollback",
                                       breach=breach)
                        report["state"] = "rolled_back"
                        report["duration_s"] = round(
                            time.monotonic() - t0, 3)
                        return report
        finally:
            r.set_deploying(False)
        report["duration_s"] = round(time.monotonic() - t0, 3)
        fflogger.info(
            "deploy: fleet on %s (%d replicas swapped in %.2fs, canary "
            "replica %s held %d SLO window(s))", version, len(swapped),
            report["duration_s"], report["canary"], self.canary_windows)
        return report

    # ---- per-replica machinery ----------------------------------------------

    def _quiesce(self, i: int):
        r = self.router
        deadline = time.monotonic() + self.drain_timeout_s
        while not r.replica_quiesced(i):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {i} did not quiesce within "
                    f"{self.drain_timeout_s}s")
            time.sleep(0.003)

    def _swap_one(self, i: int, tree, version: str, warmup_prompts,
                  max_new: int):
        """One replica through the full sequence; raises on a torn swap
        (the caller rolls back). The fleet keeps serving on the other
        replicas the whole time — capacity never drops below N-1."""
        r = self.router
        eng = r.engines[i]
        r.suspend_replica(i)
        try:
            self._quiesce(i)
            eng.drain()
            eng.swap_weights(tree, version)
            eng.reopen()
            if warmup_prompts is not None:
                eng.warmup(warmup_prompts, max_new_tokens=max_new)
            else:
                flightrec.slo_monitor().rebaseline()
        finally:
            r.resume_replica(i)

    def _recover_replica(self, i: int):
        """After a failed swap: the engine restored its own prior
        weights; make sure it is admitting again."""
        eng = self.router.engines[i]
        try:
            eng.reopen()
        except Exception:  # noqa: BLE001 — best effort: the fence
            pass           #   machinery owns a truly dead replica

    def _canary_soak(self, i: int) -> Optional[Dict]:
        """Hold the roll while the freshly-swapped canary serves live
        traffic under its own rebaselined SLO windows. Returns the first
        breach attributed to the canary (rollback), or None after
        ``canary_windows`` clean full windows (proceed)."""
        eng = self.router.engines[i]
        label = eng._tm_labels["replica"]
        mon = flightrec.slo_monitor()
        eng.deploy_state = "canary"
        try:
            deadline = (time.monotonic()
                        + self.canary_windows * self._window_s)
            while time.monotonic() < deadline:
                mon.maybe_evaluate()
                hit = [b for b in mon.breaches()
                       if str(b.get("replica")) == label]
                if hit:
                    fflogger.error(
                        "deploy: canary replica %d breached %s "
                        "(%.4g vs bound %.4g) — rolling back", i,
                        hit[0]["slo"], hit[0]["value"], hit[0]["bound"])
                    return dict(hit[0])
                # deliberately under the deploy lock: serializing
                # concurrent deploy() calls across the whole roll —
                # soak included — IS the lock's contract; nothing on
                # the serving hot path ever takes "deploy" (rank 5,
                # outermost)
                time.sleep(min(0.02, self._window_s / 5))  # ffsan: allow(lock-across-blocking)
        finally:
            if eng.deploy_state == "canary":
                eng.deploy_state = "serving"
        return None

    def _rollback(self, swapped: List[int], prior: List[str],
                  report: Dict, cause: str,
                  breach: Optional[Dict] = None):
        """Swap every already-swapped replica back to its prior version
        (None override when the prior is the construction version), dump
        ONE flight-recorder bundle naming the cause (and the offending
        SLO for a canary breach), and stamp the breach->fleet-on-prior
        latency the bench reports."""
        r = self.router
        t0 = time.monotonic()
        for i in swapped:
            prev = prior[i]
            r.suspend_replica(i)
            try:
                self._quiesce(i)
                eng = r.engines[i]
                eng.drain()
                # prior == the construction version -> clear the
                # override (model.params); a prior REGISTRY version
                # reloads its artifact
                if prev == DEFAULT_WEIGHT_VERSION:
                    eng.swap_weights(None, prev)
                else:
                    host = self.registry.load_params(prev)
                    eng.swap_weights(
                        r.model.executor.reshard_params(host), prev)
                eng.reopen()
                flightrec.slo_monitor().rebaseline()
            except Exception as e:  # noqa: BLE001
                fflogger.error(
                    "deploy: rollback of replica %d to %s failed (%s) — "
                    "leaving it to the fence machinery", i, prev, e)
            finally:
                r.resume_replica(i)
        r.note_rollback()
        report["rollback_s"] = round(time.monotonic() - t0, 3)
        note = {"from_version": report["version"],
                "rolled_back_replicas": list(swapped),
                "rollback_s": report["rollback_s"]}
        if breach is not None:
            note["slo"] = breach["slo"]
            note["replica"] = breach["replica"]
            note["value"] = breach["value"]
            note["bound"] = breach["bound"]
        try:
            report["bundle"] = flightrec.dump(cause, **note)
        except Exception as e:  # noqa: BLE001 — no configured bundle
            #   dir: the rollback itself must not fail over evidence
            fflogger.warning(
                "deploy: rollback bundle not written (%s)", e)
        fflogger.warning(
            "deploy: ROLLED BACK %s -> prior versions (%s) in %.2fs%s",
            report["version"], cause, report["rollback_s"],
            f" — bundle {report['bundle']}" if report["bundle"] else "")
