"""Elastic recovery: resume a checkpointed run on a changed topology.

The paper's core position is that the parallelization strategy is a
*searchable artifact* of (graph, machine), not a fixed property of the job
— so losing a host of a preemptible pool must not end the run when a
perfectly good strategy exists for the surviving chips. PR 2's supervisor
could only resume onto the exact mesh it died on; this module makes the
restart topology-agnostic:

  * checkpoints already store per-step ``ff_meta.json`` (mesh shape,
    device/process count, batch size, grad-accum factor) and
    ``strategy.txt``; single-controller payloads are host numpy, so the
    bytes themselves are placement-free;
  * ``apply_elastic_policy(model)`` runs at the top of ``FFModel.compile``
    whenever ``checkpoint_dir`` is set: it compares the newest *intact*
    checkpoint's topology against what the restarting process actually
    has, and applies ``FFConfig.on_topology_change``:

      resume_resharded  refit the mesh to the surviving devices (candidate
                        factorizations over the saved axis names, ranked
                        by the search cost model under a re-partition of
                        the saved strategy — search.driver
                        .rank_mesh_candidates), re-derive the saved
                        strategy's axis maps on it, and preserve the
                        GLOBAL batch by scaling grad_accum_steps with the
                        data-degree change (optimizer trajectory stays
                        comparable at N-1 devices);
      research          same refit, then re-run the MCMC strategy search
                        at the new device count (the machine changed, so
                        the strategy is re-searched — the paper's thesis
                        applied to recovery);
      abort             raise TopologyChangedError.

  * the actual restore then rides the ordinary path: params/opt-state
    re-shard onto the new mesh in ``executor.reshard_params`` via
    ``restore_checkpoint`` — bitwise the saved values, new placement.

Deterministic drills (runtime/faultinject.py): ``shrink(<k>)@resume:<n>``
presents only k visible devices on the n-th resume
(``_env.force_cpu_devices`` in a fresh process, a capped count when the
backend is already up), and ``corrupt_ckpt@save:<n>`` flips payload bytes
after the n-th save publishes so the integrity-manifest fallback runs end
to end (``ci/run_ci.sh elastic``, tests/test_elastic.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from flexflow_tpu.logger import fflogger
from flexflow_tpu.runtime import faultinject


class TopologyChangedError(RuntimeError):
    """The resuming process's topology differs from the checkpoint's and
    the configured policy refuses to adapt (``on_topology_change="abort"``
    or fewer than ``elastic_min_devices`` survivors)."""


@dataclasses.dataclass
class ElasticDecision:
    """What the elastic policy did at compile time — stored on
    ``model._elastic`` for tests/telemetry and logged once."""

    policy: str
    step: int                       # checkpoint step the decision read
    saved_mesh: Dict[str, int]
    new_mesh: Dict[str, int]
    changed: bool                   # topology actually differed
    saved_grad_accum: int
    grad_accum: int                 # factor after global-batch preservation
    strategy_source: str            # "checkpoint" | "research" | "default"
    ranked_candidates: int = 0      # meshes scored during the refit

    @property
    def saved_devices(self) -> int:
        return _prod(self.saved_mesh)

    @property
    def devices(self) -> int:
        return _prod(self.new_mesh)


def _prod(shape: Dict[str, int]) -> int:
    n = 1
    for v in shape.values():
        n *= int(v)
    return n


def visible_device_count() -> int:
    """How many devices this process can actually use. Consumes a
    scheduled ``shrink(<k>)@resume`` fault first: in a fresh process
    ``force_cpu_devices`` genuinely shrinks the platform; with a live
    backend the count is capped instead, so in-process tests exercise the
    same policy arithmetic the real restart does."""
    import jax

    plan = faultinject.active_plan()
    if plan.fire("shrink", "resume"):
        k = plan.last_value
        if k:
            from flexflow_tpu._env import force_cpu_devices

            force_cpu_devices(int(k))
            n = len(jax.devices())
            fflogger.warning(
                "faultinject: shrink@resume — presenting %d of %d visible "
                "devices (FF_FAULT)", min(int(k), n), n)
            return min(int(k), n)
    return len(jax.devices())


def mesh_candidates(saved_mesh: Dict[str, int], num_devices: int,
                    cap: int = 64) -> List[Dict[str, int]]:
    """All factorizations of ``num_devices`` over the saved mesh's axis
    names (axis order preserved; size-1 axes kept so saved axis maps stay
    name-valid). The refit search space for a changed device count."""
    axes = [a for a in saved_mesh] or ["data"]
    out: List[Dict[str, int]] = []

    def rec(i: int, remaining: int, acc: Dict[str, int]):
        if len(out) >= cap:
            return
        if i == len(axes) - 1:
            out.append({**acc, axes[i]: remaining})
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                rec(i + 1, remaining // d, {**acc, axes[i]: d})
            d += 1

    rec(0, max(1, int(num_devices)), {})
    return out


def _saved_strategies(model, directory: str, step: int):
    """The checkpoint's strategy table (per-step ``strategy.txt``, falling
    back to the top-level mirror), or {} when unreadable."""
    from flexflow_tpu.parallel.strategy import load_strategies_from_file

    per_step = os.path.join(os.path.abspath(directory), f"step_{step}",
                            "strategy.txt")
    path = per_step if os.path.exists(per_step) \
        else os.path.join(os.path.abspath(directory), "strategy.txt")
    try:
        return load_strategies_from_file(path)
    except (FileNotFoundError, ValueError) as e:
        fflogger.warning("elastic: checkpoint strategy file unreadable "
                         "(%s) — resuming with default strategies", e)
        return {}


def _rederive_strategies(model, saved, new_mesh: Dict[str, int]):
    """Re-partition: each op keeps its saved axis map, restricted to the
    new mesh's axes, with degrees RE-DERIVED from the new axis sizes
    (``ParallelConfig.from_axis_map``) — the same names on a smaller mesh
    are the shrunk strategy. Ops whose map no longer divides cleanly fall
    back to default resolution, named in the log."""
    from flexflow_tpu.ops.base import InputOp
    from flexflow_tpu.parallel.pconfig import ParallelConfig

    out = {}
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = saved.get(op.name)
        am = getattr(pc, "axis_map", None) if pc is not None else None
        if not am:
            continue
        am = {ax: d for ax, d in am.items() if ax in new_mesh}
        try:
            out[op.name] = ParallelConfig.from_axis_map(
                op.outputs[0].num_dims, new_mesh, am)
        except Exception as e:
            fflogger.warning(
                "elastic: saved strategy for %r does not re-derive on "
                "mesh %s (%s) — using the default for this op",
                op.name, new_mesh, e)
    return out


def _preserve_global_batch(cfg, meta, saved_mesh: Dict[str, int],
                           new_mesh: Dict[str, int]) -> int:
    """Global-batch preservation: ``batch_size`` (the GLOBAL batch) stays
    what it was, and the per-device microbatch stays constant by scaling
    the grad-accum factor with the data-degree change —

        rows/device/microstep = B / (accum * d_data)
        accum' = accum_saved * d_old / d_new

    so the optimizer sees the same effective batch per update and the
    surviving devices see the same activation memory. Returns the new
    accum factor (cfg is updated); falls back with a warning when the
    ratio is not integral or B stops dividing."""
    saved_accum = int(meta.get("grad_accum_steps", 1))
    saved_bs = int(meta.get("batch_size", cfg.batch_size))
    if int(cfg.batch_size) != saved_bs:
        fflogger.warning(
            "elastic: config batch_size %d differs from the checkpoint's "
            "%d — the global batch is NOT preserved across this resume "
            "(explicit config change wins)", cfg.batch_size, saved_bs)
        return cfg.grad_accum_steps
    d_old = int(saved_mesh.get("data", 1))
    d_new = int(new_mesh.get("data", 1))
    num = saved_accum * d_old
    if num % d_new == 0 and cfg.batch_size % (num // d_new) == 0:
        new_accum = num // d_new
        if new_accum != cfg.grad_accum_steps:
            if d_old == d_new:
                # same data degree but the checkpoint's accum differs from
                # the config's: the saved factor may itself be the product
                # of an EARLIER elastic resume (8 devs -> 4 doubled it) —
                # adopt it, or the second restart would silently halve the
                # effective batch the trajectory was trained at
                fflogger.info(
                    "elastic: adopting the checkpoint's grad_accum_steps "
                    "%d over the config's %d (same data degree %d; the "
                    "saved factor keeps the optimizer trajectory "
                    "comparable)", new_accum, cfg.grad_accum_steps, d_new)
            else:
                fflogger.info(
                    "elastic: data degree %d -> %d; grad_accum_steps "
                    "%d -> %d keeps the global batch at %d with an "
                    "unchanged per-device microbatch", d_old, d_new,
                    cfg.grad_accum_steps, new_accum, cfg.batch_size)
            cfg.grad_accum_steps = new_accum
        return new_accum
    fflogger.warning(
        "elastic: cannot scale grad_accum_steps for data degree %d -> %d "
        "(saved accum %d, batch %d): ratio not integral — global batch is "
        "preserved but the per-device microbatch changes",
        d_old, d_new, saved_accum, cfg.batch_size)
    return cfg.grad_accum_steps


def apply_elastic_policy(model) -> Optional[ElasticDecision]:
    """Compile-time elastic hook (called from ``FFModel.compile`` before
    the mesh is built, whenever ``checkpoint_dir`` is set). Reads the
    newest intact checkpoint's recorded topology, compares it with what
    this process actually has, and mutates ``model.config`` (mesh shape,
    strategies, grad-accum) per ``on_topology_change``. Returns the
    decision record, or None when there is nothing to resume or nothing
    changed."""
    cfg = model.config
    directory = getattr(cfg, "checkpoint_dir", "")
    if not directory:
        return None
    from flexflow_tpu.runtime.checkpoint import (latest_intact_step,
                                                 load_meta)

    verify = bool(getattr(cfg, "verify_checkpoints", True))
    step = latest_intact_step(directory, verify=verify)
    if step is None:
        return None
    if verify:
        # the resume paths (supervisor.resume / auto_resume) skip
        # re-hashing the step this hook just verified — but the trust is
        # scoped to THIS directory (checkpoint.trusted_step_for): a
        # supervisor pointed somewhere else must re-verify
        model._elastic_verified_step = step
        model._elastic_verified_dir = os.path.abspath(directory)
    meta = load_meta(directory, step)
    saved_mesh = {k: int(v)
                  for k, v in (meta.get("mesh_shape") or {}).items()}
    if not saved_mesh:
        return None
    avail = visible_device_count()
    want = {k: int(v) for k, v in (cfg.mesh_shape or {}).items()}
    saved = None
    ranked_n = 0
    if _prod(want) <= avail:
        # the requested mesh is buildable: it stands, changed or not —
        # an explicit differently-shaped mesh is itself a topology change
        new_mesh = want
    else:
        # the requested mesh no longer fits (the classic restart: config
        # still says 8 devices, one host is gone): refit over the saved
        # axis names at the surviving count, cheapest candidate first
        saved = _saved_strategies(model, directory, step)
        from flexflow_tpu.search.driver import rank_mesh_candidates

        cands = mesh_candidates(saved_mesh, avail)
        ranked = rank_mesh_candidates(model, cands, strategies=saved)
        ranked_n = len(ranked)
        new_mesh = dict(ranked[0][1])
        fflogger.warning(
            "elastic: configured mesh %s needs %d devices but only %d are "
            "visible — refit to %s (best of %d csim-ranked candidates)",
            want, _prod(want), avail, new_mesh, ranked_n)
    changed = new_mesh != saved_mesh
    decision = ElasticDecision(
        policy=cfg.on_topology_change, step=step, saved_mesh=saved_mesh,
        new_mesh=dict(new_mesh), changed=changed,
        saved_grad_accum=int(meta.get("grad_accum_steps", 1)),
        grad_accum=cfg.grad_accum_steps, strategy_source="default",
        ranked_candidates=ranked_n)
    if not changed:
        # still apply the refit (the config asked for more devices than
        # exist) and keep the checkpoint's batch math: a run that already
        # resumed elastically once records its ADJUSTED grad-accum, which
        # the next same-topology restart must adopt, not reset
        cfg.mesh_shape = dict(new_mesh)
        cfg.num_devices = _prod(new_mesh)
        decision.grad_accum = _preserve_global_batch(cfg, meta, saved_mesh,
                                                     new_mesh)
        return decision
    if cfg.on_topology_change == "abort":
        raise TopologyChangedError(
            f"checkpoint at {directory} (step {step}) was saved on mesh "
            f"{saved_mesh} ({_prod(saved_mesh)} devices) but this process "
            f"has mesh {new_mesh} ({_prod(new_mesh)} devices) and "
            f"on_topology_change='abort' — re-provision the original "
            f"topology or set the policy to 'resume_resharded'")
    if _prod(new_mesh) < int(getattr(cfg, "elastic_min_devices", 1)):
        raise TopologyChangedError(
            f"elastic resume refused: {_prod(new_mesh)} surviving devices "
            f"< elastic_min_devices={cfg.elastic_min_devices} (checkpoint "
            f"was saved on {_prod(saved_mesh)})")
    if cfg.on_topology_change == "research":
        from flexflow_tpu.search.driver import research_strategies

        # warm-start the M-chip re-search from the N-chip strategy (ISSUE
        # 19d): the saved table seeds the anneal (and, with a cost DB
        # configured, its op measurements are already keyed on disk)
        if saved is None:
            saved = _saved_strategies(model, directory, step)
        cfg.strategies.update(research_strategies(model, new_mesh,
                                                  warm_start=saved))
        decision.strategy_source = "research"
    else:  # resume_resharded: re-derive the saved table on the new mesh
        if saved is None:
            saved = _saved_strategies(model, directory, step)
        rederived = _rederive_strategies(model, saved, new_mesh)
        if rederived:
            cfg.strategies.update(rederived)
            decision.strategy_source = "checkpoint"
    decision.grad_accum = _preserve_global_batch(cfg, meta, saved_mesh,
                                                 new_mesh)
    cfg.mesh_shape = dict(new_mesh)
    cfg.num_devices = _prod(new_mesh)
    fflogger.warning(
        "elastic: topology changed %s (%d devices) -> %s (%d devices); "
        "policy=%s, strategies=%s, grad_accum %d -> %d (global batch %d "
        "preserved)", saved_mesh, _prod(saved_mesh), new_mesh,
        _prod(new_mesh), decision.policy, decision.strategy_source,
        decision.saved_grad_accum, decision.grad_accum, cfg.batch_size)
    return decision
