// Native data loader: threaded shuffle + gather + prefetch.
//
// TPU re-design of the reference's native dataloader task system
// (python/flexflow_dataloader.{h,cc,cu}: full dataset resident in zero-copy
// memory, `next_batch` index launches copying per-shard sample slices). On
// TPU the device transfer is jax.device_put under the batch NamedSharding;
// what remains host-side — the shuffled per-sample gather into a contiguous
// batch buffer — is the part worth doing natively, overlapped with device
// compute via a ring of prefetch slots filled by worker threads.
//
// A loader owns a *group* of parallel arrays (input(s) + label) so one index
// permutation stays consistent across all of them, like the reference's
// SampleIdxs argmap shared by the input and label loaders
// (flexflow_dataloader.h:88-141).
//
// C ABI for ctypes (no pybind11 in this environment).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <numeric>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<std::vector<uint8_t>> buffers;  // one per array
  int64_t batch_index = -1;
  int64_t epoch = -1;
};

struct Loader {
  // dataset
  std::vector<const uint8_t*> data;      // base pointer per array
  std::vector<int64_t> sample_bytes;     // bytes per sample per array
  int64_t num_samples = 0;
  int64_t batch_size = 0;
  bool shuffle = false;
  std::mt19937_64 rng;

  // epoch state (guarded by mu; `order` is only mutated while no fill is in
  // flight — see reset())
  std::vector<int64_t> order;
  int64_t num_batches = 0;
  int64_t epoch = 0;

  // prefetch ring
  std::vector<Slot> slots;
  std::queue<int> free_slots;            // slots available for filling
  std::queue<int> ready_slots;           // filled slots (any order)
  int64_t next_fill = 0;                 // next batch index to assign a filler
  int64_t next_serve = 0;                // next batch index to hand to caller
  int in_flight = 0;                     // fills currently executing
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;

  void fill_slot(int slot_idx, int64_t batch_index) {
    Slot& s = slots[slot_idx];
    const int64_t start = batch_index * batch_size;
    for (size_t a = 0; a < data.size(); ++a) {
      const int64_t sb = sample_bytes[a];
      uint8_t* dst = s.buffers[a].data();
      for (int64_t i = 0; i < batch_size; ++i) {
        const int64_t src_idx = order[start + i];
        std::memcpy(dst + i * sb, data[a] + src_idx * sb, sb);
      }
    }
  }

  void worker() {
    for (;;) {
      int slot_idx;
      int64_t batch_index, fill_epoch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          return stop.load() ||
                 (!free_slots.empty() && next_fill < num_batches);
        });
        if (stop.load()) return;
        slot_idx = free_slots.front();
        free_slots.pop();
        batch_index = next_fill++;
        fill_epoch = epoch;
        ++in_flight;
      }
      fill_slot(slot_idx, batch_index);
      {
        std::lock_guard<std::mutex> lk(mu);
        slots[slot_idx].batch_index = batch_index;
        slots[slot_idx].epoch = fill_epoch;
        ready_slots.push(slot_idx);
        --in_flight;
      }
      cv_ready.notify_all();
    }
  }

  // Caller side: block until the slot holding batch `next_serve` of the
  // current epoch is ready. Batch indices are handed to workers
  // monotonically, but with >1 worker completion order can differ, so scan
  // the ready queue for the exact (epoch, batch) pair; slots from a previous
  // epoch (possible after a mid-epoch reset) are recycled.
  int next() {
    std::unique_lock<std::mutex> lk(mu);
    if (next_serve >= num_batches) return -1;
    for (;;) {
      size_t n = ready_slots.size();
      bool recycled = false;
      for (size_t i = 0; i < n; ++i) {
        int idx = ready_slots.front();
        ready_slots.pop();
        if (slots[idx].epoch != epoch) {  // stale: from before a reset
          free_slots.push(idx);
          recycled = true;
          continue;
        }
        if (slots[idx].batch_index == next_serve) {
          next_serve++;
          return idx;
        }
        ready_slots.push(idx);
      }
      if (recycled) cv_free.notify_all();
      size_t have = ready_slots.size();
      cv_ready.wait(lk, [&] { return ready_slots.size() > have || stop.load(); });
      if (stop.load()) return -1;
    }
  }

  void release(int slot_idx) {
    {
      std::lock_guard<std::mutex> lk(mu);
      slots[slot_idx].batch_index = -1;
      slots[slot_idx].epoch = -1;
      free_slots.push(slot_idx);
    }
    cv_free.notify_all();
  }

  void reset() {
    std::unique_lock<std::mutex> lk(mu);
    // stop handing out new fills, then wait for in-flight fills (they read
    // `order`) to drain before reshuffling
    next_fill = num_batches;
    cv_ready.wait(lk, [&] { return in_flight == 0 || stop.load(); });
    if (stop.load()) return;
    // recycle filled-but-unserved slots; their contents are stale
    while (!ready_slots.empty()) {
      free_slots.push(ready_slots.front());
      ready_slots.pop();
    }
    ++epoch;
    next_fill = 0;
    next_serve = 0;
    reshuffle();
    lk.unlock();
    cv_free.notify_all();
  }

  void reshuffle() {
    if (!shuffle) return;
    for (int64_t i = num_samples - 1; i > 0; --i) {
      std::uniform_int_distribution<int64_t> d(0, i);
      std::swap(order[i], order[d(rng)]);
    }
  }
};

}  // namespace

extern "C" {

void* ffdl_create(int num_arrays, const void** data_ptrs,
                  const int64_t* sample_bytes, int64_t num_samples,
                  int64_t batch_size, int shuffle, uint64_t seed,
                  int num_slots, int num_threads) {
  if (num_arrays <= 0 || num_samples <= 0 || batch_size <= 0 ||
      batch_size > num_samples)
    return nullptr;
  Loader* L = new Loader();
  for (int a = 0; a < num_arrays; ++a) {
    L->data.push_back(static_cast<const uint8_t*>(data_ptrs[a]));
    L->sample_bytes.push_back(sample_bytes[a]);
  }
  L->num_samples = num_samples;
  L->batch_size = batch_size;
  L->shuffle = shuffle != 0;
  L->rng.seed(seed);
  L->num_batches = num_samples / batch_size;
  L->order.resize(num_samples);
  std::iota(L->order.begin(), L->order.end(), 0);
  L->reshuffle();

  if (num_slots < 2) num_slots = 2;
  L->slots.resize(num_slots);
  for (int s = 0; s < num_slots; ++s) {
    for (int a = 0; a < num_arrays; ++a)
      L->slots[s].buffers.emplace_back(batch_size * sample_bytes[a]);
    L->free_slots.push(s);
  }
  if (num_threads < 1) num_threads = 1;
  for (int t = 0; t < num_threads; ++t)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int64_t ffdl_num_batches(void* handle) {
  return static_cast<Loader*>(handle)->num_batches;
}

// Blocks until the next batch (in order) is prefetched; returns slot id or -1
// at end of epoch.
int ffdl_next(void* handle) { return static_cast<Loader*>(handle)->next(); }

// Pointer to the gathered batch buffer for array `array_idx` in `slot`.
const void* ffdl_buffer(void* handle, int slot, int array_idx) {
  Loader* L = static_cast<Loader*>(handle);
  return L->slots[slot].buffers[array_idx].data();
}

void ffdl_release(void* handle, int slot) {
  static_cast<Loader*>(handle)->release(slot);
}

// New epoch: reshuffles (if enabled) and restarts prefetching from batch 0.
void ffdl_reset(void* handle) { static_cast<Loader*>(handle)->reset(); }

void ffdl_destroy(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
