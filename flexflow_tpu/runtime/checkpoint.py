"""Checkpoint / resume.

The reference has NO model checkpointing (SURVEY §5.4) — only strategy files
persist (strategy.cc) and weights can be moved via set/get_tensor. The TPU
build makes checkpointing first-class: orbax saves the sharded params /
optimizer state / batch-norm stats / step counter (each chip writes its own
shard — no host gather), and the strategy table is saved alongside in the
reference text schema so a resumed job re-shards identically.

Crash consistency (the preemption story, runtime/resilience.py): each save
lands in ``<dir>/.tmp-step_N`` and becomes ``<dir>/step_N`` via one
``os.replace`` — a kill mid-save leaves only an ignored tmp dir, never a
half-written checkpoint. ``ff_meta.json`` (step, layout guards, supervisor
extras: RNG key, dataloader cursors) is written INSIDE the step dir before
the rename, so a renamed checkpoint is always self-contained; the top-level
``meta.json``/``strategy.txt`` mirror the newest step for older readers.
``latest_step`` scans the ``step_*`` dirs (tmp dirs skipped), and orbax
save/load run under ``resilience.retry`` with ``io_fail`` fault-injection
hooks (FF_FAULT) so the retry path is tier-1-testable.

Integrity (the elastic-recovery story, runtime/elastic.py): every step dir
carries a content-hash manifest ``ff_manifest.json`` (relative path ->
sha256 + byte size over every other file in the dir), written INSIDE the
tmp dir before the publish rename so a published checkpoint always carries
its own proof. ``verify_step`` recomputes the hashes; resume paths
(``auto_resume``, ``TrainSupervisor.resume``, ``restore_checkpoint`` with
``step=None``) fall back to the newest *intact* step when the latest one
fails verification (torn write, bitrot, FF_FAULT ``corrupt_ckpt@save:<n>``
injection), and keep-K retention never deletes the last intact checkpoint
even when every newer step is corrupt.

Topology: single-controller checkpoints are host numpy, so a restore
re-shards onto whatever mesh the restoring model compiled with
(``executor.reshard_params``) — the checkpoint itself is topology-free and
a job killed on N devices resumes on N-1 (see runtime/elastic.py for the
policy and mesh-refit side).
"""

from __future__ import annotations

import collections
import hashlib
import json
import re
import os
import threading
from typing import List, Optional

import jax
import numpy as np

from flexflow_tpu.parallel.strategy import (load_strategies_from_file,
                                            save_strategies_to_file)
from flexflow_tpu.runtime import faultinject, locks
from flexflow_tpu.runtime.resilience import retry


MANIFEST_NAME = "ff_manifest.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint's payload no longer matches its content-hash manifest
    (torn write, bitrot, injected corruption). Resume paths catch this and
    fall back to the newest intact step."""


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


# ------------------------------------------------------ integrity manifest


def _manifest_files(step_dir: str):
    """Every regular file under `step_dir` except the manifest itself, as
    (relative posix path, absolute path) sorted for determinism."""
    out = []
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, step_dir).replace(os.sep, "/")
            if rel == MANIFEST_NAME:
                continue
            out.append((rel, full))
    out.sort()
    return out


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(step_dir: str) -> str:
    """Write the content-hash manifest for a (not yet published) step dir:
    ``{"algo": "sha256", "files": {relpath: {"sha256": ..., "bytes": n}}}``.
    Called inside the tmp dir BEFORE the publish rename, so every published
    checkpoint is born with its proof."""
    manifest = {"algo": "sha256", "files": {}}
    for rel, full in _manifest_files(step_dir):
        manifest["files"][rel] = {"sha256": _sha256(full),
                                  "bytes": os.path.getsize(full)}
    path = os.path.join(step_dir, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f)
    return path


def verify_dir_manifest(step_dir: str, label: Optional[str] = None,
                        require: bool = False):
    """Recompute the content-hash manifest of any published directory
    (checkpoint step dirs AND flight-recorder post-mortem bundles share
    this verifier) and raise ``CheckpointCorruptError`` naming the first
    mismatching file. Without a manifest: passes when ``require`` is
    False (pre-manifest checkpoints), raises when True (a bundle is
    born with its proof — a manifest-less one IS a torn write)."""
    label = label or step_dir
    mpath = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        if require:
            raise CheckpointCorruptError(
                f"{label}: no {MANIFEST_NAME} — torn or foreign write")
        return
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{label}: unreadable manifest {mpath}: {e}")
    files = manifest.get("files", {})
    present = {rel: full for rel, full in _manifest_files(step_dir)}
    missing = [rel for rel in files if rel not in present]
    if missing:
        raise CheckpointCorruptError(
            f"{label}: {len(missing)} manifest file(s) "
            f"missing, first {missing[0]!r}")
    for rel, rec in files.items():
        full = present[rel]
        if os.path.getsize(full) != rec.get("bytes"):
            raise CheckpointCorruptError(
                f"{label}: {rel!r} is "
                f"{os.path.getsize(full)} bytes, manifest records "
                f"{rec.get('bytes')}")
        if _sha256(full) != rec.get("sha256"):
            raise CheckpointCorruptError(
                f"{label}: content hash mismatch on {rel!r} "
                f"— payload corrupted after save")


def verify_checkpoint(directory: str, step: int):
    """Recompute the manifest hashes of ``step_<step>`` and raise
    ``CheckpointCorruptError`` naming the first mismatching file. A
    checkpoint predating the manifest layer (no ff_manifest.json) passes —
    there is nothing to verify it against, and refusing every pre-existing
    checkpoint would turn an upgrade into data loss."""
    step_dir = os.path.join(os.path.abspath(directory), f"step_{step}")
    verify_dir_manifest(step_dir, label=f"checkpoint step {step}")


def verify_step(directory: str, step: int) -> bool:
    """Boolean flavor of verify_checkpoint (plus meta readability) for
    scan loops; corruption details go through verify_checkpoint."""
    if not _meta_readable(directory, step):
        return False
    try:
        verify_checkpoint(directory, step)
        return True
    except CheckpointCorruptError:
        return False


def _meta_readable(directory: str, step: int) -> bool:
    """Is the step's metadata usable? A per-step ff_meta.json that exists
    but fails to parse marks a damaged dir; a dir with NO per-step meta is
    only usable through a readable top-level meta.json (pre-atomic-write
    layout)."""
    per_step = os.path.join(directory, f"step_{step}", "ff_meta.json")
    target = per_step if os.path.exists(per_step) \
        else os.path.join(directory, "meta.json")
    try:
        with open(target) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def _intact_with_warning(directory: str, step: int, verify: bool) -> bool:
    from flexflow_tpu.logger import fflogger

    if not _meta_readable(directory, step):
        fflogger.warning(
            "checkpoint step %d in %s: unreadable metadata — skipping "
            "(torn write or damaged dir)", step, directory)
        return False
    if verify:
        try:
            verify_checkpoint(directory, step)
        except CheckpointCorruptError as e:
            fflogger.warning(
                "checkpoint step %d in %s failed integrity "
                "verification — skipping: %s", step, directory, e)
            return False
    return True


def iter_intact_steps(directory: str, verify: bool = True, on_skip=None,
                      trusted_step: Optional[int] = None):
    """Lazily yield published checkpoint steps newest-first, skipping
    (with a warning, and an ``on_skip(step)`` callback for counters) any
    whose metadata is unreadable or — when `verify` — whose manifest
    fails verification. LAZY on purpose: verification hashes the full
    payload, so the resume paths (which stop at the first restorable
    step) pay one hash pass over one checkpoint, not K. ``trusted_step``
    names a step the caller already verified in this process (the
    compile-time elastic hook records one) — its payload is not hashed
    again, only its metadata re-checked."""
    directory = os.path.abspath(directory)
    for step in sorted(_step_dirs(directory), reverse=True):
        if _intact_with_warning(directory, step,
                                verify and step != trusted_step):
            yield step
        elif on_skip is not None:
            on_skip(step)


def trusted_step_for(model, directory: str) -> Optional[int]:
    """The step the compile-time elastic hook verified, or None — honored
    ONLY when ``directory`` is the one the hook actually hashed, so a
    resume pointed at a different directory never inherits the trust."""
    step = getattr(model, "_elastic_verified_step", None)
    if step is None:
        return None
    recorded = getattr(model, "_elastic_verified_dir", None)
    if recorded is not None and \
            os.path.abspath(recorded) != os.path.abspath(directory):
        return None
    return step


def has_checkpoints(directory: str) -> bool:
    """Any published step dir at all in `directory`, intact or not — the
    'is there evidence of prior training' test the resume paths use to
    distinguish a fresh start from a directory of damaged checkpoints."""
    return bool(_step_dirs(os.path.abspath(directory)))


def intact_steps(directory: str, verify: bool = True) -> List[int]:
    """Eager flavor of ``iter_intact_steps`` — the full fallback chain,
    for callers that genuinely need every intact step."""
    return list(iter_intact_steps(directory, verify=verify))


def latest_intact_step(directory: str, verify: bool = True) -> Optional[int]:
    return next(iter_intact_steps(directory, verify=verify), None)


def _inject_corruption(step_dir: str):
    """FF_FAULT ``corrupt_ckpt@save:<n>``: flip bytes in the middle of the
    step's largest payload file AFTER the publish rename — the
    deterministic stand-in for bitrot / a torn write that slipped past
    rename atomicity. The manifest is left intact so verification can
    catch the damage."""
    from flexflow_tpu.logger import fflogger

    skip = {MANIFEST_NAME, "ff_meta.json", "strategy.txt"}
    candidates = [(os.path.getsize(full), rel, full)
                  for rel, full in _manifest_files(step_dir)
                  if rel.split("/")[-1] not in skip
                  and os.path.getsize(full) > 0]
    if not candidates:  # nothing but metadata: corrupt the meta instead
        candidates = [(os.path.getsize(full), rel, full)
                      for rel, full in _manifest_files(step_dir)
                      if os.path.getsize(full) > 0]
    if not candidates:
        return
    size, rel, full = max(candidates)
    with open(full, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(8) or b"\x00"
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    fflogger.warning(
        "faultinject: corrupted checkpoint payload %s in %s (FF_FAULT "
        "corrupt_ckpt@save)", rel, step_dir)


def _opt_layout(model) -> str:
    """Optimizer-state pytree layout: the fused wrappers store state as
    flat per-dtype vectors, so a checkpoint written under one layout
    cannot restore into another (the tree structures differ). Recorded in
    meta.json; restore refuses a mismatch with a clear error instead of
    an opaque tree-structure failure."""
    from flexflow_tpu.runtime.optimizer import (FusedUpdate,
                                                ShardedFusedUpdate)

    opt = model.optimizer
    if isinstance(opt, ShardedFusedUpdate):
        return "sharded_fused"
    if isinstance(opt, FusedUpdate):
        return "fused"
    return "per_leaf"


def _sharded_fused_shardings(model):
    """The sharded-fused flat vector's element order is a pure function
    of (tree structure, leaf shardings, mesh) — record all three so a
    restore onto a DIFFERENT topology is refused instead of silently
    scrambling the moments (same per-dtype length, different
    (leaf, element) mapping)."""
    return {op: {w: str(spec) for w, spec in ws.items()}
            for op, ws in model.optimizer.specs.items()}


def _is_multihost() -> bool:
    return jax.process_count() > 1


def save_checkpoint(model, directory: str, step: Optional[int] = None,
                    extra_meta: Optional[dict] = None,
                    keep: Optional[int] = None,
                    async_save: bool = False) -> str:
    """Save model state. Returns the checkpoint path.

    Atomic: orbax writes into ``<directory>/.tmp-step_N``; meta + strategy
    land inside it; ONE ``os.replace`` publishes ``step_N``. A kill at any
    point leaves either the previous checkpoints intact plus a stale tmp
    dir (ignored by latest_step and cleaned on the next save of that
    step), or the complete new checkpoint — never a torn one.

    ``extra_meta`` merges into the per-step ``ff_meta.json`` (the
    supervisor records RNG key + dataloader cursors there); ``keep``
    prunes all but the newest ``keep`` step dirs after a successful
    publish.

    Single-controller: arrays are gathered to host numpy before writing, so
    checkpoints are topology-free — a restore re-shards onto whatever mesh
    the restoring model compiled with.

    ``async_save`` (FFConfig.async_checkpointing): the host snapshot is
    still taken on THIS thread before returning — the training loop
    donates param buffers to the next step, so the D2H copy cannot be
    deferred (leaf transfers are started asynchronously and collected
    once) — but everything after it (orbax serialization, manifest
    hashing, fsync, the publish rename, retention) runs on ONE background
    publisher thread, so ``checkpoint_every`` stops costing step time.
    Submissions publish strictly in order; ``wait_pending_saves``
    quiesces and re-raises the first failure; a publisher slower than the
    save cadence applies BACKPRESSURE (at most one snapshot queued behind
    the in-flight publish — the submit blocks rather than growing host
    memory without bound); the atomicity story is unchanged (a process
    exit mid-publish leaves a stale tmp dir, never a torn step).
    Single-controller only — multihost saves are collective and fall
    back to synchronous with a warning.

    Multi-controller (jax.process_count() > 1): arrays are handed to orbax
    as sharded jax.Arrays and EVERY process participates in the save — each
    host writes only its addressable shards (no host gather; a vocab-sharded
    embedding never materializes on one host). All processes must call this
    collectively; process 0 does the rename/prune between the barriers.
    Saving the same step twice overwrites (idempotent)."""
    directory = os.path.abspath(directory)
    step = int(step if step is not None else model._step_count)
    if _is_multihost():
        if async_save:
            from flexflow_tpu.logger import fflogger

            fflogger.warning(
                "async checkpointing is single-controller only (the "
                "multihost orbax save is collective) — saving step %d "
                "synchronously", step)
        return _save_multihost(model, directory, step, extra_meta, keep)

    state = _host_state(model)
    meta = _build_meta(model, step, with_opt="opt_state" in state,
                       multihost=False)
    if extra_meta:
        meta.update(extra_meta)
    strategies = dict(model.config.strategies)
    path = os.path.join(directory, f"step_{step}")
    if async_save:
        import functools

        # backpressure: each queued save holds a FULL host snapshot, so a
        # publisher slower than the save cadence must slow the caller
        # down (degrading toward a synchronous save), not grow host
        # memory without bound — at most one snapshot in flight plus the
        # one being submitted
        _SAVER.wait_below(directory, 1)
        _SAVER.submit(directory, step, functools.partial(
            _publish_step, directory, step, state, meta, strategies, keep))
        return path
    _publish_step(directory, step, state, meta, strategies, keep)
    return path


def _host_state(model) -> dict:
    """Snapshot params / optimizer state / bn stats to host numpy. Every
    leaf's D2H transfer is STARTED before the first blocking conversion,
    so the copies overlap instead of serializing leaf by leaf."""
    state = {"params": _strip_none(model.params)}
    if model.opt_state is not None:
        state["opt_state"] = _strip_none(model.opt_state)
    if model.bn_state:
        state["bn_state"] = _strip_none(model.bn_state)
    for leaf in jax.tree_util.tree_leaves(state):
        try:
            leaf.copy_to_host_async()
        except AttributeError:
            pass  # already host numpy / older array type
    return jax.tree_util.tree_map(lambda a: np.asarray(a), state)


def _build_meta(model, step: int, *, with_opt: bool,
                multihost: bool) -> dict:
    """Per-step ff_meta.json: topology + batch math recorded for elastic
    resume (runtime/elastic.py) — a restart on a different device count
    reads these to refit the mesh and preserve the global batch via
    grad-accum adjustment."""
    meta = {"step": int(step),
            "mesh_shape": model.config.mesh_shape,
            "num_devices": int(model.config.num_devices or 0),
            "process_count": jax.process_count(),
            "batch_size": int(model.config.batch_size),
            "grad_accum_steps": int(getattr(model.config,
                                            "grad_accum_steps", 1)),
            "multihost": multihost,
            "loss_type": model.loss_type.name if model.loss_type else None}
    if with_opt:  # layout only meaningful when state saved
        meta["opt_layout"] = _opt_layout(model)
        if meta["opt_layout"] == "sharded_fused":
            meta["opt_state_shardings"] = _sharded_fused_shardings(model)
    return meta


def _publish_step(directory: str, step: int, state: dict, meta: dict,
                  strategies: dict, keep: Optional[int]):
    """The write-and-publish half of a single-controller save: orbax the
    host state into the tmp dir (retried), then finalize. Runs on the
    caller's thread for a synchronous save, on the publisher thread for an
    async one — the inputs are already host-resident snapshots, so it
    never touches the model or the device."""
    import shutil

    tmp = os.path.join(directory, f".tmp-step_{step}")
    os.makedirs(directory, exist_ok=True)
    # only the TMP dir is cleared up front (orbax refuses to overwrite); a
    # pre-existing published step_N stays live until the new one is ready
    # — clearing it here would lose the checkpoint if the process dies
    # during the orbax write
    if os.path.exists(tmp):
        shutil.rmtree(tmp)

    def _save():
        faultinject.maybe_fail("io_fail", "save")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)  # half-written tmp from a failed attempt
        _checkpointer().save(tmp, state)

    retry(attempts=3, base_delay=0.05, retryable=(OSError,),
          name="orbax save")(_save)()
    _finalize_step_dir(directory, step, meta, strategies, keep)


def _save_multihost(model, directory: str, step: int,
                    extra_meta: Optional[dict], keep: Optional[int]) -> str:
    """Collective multi-controller save: orbax writes sharded jax.Arrays
    (each host only its addressable shards), process 0 finalizes between
    the two global barriers."""
    import shutil

    path = os.path.join(directory, f"step_{step}")
    tmp = os.path.join(directory, f".tmp-step_{step}")
    is_writer = jax.process_index() == 0
    if is_writer:
        os.makedirs(directory, exist_ok=True)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("ff_ckpt_clean")
    state = {"params": _strip_none(model.params)}
    if model.opt_state is not None:
        state["opt_state"] = _strip_none(model.opt_state)
    if model.bn_state:
        state["bn_state"] = _strip_none(model.bn_state)
    # the orbax save is COLLECTIVE: a per-host retry would re-enter it on
    # one process only (different op counts per host -> the job deadlocks
    # at orbax's internal syncs, or the writer rmtrees shards peers just
    # wrote). A failed collective save must be retried collectively by the
    # caller on every host.
    faultinject.maybe_fail("io_fail", "save")
    _checkpointer().save(tmp, state)
    if is_writer:
        meta = _build_meta(model, step, with_opt="opt_state" in state,
                           multihost=True)
        if extra_meta:
            meta.update(extra_meta)
        _finalize_step_dir(directory, step, meta,
                           dict(model.config.strategies), keep)
    multihost_utils.sync_global_devices("ff_ckpt_done")
    return path


def _finalize_step_dir(directory: str, step: int, meta: dict,
                       strategies: dict, keep: Optional[int]):
    """Meta + strategy + manifest into the tmp dir, the publish rename,
    the top-level mirrors, the corruption drill, and retention — shared by
    the sync, async, and multihost writer paths."""
    import shutil

    path = os.path.join(directory, f"step_{step}")
    tmp = os.path.join(directory, f".tmp-step_{step}")
    with open(os.path.join(tmp, "ff_meta.json"), "w") as f:
        json.dump(meta, f)
    save_strategies_to_file(os.path.join(tmp, "strategy.txt"), strategies)
    # the manifest is the LAST write into tmp: it covers every other
    # file (orbax payload, meta, strategy), so a published dir always
    # carries a complete proof of its own contents
    write_manifest(tmp)
    if os.path.exists(path):
        # same-step overwrite: the old dir must vanish for the rename
        # (os.replace cannot clobber a non-empty dir). The unprotected
        # window shrinks to this instant — the complete replacement is
        # already on disk in tmp, so a kill here leaves tmp salvageable
        # rather than nothing mid-write
        shutil.rmtree(path)
    os.replace(tmp, path)  # the publish point
    # top-level mirrors (older readers + import_strategy_file): written
    # atomically too, AFTER the step dir is live
    mtmp = os.path.join(directory, f".meta.json.tmp-{os.getpid()}")
    with open(mtmp, "w") as f:
        json.dump(meta, f)
    os.replace(mtmp, os.path.join(directory, "meta.json"))
    stmp = os.path.join(directory, f".strategy.txt.tmp-{os.getpid()}")
    save_strategies_to_file(stmp, strategies)
    os.replace(stmp, os.path.join(directory, "strategy.txt"))
    if faultinject.active_plan().fire("corrupt_ckpt", "save"):
        # deterministic bitrot drill: damage the JUST-PUBLISHED payload
        # (before retention runs, so the intact-preservation rule below
        # is what keeps an older recoverable step alive)
        _inject_corruption(path)
    if keep is not None and keep > 0:
        steps_sorted = sorted(_step_dirs(directory))
        doomed = steps_sorted[:-keep]

        # the step THIS call just wrote (and fully hashed in
        # write_manifest) is intact by construction — don't pay a
        # second hash pass on the save critical path. The exception is
        # the corruption drill, whose whole point is that the fresh
        # step may no longer match its manifest.
        drill = any(k == "corrupt_ckpt"
                    for k, _s, _i in faultinject.active_plan().events)

        def _survivor_intact(s: int) -> bool:
            if s == int(step) and not drill:
                return True
            return verify_step(directory, s)

        # newest-first so an intact newest survivor short-circuits
        if doomed and not any(_survivor_intact(s)
                              for s in reversed(steps_sorted[-keep:])):
            # every survivor is corrupt/unreadable: deleting the whole
            # tail would leave NO restorable checkpoint — spare the
            # newest intact one (retention resumes normally once an
            # intact step re-enters the survivor window)
            for s in reversed(doomed):
                if verify_step(directory, s):
                    doomed.remove(s)
                    from flexflow_tpu.logger import fflogger

                    fflogger.warning(
                        "checkpoint retention: every surviving step of "
                        "keep=%d fails verification — keeping intact "
                        "step %d beyond the retention window", keep, s)
                    break
        for old in doomed:
            shutil.rmtree(os.path.join(directory, f"step_{old}"),
                          ignore_errors=True)


# ------------------------------------------------------ async publisher


class _AsyncSaver:
    """ONE background publisher thread for async checkpointing: saves to
    any directory publish strictly in submission order (step N can never
    rename after step N+1), pending work is awaitable per directory, and
    the first failure is re-raised at the next wait — callers treat it
    exactly like a synchronous save failure. The thread is a daemon: a
    process exit mid-publish leaves only a stale tmp dir (the publish
    rename is atomic), never a torn checkpoint; callers that need the
    save DURABLE (supervisor preempt/final, rewind's intact scan) call
    ``wait_pending_saves`` first."""

    def __init__(self):
        self._cond = locks.make_condition("checkpoint-saver")
        self._queue: collections.deque = collections.deque()
        self._active: Optional[str] = None  # directory being published
        self._errors: List[tuple] = []
        self._thread: Optional[threading.Thread] = None

    def submit(self, directory: str, step: int, fn):
        with self._cond:
            self._queue.append((directory, step, fn))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="ff-ckpt-publisher", daemon=True)
                self._thread.start()
            self._cond.notify_all()

    def _run(self):
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                directory, step, fn = self._queue.popleft()
                self._active = directory
            try:
                fn()
            except BaseException as e:  # surfaced at the next wait()
                from flexflow_tpu.logger import fflogger

                fflogger.error(
                    "async checkpoint: publishing step %d in %s failed: "
                    "%s: %s", step, directory, type(e).__name__, e)
                # drop the traceback chain BEFORE retaining: its frames
                # reference the publish closure and with it the full
                # model host snapshot — a retained error must not pin
                # model-sized memory until someone waits on it
                e.__traceback__ = None
                with self._cond:
                    self._errors.append((directory, step, e))
            finally:
                with self._cond:
                    self._active = None
                    self._cond.notify_all()

    def _matches(self, d: Optional[str], directory: Optional[str]) -> bool:
        return directory is None or d == directory

    def pending(self, directory: Optional[str] = None) -> int:
        with self._cond:
            return self._pending_locked(directory)

    def wait_below(self, directory: Optional[str], n: int):
        """Block until fewer than ``n`` matching saves are queued or in
        flight — the submit-side backpressure primitive. Never raises:
        retained failures keep surfacing at wait()."""
        with self._cond:
            while self._pending_locked(directory) > n:
                self._cond.wait()

    def _pending_locked(self, directory: Optional[str]) -> int:
        n = sum(1 for d, _s, _f in self._queue
                if self._matches(d, directory))
        if self._active is not None and self._matches(self._active,
                                                      directory):
            n += 1
        return n

    def wait(self, directory: Optional[str] = None):
        with self._cond:
            while self._pending_locked(directory) > 0:
                self._cond.wait()
            errs = [e for e in self._errors
                    if self._matches(e[0], directory)]
            if errs:
                self._errors = [e for e in self._errors if e not in errs]
                if len(errs) > 1:
                    from flexflow_tpu.logger import fflogger

                    fflogger.warning(
                        "async checkpoint: %d further save failure(s) "
                        "consumed alongside the one re-raised (each was "
                        "logged at failure time)", len(errs) - 1)
                d, s, exc = errs[0]
                raise RuntimeError(
                    f"async checkpoint save of step {s} in {d} "
                    f"failed") from exc


_SAVER = _AsyncSaver()


def wait_pending_saves(directory: Optional[str] = None):
    """Quiesce async checkpointing: block until every pending async save
    (to ``directory``, or anywhere when None) has published, then
    re-raise the first failure among them. A no-op when nothing is
    pending — safe to call unconditionally before reading a checkpoint
    directory the training loop writes asynchronously."""
    _SAVER.wait(os.path.abspath(directory) if directory else None)


def pending_saves(directory: Optional[str] = None) -> int:
    """Number of async saves still queued or publishing."""
    return _SAVER.pending(os.path.abspath(directory) if directory else None)


def restore_checkpoint(model, directory: str, step: Optional[int] = None,
                       verify: Optional[bool] = None):
    """Restore into a compiled model. Single-controller checkpoints are
    stored as host numpy (see save_checkpoint), so restore re-shards onto
    the restoring model's own mesh regardless of the topology that saved
    them — including a DIFFERENT device count (the elastic path,
    runtime/elastic.py). Under multi-controller, every process calls this
    collectively and orbax restores each array directly into the model's
    current sharding (each host reads only its shards).

    ``verify`` (default: FFConfig.verify_checkpoints) recomputes the step's
    content-hash manifest first and raises ``CheckpointCorruptError`` on a
    mismatch; with ``step=None`` the newest INTACT step is chosen, so a
    corrupted latest falls back automatically. Even with ``verify=False``
    a restore that fails mid-read is re-checked against the manifest: if
    the step no longer verifies (damage or retention raced the caller's
    intact scan) the failure is reclassified as ``CheckpointCorruptError``
    so the resume fallback chains engage; a genuine error over an intact
    step propagates untouched."""
    directory = os.path.abspath(directory)
    if verify is None:
        verify = bool(getattr(model.config, "verify_checkpoints", True))
    if step is None:
        step = latest_intact_step(directory, verify=verify)
        if step is None:
            raise FileNotFoundError(
                f"no (intact) checkpoint found in {directory}")
    elif verify:
        verify_checkpoint(directory, step)
    try:
        return _restore_into(model, directory, step)
    except CheckpointCorruptError:
        raise
    except Exception as err:
        _reclassify_raced_damage(directory, step, err)
        raise


def _reclassify_raced_damage(directory: str, step: int, err: Exception):
    """A restore that failed AFTER the caller's intact scan may be raced
    damage (concurrent retention pruned the step, corruption landed after
    the hash pass) rather than a code bug. Re-check the step: a vanished
    dir, unreadable metadata, or a manifest that no longer verifies
    reclassifies the failure as ``CheckpointCorruptError`` — the exception
    the documented fallbacks (auto_resume, TrainSupervisor.resume) catch.
    An intact step means the error is real; return and let it propagate."""
    step_dir = os.path.join(directory, f"step_{step}")
    if not os.path.isdir(step_dir):
        raise CheckpointCorruptError(
            f"checkpoint step {step} disappeared mid-restore "
            f"({type(err).__name__}: {err})") from err
    if not _meta_readable(directory, step):
        raise CheckpointCorruptError(
            f"checkpoint step {step}: metadata became unreadable "
            f"mid-restore ({type(err).__name__}: {err})") from err
    try:
        verify_checkpoint(directory, step)
    except CheckpointCorruptError as ce:
        raise CheckpointCorruptError(
            f"{ce} (surfaced as {type(err).__name__} mid-restore)") from err


def _restore_into(model, directory: str, step: int) -> int:
    """Read + re-shard a chosen, published step into the model — the body
    of ``restore_checkpoint`` after step selection/verification, separated
    so the wrapper can reclassify raced-damage read failures."""
    meta = load_meta(directory, step)
    path = os.path.join(directory, f"step_{step}")

    # absent on pre-r5 and params-only checkpoints (no opt state to
    # mismatch — a weights-export -> fine-tune restore must not be blocked)
    saved_layout = meta.get("opt_layout")
    if saved_layout is not None and model.optimizer is not None:
        if saved_layout != _opt_layout(model):
            raise ValueError(
                f"checkpoint at {directory} stores optimizer state in the "
                f"{saved_layout!r} layout but this model uses "
                f"{_opt_layout(model)!r} (FFConfig.fused_optimizer and the "
                f"sharding strategy determine the layout). Re-compile with "
                f"a matching fused_optimizer setting to restore.")
        if saved_layout == "sharded_fused":
            # same layout kind is not enough: the flat vector's element
            # order depends on (mesh, leaf shardings) — a cross-topology
            # restore would silently scramble the moments
            saved_sh = meta.get("opt_state_shardings")
            cur_sh = _sharded_fused_shardings(model)
            # ordered compare: the flat layout follows mesh AXIS ORDER
            # (P(tuple(axis_names))), so {'data':2,'model':2} and
            # {'model':2,'data':2} are different layouts even though the
            # dicts compare equal (JSON preserves key order)
            mesh_saved = list((meta.get("mesh_shape") or {}).items())
            mesh_cur = list(model.config.mesh_shape.items())
            if (mesh_saved != mesh_cur
                    or (saved_sh is not None and saved_sh != cur_sh)):
                raise ValueError(
                    f"checkpoint at {directory} stores sharded-fused "
                    f"optimizer state for mesh {meta.get('mesh_shape')} "
                    f"with different parameter shardings — the flat state "
                    f"layout is topology-dependent. Re-compile with the "
                    f"saved mesh/strategy, or restore weights only "
                    f"(optimizer=None) and start the optimizer fresh.")

    if _is_multihost():
        import orbax.checkpoint as ocp

        template = {"params": model.params}
        if model.opt_state is not None:
            template["opt_state"] = _strip_none(model.opt_state)
        if model.bn_state:
            template["bn_state"] = model.bn_state
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        # no per-host retry around the COLLECTIVE restore (see _save):
        # one host re-entering it would desync the participants
        faultinject.maybe_fail("io_fail", "load")
        restored = _checkpointer().restore(path, restore_args=restore_args)
        model.params = restored["params"]
        if "opt_state" in restored and model.optimizer is not None:
            fresh = model.optimizer.init_state(model.params)
            model.opt_state = _merge_sharded(fresh, restored["opt_state"])
        if "bn_state" in restored:
            model.bn_state = restored["bn_state"]
        model._step_count = step
        return step

    # a checkpoint written by a multi-controller job stores SHARDED jax
    # arrays; deserializing those into a single-controller process needs
    # explicit numpy restore args (orbax refuses without a sharding) —
    # the N-hosts -> 1-host elastic resume path
    restored = (_orbax_restore_as_numpy(path) if meta.get("multihost")
                else _orbax_restore(path))
    # re-shard the host tree onto the CURRENT executor's placement — the
    # mesh the restoring process actually built, which need not match the
    # one that saved (executor.reshard_params; elastic resume rides this)
    model.params = model.executor.reshard_params(restored["params"])
    if "opt_state" in restored and model.optimizer is not None:
        fresh = model.optimizer.init_state(model.params)
        model.opt_state = _merge_restored(fresh, restored["opt_state"])
    if "bn_state" in restored:
        # ffsan: allow(uncommitted-device-put) — one-time restore
        # placement of replicated BN state, matching how init
        # placed it; the post-restore step compiles fresh anyway
        model.bn_state = {k: {n: jax.device_put(np.asarray(v))
                              for n, v in s.items()}
                          for k, s in restored["bn_state"].items()}
    model._step_count = step
    # NOTE: the checkpointed strategy file is NOT silently applied — sharding
    # was already resolved in compile(). To resume with the checkpointed
    # strategy, pass import_strategy_file=<dir>/strategy.txt in FFConfig
    # BEFORE compile(). We only warn on divergence here.
    try:
        per_step = os.path.join(path, "strategy.txt")
        saved = load_strategies_from_file(
            per_step if os.path.exists(per_step)
            else os.path.join(directory, "strategy.txt"))
        current = model.config.strategies
        def differs(a, b):
            if a.dims != b.dims:
                return True
            # dims alone miss CONTRACT/STAGE divergence (they shard
            # weights, not the output) — compare axis maps when both known
            if a.axis_map is not None and b.axis_map is not None:
                na = {k: v for k, v in a.axis_map.items() if v is not None}
                nb = {k: v for k, v in b.axis_map.items() if v is not None}
                return na != nb
            return False

        diff = [k for k in saved
                if k in current and differs(saved[k], current[k])]
        if diff:
            import sys

            print(f"[checkpoint] WARNING: strategy mismatch vs checkpoint for "
                  f"ops {diff[:5]}{'...' if len(diff) > 5 else ''}; set "
                  f"import_strategy_file before compile() to resume with the "
                  f"saved strategy", file=sys.stderr)
    except FileNotFoundError:
        pass
    return step


@retry(attempts=3, base_delay=0.05, retryable=(OSError,), name="orbax load")
def _orbax_restore(path, **kw):
    faultinject.maybe_fail("io_fail", "load")
    return _checkpointer().restore(path, **kw)


@retry(attempts=3, base_delay=0.05, retryable=(OSError,), name="orbax load")
def _orbax_restore_as_numpy(path):
    """Restore a multi-controller (sharded-array) checkpoint as plain host
    numpy: every leaf gets RestoreArgs(restore_type=np.ndarray), built
    from the checkpoint's own structure metadata. The full arrays
    materialize on this host — exactly what the cross-topology re-shard
    needs."""
    faultinject.maybe_fail("io_fail", "load")
    import orbax.checkpoint as ocp

    ckptr = _checkpointer()
    structure = ckptr.metadata(path)
    restore_args = jax.tree_util.tree_map(
        lambda _m: ocp.RestoreArgs(restore_type=np.ndarray), structure)
    return ckptr.restore(path, restore_args=restore_args)


def _step_dirs(directory: str):
    """Published checkpoint step numbers in `directory` (tmp dirs from an
    interrupted save are skipped — they never became checkpoints)."""
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    out = []
    for n in names:
        m = re.fullmatch(r"step_(\d+)", n)
        if m and os.path.isdir(os.path.join(directory, n)):
            out.append(int(m.group(1)))
    return out


def load_meta(directory: str, step: Optional[int] = None) -> dict:
    """Checkpoint metadata: the per-step ``step_N/ff_meta.json`` when
    present (self-contained checkpoints), else the top-level ``meta.json``
    (pre-atomic-write layout)."""
    directory = os.path.abspath(directory)
    if step is not None:
        per_step = os.path.join(directory, f"step_{step}", "ff_meta.json")
        if os.path.exists(per_step):
            with open(per_step) as f:
                return json.load(f)
    with open(os.path.join(directory, "meta.json")) as f:
        return json.load(f)


def latest_step(directory: str) -> Optional[int]:
    """Newest published checkpoint step in `directory` with READABLE
    metadata, or None. Scans the ``step_*`` dirs ONLY: trusting
    ``meta.json`` would return steps whose dir is gone (a kill inside the
    same-step overwrite window, retention pruning) and turn auto-resume
    into a restore-of-nothing crash loop — no dir means fresh start.
    ``.tmp-*`` leftovers from an interrupted save are ignored, and a dir
    whose ``ff_meta.json`` exists but no longer parses is skipped (a
    damaged dir used to raise mid-resume here) — payload verification is
    ``latest_intact_step``'s stricter job."""
    directory = os.path.abspath(directory)
    for step in sorted(_step_dirs(directory), reverse=True):
        if _meta_readable(directory, step):
            return step
    return None


def _strip_none(tree):
    if isinstance(tree, dict):
        return {k: _strip_none(v) for k, v in tree.items() if v is not None}
    return tree


def _merge_sharded(fresh, restored):
    """Refill None leaves stripped before a sharded save (restored arrays
    already carry the model's shardings via construct_restore_args)."""
    if isinstance(fresh, dict):
        return {k: _merge_sharded(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    return restored


def _merge_restored(fresh, restored):
    from jax.sharding import NamedSharding

    if isinstance(fresh, dict):
        return {k: _merge_restored(v, restored[k]) if k in restored else v
                for k, v in fresh.items()}
    if fresh is None:
        return None
    arr = np.asarray(restored).astype(np.asarray(fresh).dtype)
    sh = getattr(fresh, "sharding", None)
    if isinstance(sh, NamedSharding):
        return jax.device_put(arr, sh)
    # uncommitted: let jit place it alongside the mesh-sharded params
    import jax.numpy as jnp

    return jnp.asarray(arr)


def scan_and_restore(model, directory: str, *, restore, on_skip=None,
                     who: str = "auto_resume") -> Optional[int]:
    """The ONE newest-intact-first resume policy (``auto_resume`` and
    ``TrainSupervisor.resume`` both ride it): lazily scan intact steps
    (one payload hash per step actually examined, none for the step the
    compile-time elastic hook verified for this directory), call
    ``restore(step)`` on each candidate, fall back past raced
    mid-restore damage with a warning (and ``on_skip``), and return the
    restored step. Returns None when the directory holds no steps at
    all; raises CheckpointCorruptError when every existing step fails —
    silently starting fresh over damaged checkpoints would destroy the
    evidence."""
    from flexflow_tpu.logger import fflogger

    verify = bool(getattr(model.config, "verify_checkpoints", True))
    for step in iter_intact_steps(
            directory, verify=verify, on_skip=on_skip,
            trusted_step=trusted_step_for(model, directory)):
        try:
            restore(step)
            return step
        except CheckpointCorruptError as e:
            # raced corruption between the scan's hash pass and the
            # restore itself
            fflogger.warning(
                "%s: checkpoint step %d became unreadable mid-restore "
                "(%s); falling back to the next intact step", who, step, e)
            if on_skip is not None:
                on_skip(step)
    if _step_dirs(directory):
        raise CheckpointCorruptError(
            f"every checkpoint in {directory} fails metadata/manifest "
            f"verification — refusing to silently start fresh over "
            f"damaged checkpoints")
    return None


def auto_resume(model, directory: str) -> int:
    """Slice-preemption recovery (the capability gap SURVEY §5.3 notes in the
    reference: a failed node kills the job with no recovery). Call after
    compile(): restores the newest INTACT checkpoint in `directory` when
    one exists and returns its step; returns 0 on a fresh start (no step
    dirs at all). A corrupted/unreadable newer step is skipped with a
    warning instead of raising mid-resume; when every existing step fails
    verification the corruption error propagates — silently training from
    scratch on top of a directory full of damaged checkpoints would
    destroy the evidence."""
    def _restore(step):
        # the scan just verified this step — don't hash it again; raced
        # damage inside the restore itself still surfaces
        restore_checkpoint(model, directory, step=step, verify=False)

    step = scan_and_restore(model, directory, restore=_restore)
    return 0 if step is None else step
